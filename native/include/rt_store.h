/* rt_store — node-local shared-memory object store (C API).
 *
 * Capability analogue of the reference's plasma store
 * (reference: src/ray/object_manager/plasma/store.h:55 — node-local
 * immutable shared-memory objects; dlmalloc over mmap'd shm
 * plasma/dlmalloc.cc; refcount-aware eviction eviction_policy.h), built
 * TPU-host-native: one mmap'd POSIX shm arena per node, an in-shm
 * first-fit free-list allocator with coalescing, an open-addressing
 * object table, and a process-shared robust mutex so every worker
 * process on the host can create/seal/get objects with zero-copy reads.
 *
 * All offsets returned are relative to the arena base so each process
 * can resolve them against its own mapping.  Clients load this library
 * via ctypes (no pybind11 in the image) and mmap /dev/shm/<name>
 * themselves for the data plane.
 */
#ifndef RT_STORE_H
#define RT_STORE_H

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

#define RT_ID_SIZE 28  /* ObjectID width, matches ray_tpu.core.ids */

/* Error codes (negative returns). */
#define RT_OK 0
#define RT_ERR_EXISTS -1
#define RT_ERR_OOM -2
#define RT_ERR_NOT_FOUND -3
#define RT_ERR_NOT_SEALED -4
#define RT_ERR_IN_USE -5
#define RT_ERR_TABLE_FULL -6
#define RT_ERR_SYS -7

/* Object states (rt_obj_contains return values). */
#define RT_STATE_ABSENT 0
#define RT_STATE_CREATED 1
#define RT_STATE_SEALED 2

typedef struct rt_store rt_store; /* opaque per-process handle */

/* Create the arena (head/node service).  capacity = data heap bytes;
 * table_slots = object table capacity (power of two recommended).
 * Returns NULL on failure.  If the segment already exists, attaches. */
rt_store *rt_store_create(const char *name, uint64_t capacity,
                          uint32_t table_slots);

/* Attach to an existing arena (worker).  NULL if absent/invalid. */
rt_store *rt_store_attach(const char *name);

/* Unmap (does not destroy the segment). */
void rt_store_detach(rt_store *s);

/* Remove the shm segment from the system (after all detach). */
int rt_store_destroy(const char *name);

/* Total size of the mapping in bytes (mmap this much from the shm file). */
uint64_t rt_store_map_bytes(rt_store *s);

/* Allocate an object.  Returns data offset (>=0) or RT_ERR_*. */
int64_t rt_obj_create(rt_store *s, const uint8_t *id, uint64_t size);

/* Mark immutable; only sealed objects are gettable. */
int rt_obj_seal(rt_store *s, const uint8_t *id);

/* Get a sealed object: refcount++, returns offset, fills *size.
 * RT_ERR_NOT_FOUND / RT_ERR_NOT_SEALED otherwise. */
int64_t rt_obj_get(rt_store *s, const uint8_t *id, uint64_t *size_out);

/* Lookup without touching the refcount (node-side spill/inspection). */
int64_t rt_obj_lookup(rt_store *s, const uint8_t *id, uint64_t *size_out);

/* Drop one reference taken by rt_obj_get. */
int rt_obj_release(rt_store *s, const uint8_t *id);

/* Delete an object and free its block.  Fails with RT_ERR_IN_USE if the
 * refcount is nonzero (a process still holds a zero-copy view). */
int rt_obj_delete(rt_store *s, const uint8_t *id);

/* RT_STATE_* for the id. */
int rt_obj_contains(rt_store *s, const uint8_t *id);

uint64_t rt_obj_refcount(rt_store *s, const uint8_t *id);

/* LRU eviction candidates: sealed, refcount==0, oldest-access first,
 * until their sizes sum to >= nbytes.  Writes up to max_out ids into
 * out_ids (RT_ID_SIZE bytes each); returns the count. */
int rt_evict_candidates(rt_store *s, uint64_t nbytes, uint8_t *out_ids,
                        int max_out);

/* Stats. */
uint64_t rt_store_used(rt_store *s);
uint64_t rt_store_capacity(rt_store *s);
uint64_t rt_store_num_objects(rt_store *s);

#ifdef __cplusplus
}
#endif

#endif /* RT_STORE_H */
