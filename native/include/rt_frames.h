// Native dispatch-frame codec + MPSC ready-ring (C ABI, loaded from
// Python via ctypes — see ray_tpu/native/frames.py).
//
// Two halves:
//   * a zero-copy frame encoder/decoder for the control-plane wire
//     frames (tag 0x03; byte-identical to the pure-Python reference in
//     ray_tpu/core/rt_frames.py): length-prefixed framing, body
//     encoding, and the flight-recorder timestamp fold happen in ONE
//     call producing ONE buffer.  The Python-object adapter is only
//     compiled when Python.h is available (RTF_NO_PYTHON excludes it
//     for the pure-C++ unit tests).
//   * a lock-light multi-producer single-consumer byte ring used as a
//     send-combining buffer: producers reserve space with one atomic
//     fetch_add and commit with a release store; the consumer drains
//     every committed frame into one writev/sendall-sized buffer.
#pragma once

#include <stdint.h>
#include <stddef.h>

#ifdef __cplusplus
extern "C" {
#endif

// -- growable frame buffer (low-level writer; also used by the codec) --

typedef struct rtf_buf {
  uint8_t *data;
  uint64_t len;
  uint64_t cap;
} rtf_buf;

int rtf_buf_init(rtf_buf *b, uint64_t initial_cap);
void rtf_buf_free(rtf_buf *b);
int rtf_buf_put(rtf_buf *b, const void *src, uint64_t n);
int rtf_buf_put_u8(rtf_buf *b, uint8_t v);
int rtf_buf_put_u32(rtf_buf *b, uint32_t v);
int rtf_buf_put_u64(rtf_buf *b, uint64_t v);

// writer helpers mirroring the wire grammar (docs: rt_frames.py)
int rtf_w_none(rtf_buf *b);
int rtf_w_bool(rtf_buf *b, int v);
int rtf_w_i64(rtf_buf *b, int64_t v);
int rtf_w_f64(rtf_buf *b, double v);
int rtf_w_bytes(rtf_buf *b, const uint8_t *p, uint32_t n);
int rtf_w_str(rtf_buf *b, const char *s, uint32_t n);
int rtf_w_list(rtf_buf *b, uint32_t count);   // followed by count values
int rtf_w_tuple(rtf_buf *b, uint32_t count);
int rtf_w_map(rtf_buf *b, uint32_t count);    // followed by count (k,v)

// Validate one tagged payload (0x03 byte included): structure, bounds,
// nesting.  Returns 0 ok, negative error code otherwise.  This is the
// decode-side hardening a corrupted peer frame hits before any Python
// object is built.
int rtf_validate(const uint8_t *payload, uint64_t len);

// monotonic clock (CLOCK_MONOTONIC seconds) — the stamp source
double rtf_monotonic(void);

// -- MPSC ready-ring ---------------------------------------------------

typedef struct rtf_ring rtf_ring;

rtf_ring *rtf_ring_new(uint64_t capacity_bytes);
void rtf_ring_free(rtf_ring *r);
// Push one frame (or several pre-concatenated frames).  Returns 0 on
// success, -1 when the ring lacks space (caller falls back to its
// locked direct send).  Thread-safe for any number of producers.
int rtf_ring_push(rtf_ring *r, const uint8_t *data, uint64_t len);
// Drain every committed record into out (single consumer only).
// Returns bytes copied; stops early at the first record that does not
// fit in cap or is not yet committed.
uint64_t rtf_ring_drain(rtf_ring *r, uint8_t *out, uint64_t cap);
// Bytes currently reserved (committed or in flight) — cheap hint for
// "anything to flush?" checks.
uint64_t rtf_ring_pending(const rtf_ring *r);
uint64_t rtf_ring_capacity(const rtf_ring *r);
// Test/debug only: the raw slab, for asserting the zero-behind-tail
// invariant (every byte the consumer released must read 0, or a
// next-lap record start could expose stale bytes as a garbage header).
const uint8_t *rtf_ring_slab(const rtf_ring *r);

#ifdef __cplusplus
}  // extern "C"
#endif
