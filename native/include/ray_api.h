/* ray_api — native C++ worker API (capability analogue of the
 * reference's C++ frontend: cpp/include/ray/api.h — ray::Init,
 * ray::Put/Get, ray::Task(F).Remote(args...), actor handles — backed
 * by a runtime the way cpp/src/ray/runtime/local_mode_ray_runtime.cc
 * backs the reference's local mode: tasks execute on an in-process
 * executor pool and objects live in the REAL node shm store
 * (rt_store), so C++ tasks and Python workers share one object plane.
 *
 * Cross-process C++ workers (the reference's NativeRayRuntime) would
 * reuse this surface with a socket transport; the local-mode runtime
 * here is the first-class testable slice, as it is in the reference.
 *
 * Serialization: trivially-copyable types and std::string /
 * std::vector<trivially-copyable> round-trip through the object store;
 * anything else needs a Serializer<T> specialization. */
#ifndef RAY_API_H
#define RAY_API_H

#include <array>
#include <condition_variable>
#include <cstring>
#include <functional>
#include <future>
#include <memory>
#include <mutex>
#include <queue>
#include <stdexcept>
#include <string>
#include <thread>
#include <type_traits>
#include <vector>

#include "rt_store.h"

namespace ray {

using ObjectID = std::array<uint8_t, RT_ID_SIZE>;

/* ---------------- serialization ---------------- */

template <typename T, typename Enable = void>
struct Serializer;  // specialize for custom types

template <typename T>
struct Serializer<T,
    typename std::enable_if<std::is_trivially_copyable<T>::value>::type> {
  static std::vector<uint8_t> Dump(const T &v) {
    std::vector<uint8_t> out(sizeof(T));
    std::memcpy(out.data(), &v, sizeof(T));
    return out;
  }
  static T Load(const uint8_t *data, size_t n) {
    if (n != sizeof(T)) throw std::runtime_error("ray: size mismatch");
    T v;
    std::memcpy(&v, data, sizeof(T));
    return v;
  }
};

template <>
struct Serializer<std::string, void> {
  static std::vector<uint8_t> Dump(const std::string &v) {
    return std::vector<uint8_t>(v.begin(), v.end());
  }
  static std::string Load(const uint8_t *data, size_t n) {
    return std::string(reinterpret_cast<const char *>(data), n);
  }
};

template <typename E>
struct Serializer<std::vector<E>,
    typename std::enable_if<std::is_trivially_copyable<E>::value>::type> {
  static std::vector<uint8_t> Dump(const std::vector<E> &v) {
    std::vector<uint8_t> out(v.size() * sizeof(E));
    if (!v.empty()) std::memcpy(out.data(), v.data(), out.size());
    return out;
  }
  static std::vector<E> Load(const uint8_t *data, size_t n) {
    std::vector<E> v(n / sizeof(E));
    if (n) std::memcpy(v.data(), data, n);
    return v;
  }
};

/* ---------------- runtime ---------------- */

class Runtime {
 public:
  static Runtime &Instance();
  void Init(const std::string &store_name = "", uint64_t capacity = 0);
  void Shutdown();
  bool Initialized() const { return store_ != nullptr; }

  ObjectID PutBytes(const std::vector<uint8_t> &data);
  std::vector<uint8_t> GetBytes(const ObjectID &id, double timeout_s);

  /* submit: runs fn on the executor pool; the result bytes are sealed
   * into the store under the returned id when the task finishes. */
  ObjectID Submit(std::function<std::vector<uint8_t>()> fn);

  rt_store *store() { return store_; }

 private:
  Runtime() = default;
  void Worker();
  ObjectID NextId();
  void StoreResult(const ObjectID &id, const std::vector<uint8_t> &data);

  rt_store *store_ = nullptr;
  std::string store_name_;
  bool owns_store_ = false;
  uint8_t *base_ = nullptr;   /* mmap of the shm data plane */
  uint64_t map_bytes_ = 0;

  std::mutex mu_;
  std::condition_variable cv_;
  std::queue<std::function<void()>> queue_;
  std::vector<std::thread> workers_;
  bool stopping_ = false;
  uint64_t counter_ = 0;
  /* ids whose task errored: Get throws instead of blocking forever */
  std::mutex err_mu_;
  std::vector<std::pair<ObjectID, std::string>> errors_;
 public:
  void RecordError(const ObjectID &id, const std::string &what);
  bool FindError(const ObjectID &id, std::string *out);
};

inline void Init() { Runtime::Instance().Init(); }
inline void Init(const std::string &store_name, uint64_t capacity) {
  Runtime::Instance().Init(store_name, capacity);
}
inline void Shutdown() { Runtime::Instance().Shutdown(); }
inline bool IsInitialized() { return Runtime::Instance().Initialized(); }

/* ---------------- ObjectRef / Put / Get ---------------- */

template <typename T>
class ObjectRef {
 public:
  ObjectRef() = default;
  explicit ObjectRef(const ObjectID &id) : id_(id) {}
  const ObjectID &ID() const { return id_; }
  T Get(double timeout_s = 60.0) const {
    auto bytes = Runtime::Instance().GetBytes(id_, timeout_s);
    return Serializer<T>::Load(bytes.data(), bytes.size());
  }

 private:
  ObjectID id_{};
};

template <typename T>
ObjectRef<T> Put(const T &value) {
  auto id = Runtime::Instance().PutBytes(Serializer<T>::Dump(value));
  return ObjectRef<T>(id);
}

template <typename T>
T Get(const ObjectRef<T> &ref, double timeout_s = 60.0) {
  return ref.Get(timeout_s);
}

/* ---------------- Task(...).Remote(...) ---------------- */

template <typename F, typename... Args>
class TaskCaller {
 public:
  TaskCaller(F fn, std::tuple<Args...> args)
      : fn_(fn), args_(std::move(args)) {}

  using R = decltype(std::apply(std::declval<F>(),
                                std::declval<std::tuple<Args...>>()));

  ObjectRef<R> Remote() {
    F fn = fn_;
    auto args = args_;
    auto id = Runtime::Instance().Submit(
        [fn, args]() -> std::vector<uint8_t> {
          R result = std::apply(fn, args);
          return Serializer<R>::Dump(result);
        });
    return ObjectRef<R>(id);
  }

 private:
  F fn_;
  std::tuple<Args...> args_;
};

/* ray::Task(f, a, b).Remote() — args are bound at Task() (the
 * reference binds them at Remote(); only this spelling is supported
 * here). */
template <typename F, typename... Args>
TaskCaller<F, Args...> Task(F fn, Args... args) {
  return TaskCaller<F, Args...>(fn, std::make_tuple(args...));
}

/* ---------------- actors ---------------- */

template <typename C>
class ActorHandle {
 public:
  explicit ActorHandle(std::shared_ptr<C> inst,
                       std::shared_ptr<std::mutex> mu)
      : inst_(std::move(inst)), mu_(std::move(mu)) {}

  /* handle.Task(&C::Method, args...).Remote() */
  template <typename R, typename... MArgs, typename... CallArgs>
  ObjectRef<R> Call(R (C::*method)(MArgs...), CallArgs... args) {
    auto inst = inst_;
    auto mu = mu_;
    auto tup = std::make_tuple(args...);
    auto id = Runtime::Instance().Submit(
        [inst, mu, method, tup]() -> std::vector<uint8_t> {
          /* per-actor mutex: method calls serialize, matching actor
           * semantics (one logical thread per actor) */
          std::lock_guard<std::mutex> lk(*mu);
          R result = std::apply(
              [&](auto... a) { return ((*inst).*method)(a...); }, tup);
          return Serializer<R>::Dump(result);
        });
    return ObjectRef<R>(id);
  }

 private:
  std::shared_ptr<C> inst_;
  std::shared_ptr<std::mutex> mu_;
};

template <typename C, typename... Args>
class ActorCreator {
 public:
  explicit ActorCreator(std::tuple<Args...> args)
      : args_(std::move(args)) {}
  ActorHandle<C> Remote() {
    auto inst = std::apply(
        [](auto... a) { return std::make_shared<C>(a...); }, args_);
    return ActorHandle<C>(inst, std::make_shared<std::mutex>());
  }

 private:
  std::tuple<Args...> args_;
};

template <typename C, typename... Args>
ActorCreator<C, Args...> Actor(Args... args) {
  return ActorCreator<C, Args...>(std::make_tuple(args...));
}

}  // namespace ray

#endif  /* RAY_API_H */
