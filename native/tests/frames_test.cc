// Unit tests for the frame codec core + MPSC ready-ring (no Python:
// built with -DRTF_NO_PYTHON; the PyObject adapter is covered from
// Python by tests/test_rt_frames.py's fuzz parity suite).
//
// Build/run:  make -C native frames_test
// TSAN:       make -C native frames_tsan

#include "rt_frames.h"

#include <atomic>
#include <cstdio>
#include <cstring>
#include <string>
#include <thread>
#include <vector>

static int failures = 0;

#define CHECK(cond)                                                     \
  do {                                                                  \
    if (!(cond)) {                                                      \
      std::fprintf(stderr, "FAIL %s:%d: %s\n", __FILE__, __LINE__,      \
                   #cond);                                              \
      failures++;                                                       \
    }                                                                   \
  } while (0)

// -- grammar writers + validator ---------------------------------------

static void test_codec_roundtrip_shape() {
  rtf_buf b;
  CHECK(rtf_buf_init(&b, 16) == 0);
  // payload for {"t": "task_done", "task_id": b"\x01\x02", "error":
  //              None, "n": 7, "x": 1.5, "fr": [("submit", 0.25)],
  //              "flags": (True, False)}
  CHECK(rtf_buf_put_u8(&b, 0x03) == 0);
  CHECK(rtf_w_map(&b, 7) == 0);
  CHECK(rtf_w_str(&b, "t", 1) == 0);
  CHECK(rtf_w_str(&b, "task_done", 9) == 0);
  CHECK(rtf_w_str(&b, "task_id", 7) == 0);
  const uint8_t tid[2] = {1, 2};
  CHECK(rtf_w_bytes(&b, tid, 2) == 0);
  CHECK(rtf_w_str(&b, "error", 5) == 0);
  CHECK(rtf_w_none(&b) == 0);
  CHECK(rtf_w_str(&b, "n", 1) == 0);
  CHECK(rtf_w_i64(&b, 7) == 0);
  CHECK(rtf_w_str(&b, "x", 1) == 0);
  CHECK(rtf_w_f64(&b, 1.5) == 0);
  CHECK(rtf_w_str(&b, "fr", 2) == 0);
  CHECK(rtf_w_list(&b, 1) == 0);
  CHECK(rtf_w_tuple(&b, 2) == 0);
  CHECK(rtf_w_str(&b, "submit", 6) == 0);
  CHECK(rtf_w_f64(&b, 0.25) == 0);
  CHECK(rtf_w_str(&b, "flags", 5) == 0);
  CHECK(rtf_w_tuple(&b, 2) == 0);
  CHECK(rtf_w_bool(&b, 1) == 0);
  CHECK(rtf_w_bool(&b, 0) == 0);
  CHECK(rtf_validate(b.data, b.len) == 0);

  // every truncation of a valid frame must be rejected, never read OOB
  for (uint64_t cut = 0; cut < b.len; cut++)
    CHECK(rtf_validate(b.data, cut) != 0);
  // flipped tag byte -> not an rt-frames payload
  b.data[0] = 0x00;
  CHECK(rtf_validate(b.data, b.len) != 0);
  b.data[0] = 0x03;
  // non-map top level
  const uint8_t not_map[2] = {0x03, 'N'};
  CHECK(rtf_validate(not_map, 2) != 0);
  // map key with a non-key tag
  rtf_buf bad;
  CHECK(rtf_buf_init(&bad, 16) == 0);
  CHECK(rtf_buf_put_u8(&bad, 0x03) == 0);
  CHECK(rtf_w_map(&bad, 1) == 0);
  CHECK(rtf_w_i64(&bad, 3) == 0);
  CHECK(rtf_w_none(&bad) == 0);
  CHECK(rtf_validate(bad.data, bad.len) != 0);
  rtf_buf_free(&bad);
  rtf_buf_free(&b);
}

static void test_nesting_bound() {
  // 40 levels of [[...]] exceeds RTF_MAX_DEPTH and must be rejected
  rtf_buf b;
  CHECK(rtf_buf_init(&b, 16) == 0);
  CHECK(rtf_buf_put_u8(&b, 0x03) == 0);
  CHECK(rtf_w_map(&b, 1) == 0);
  CHECK(rtf_w_str(&b, "k", 1) == 0);
  for (int i = 0; i < 40; i++) CHECK(rtf_w_list(&b, 1) == 0);
  CHECK(rtf_w_none(&b) == 0);
  CHECK(rtf_validate(b.data, b.len) != 0);
  rtf_buf_free(&b);
}

static void test_buffer_growth() {
  rtf_buf b;
  CHECK(rtf_buf_init(&b, 16) == 0);
  std::string big(100000, 'x');
  CHECK(rtf_buf_put_u8(&b, 0x03) == 0);
  CHECK(rtf_w_map(&b, 1) == 0);
  CHECK(rtf_w_str(&b, "data", 4) == 0);
  CHECK(rtf_w_bytes(&b, reinterpret_cast<const uint8_t *>(big.data()),
                    static_cast<uint32_t>(big.size())) == 0);
  CHECK(rtf_validate(b.data, b.len) == 0);
  CHECK(b.len == 1 + 5 + (5 + 4) + (5 + big.size()));
  rtf_buf_free(&b);
}

// -- ring --------------------------------------------------------------

static void test_ring_basic() {
  rtf_ring *r = rtf_ring_new(4096);
  CHECK(r != nullptr);
  CHECK(rtf_ring_pending(r) == 0);
  CHECK(rtf_ring_push(r, reinterpret_cast<const uint8_t *>("hello"), 5) == 0);
  CHECK(rtf_ring_push(r, reinterpret_cast<const uint8_t *>("world!"), 6) ==
        0);
  CHECK(rtf_ring_pending(r) > 0);
  uint8_t out[64];
  uint64_t n = rtf_ring_drain(r, out, sizeof(out));
  CHECK(n == 11);
  CHECK(std::memcmp(out, "helloworld!", 11) == 0);
  CHECK(rtf_ring_pending(r) == 0);
  // empty push is rejected, oversized push is rejected
  CHECK(rtf_ring_push(r, out, 0) == -1);
  std::vector<uint8_t> huge(4096, 7);
  CHECK(rtf_ring_push(r, huge.data(), huge.size()) == -1);
  rtf_ring_free(r);
}

static void test_ring_wraparound() {
  rtf_ring *r = rtf_ring_new(4096);
  uint8_t frame[97];  // deliberately unaligned record size
  uint8_t out[4096];
  uint64_t total = 0;
  for (int lap = 0; lap < 500; lap++) {
    for (int i = 0; i < 3; i++) {
      std::memset(frame, lap % 251, sizeof(frame));
      CHECK(rtf_ring_push(r, frame, sizeof(frame)) == 0);
    }
    uint64_t n = rtf_ring_drain(r, out, sizeof(out));
    CHECK(n == 3 * sizeof(frame));
    for (uint64_t j = 0; j < n; j++) CHECK(out[j] == lap % 251);
    total += n;
  }
  CHECK(total == 500u * 3u * sizeof(frame));
  rtf_ring_free(r);
}

static void test_ring_full_then_recovers() {
  rtf_ring *r = rtf_ring_new(4096);
  uint8_t frame[1000];
  int pushed = 0;
  while (rtf_ring_push(r, frame, sizeof(frame)) == 0) pushed++;
  CHECK(pushed >= 3);  // 4 KiB ring holds at least 3 x 1 KiB records
  uint8_t out[4096];
  CHECK(rtf_ring_drain(r, out, sizeof(out)) ==
        static_cast<uint64_t>(pushed) * sizeof(frame));
  CHECK(rtf_ring_push(r, frame, sizeof(frame)) == 0);  // space came back
  rtf_ring_free(r);
}

// Regression: the zero-behind-tail invariant.  Record boundaries shift
// between laps (varied sizes + pads), so a position that was record
// INTERIOR last lap can be a record START this lap; unless drain zeroes
// the whole released region, a consumer at an uncommitted next-lap
// record start reads stale payload bytes as a committed garbage length
// (observed as rare corrupted frames under the broadcast bench).
static void test_ring_zero_behind_tail_across_laps() {
  rtf_ring *ring = rtf_ring_new(4096);
  uint8_t frame[2048];
  std::memset(frame, 0xAB, sizeof(frame));  // nonzero stale payload
  uint8_t out[4096];
  // varied sizes force boundary misalignment across laps
  const uint64_t sizes[] = {97, 1000, 13, 512, 61, 2000, 5, 300};
  for (int lap = 0; lap < 300; lap++) {
    uint64_t n1 = sizes[lap % 8], n2 = sizes[(lap + 3) % 8];
    CHECK(rtf_ring_push(ring, frame, n1) == 0);
    CHECK(rtf_ring_push(ring, frame, n2) == 0);
    CHECK(rtf_ring_drain(ring, out, sizeof(out)) == n1 + n2);
    // invariant: with the ring empty, EVERY slab byte reads zero
    const uint8_t *slab = rtf_ring_slab(ring);
    for (uint64_t i = 0; i < rtf_ring_capacity(ring); i++)
      if (slab[i] != 0) {
        CHECK(slab[i] == 0);
        break;
      }
  }
  rtf_ring_free(ring);
}

// Concurrency stress: N producers push length-self-describing records,
// one consumer drains until every record arrived intact and in a
// per-producer FIFO order.  This is the TSAN target's main course.
static void test_ring_mpsc_stress() {
  rtf_ring *r = rtf_ring_new(1 << 16);
  const int kProducers = 4;
  const int kPerProducer = 20000;
  std::atomic<int> total_pushed{0};
  std::vector<std::thread> producers;
  for (int p = 0; p < kProducers; p++) {
    producers.emplace_back([&, p] {
      uint8_t frame[32];
      for (int i = 0; i < kPerProducer; i++) {
        // record: [producer u8][seq u32][len u8][payload of len bytes]
        uint8_t len = static_cast<uint8_t>(1 + (i * 7 + p) % 24);
        frame[0] = static_cast<uint8_t>(p);
        std::memcpy(frame + 1, &i, 4);
        frame[5] = len;
        for (int j = 0; j < len; j++)
          frame[6 + j] = static_cast<uint8_t>(p * 31 + i + j);
        while (rtf_ring_push(r, frame, 6u + len) != 0)
          std::this_thread::yield();  // full: wait for the consumer
        total_pushed.fetch_add(1, std::memory_order_relaxed);
      }
    });
  }
  std::vector<int> next_seq(kProducers, 0);
  std::vector<uint8_t> out(1 << 16);
  int received = 0;
  int idle_spins = 0;
  while (received < kProducers * kPerProducer) {
    uint64_t n = rtf_ring_drain(r, out.data(), out.size());
    if (n == 0) {
      if (++idle_spins > 100000000) break;  // deadlock guard
      std::this_thread::yield();
      continue;
    }
    idle_spins = 0;
    uint64_t pos = 0;
    while (pos < n) {
      CHECK(pos + 6 <= n);
      int p = out[pos];
      int seq;
      std::memcpy(&seq, out.data() + pos + 1, 4);
      uint8_t len = out[pos + 5];
      CHECK(p >= 0 && p < kProducers);
      CHECK(seq == next_seq[p]);  // per-producer FIFO survives
      next_seq[p] = seq + 1;
      CHECK(pos + 6 + len <= n);
      for (int j = 0; j < len; j++)
        CHECK(out[pos + 6 + j] == static_cast<uint8_t>(p * 31 + seq + j));
      pos += 6u + len;
      received++;
    }
  }
  for (auto &t : producers) t.join();
  CHECK(received == kProducers * kPerProducer);
  CHECK(rtf_ring_pending(r) == 0);
  rtf_ring_free(r);
}

int main() {
  test_codec_roundtrip_shape();
  test_nesting_bound();
  test_buffer_growth();
  test_ring_basic();
  test_ring_wraparound();
  test_ring_full_then_recovers();
  test_ring_zero_behind_tail_across_laps();
  test_ring_mpsc_stress();
  if (failures) {
    std::fprintf(stderr, "%d failure(s)\n", failures);
    return 1;
  }
  std::printf("frames_test: all ok\n");
  return 0;
}
