/* Concurrency stress for the shm store, intended for ThreadSanitizer
 * builds (`make tsan`) — the analogue of the reference running its
 * object-store tests under a TSAN bazel config. N threads hammer
 * create/seal/get/release/delete on an overlapping id space through one
 * attached store, so TSAN can observe any unlocked shared-state access
 * in shm_store.cc; a coherence check runs after the storm. */

#include <assert.h>
#include <pthread.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <unistd.h>

#include "rt_store.h"

static const int kThreads = 8;
static const int kIters = 500;
static const int kIdSpace = 32;

struct Ctx {
  rt_store *store;
  int tid;
};

static void make_id(uint8_t *id, int n) {
  memset(id, 0, RT_ID_SIZE);
  memcpy(id, &n, sizeof(n));
}

static void *worker(void *arg) {
  Ctx *ctx = (Ctx *)arg;
  rt_store *s = ctx->store;
  unsigned seed = 1234u + (unsigned)ctx->tid;
  for (int i = 0; i < kIters; i++) {
    uint8_t id[RT_ID_SIZE];
    make_id(id, (int)(rand_r(&seed) % kIdSpace));
    int op = rand_r(&seed) % 5;
    uint64_t sz = 64 + rand_r(&seed) % 4096;
    if (op == 0) {
      (void)rt_obj_create(s, id, sz); /* RT_ERR_EXISTS is fine */
    } else if (op == 1) {
      (void)rt_obj_seal(s, id);
    } else if (op == 2) {
      uint64_t got = 0;
      if (rt_obj_get(s, id, &got) >= 0) {
        (void)rt_obj_release(s, id);
      }
    } else if (op == 3) {
      (void)rt_obj_release(s, id);
    } else {
      (void)rt_obj_delete(s, id);
    }
  }
  return nullptr;
}

int main() {
  const char *name = "/rt_race_test";
  rt_store_destroy(name);
  rt_store *s = rt_store_create(name, 16u << 20, 1024);
  assert(s);

  pthread_t threads[kThreads];
  Ctx ctxs[kThreads];
  for (int t = 0; t < kThreads; t++) {
    ctxs[t].store = s;
    ctxs[t].tid = t;
    int rc = pthread_create(&threads[t], nullptr, worker, &ctxs[t]);
    assert(rc == 0);
  }
  for (int t = 0; t < kThreads; t++) pthread_join(threads[t], nullptr);

  /* store must still be coherent after the storm */
  uint8_t id[RT_ID_SIZE];
  make_id(id, 9999);
  int64_t off = rt_obj_create(s, id, 128);
  assert(off > 0);
  assert(rt_obj_seal(s, id) == RT_OK);
  uint64_t sz = 0;
  assert(rt_obj_get(s, id, &sz) > 0 && sz == 128);
  assert(rt_obj_release(s, id) == RT_OK);

  rt_store_detach(s);
  rt_store_destroy(name);
  printf("race_test ok (%d threads x %d iters)\n", kThreads, kIters);
  return 0;
}
