/* C++ worker API tests (reference analogue: cpp/src/ray/test/
 * api_test.cc — init, put/get, tasks, actors, error paths). */

#include <assert.h>
#include <stdio.h>

#include <cmath>
#include <string>
#include <vector>

#include "ray_api.h"

static int Add(int a, int b) { return a + b; }
static double Hypot2(double x, double y) { return x * x + y * y; }
static std::string Greet(std::string name) { return "hello " + name; }
static int Boom() { throw std::runtime_error("kaput"); }

class Counter {
 public:
  explicit Counter(int start) : n_(start) {}
  int Add(int k) {
    n_ += k;
    return n_;
  }
  int Value() { return n_; }

 private:
  int n_;
};

static void test_put_get() {
  auto r1 = ray::Put(42);
  assert(ray::Get(r1) == 42);
  auto r2 = ray::Put(std::string("abc"));
  assert(ray::Get(r2) == "abc");
  std::vector<float> v = {1.5f, 2.5f};
  auto r3 = ray::Put(v);
  assert(ray::Get(r3) == v);
  auto r4 = ray::Put(std::string(""));   /* empty payload */
  assert(ray::Get(r4).empty());
  printf("put/get ok\n");
}

static void test_tasks() {
  auto ref = ray::Task(Add, 2, 3).Remote();
  assert(ref.Get() == 5);
  auto ref2 = ray::Task(Hypot2, 3.0, 4.0).Remote();
  assert(std::abs(ref2.Get() - 25.0) < 1e-9);
  auto ref3 = ray::Task(Greet, std::string("tpu")).Remote();
  assert(ref3.Get() == "hello tpu");

  /* parallel fan-out */
  std::vector<ray::ObjectRef<int>> refs;
  for (int i = 0; i < 32; i++) refs.push_back(ray::Task(Add, i, i).Remote());
  for (int i = 0; i < 32; i++) assert(refs[i].Get() == 2 * i);
  printf("tasks ok\n");
}

static void test_task_error() {
  auto ref = ray::Task(Boom).Remote();
  bool threw = false;
  try {
    ref.Get(10.0);
  } catch (const std::exception &e) {
    threw = std::string(e.what()).find("kaput") != std::string::npos;
  }
  assert(threw);
  printf("task error ok\n");
}

static void test_actors() {
  auto h = ray::Actor<Counter>(100).Remote();
  auto a = h.Call(&Counter::Add, 1);
  auto b = h.Call(&Counter::Add, 10);
  auto c = h.Call(&Counter::Value);
  /* per-actor mutex serializes calls; sum must be exact */
  (void)a.Get();
  (void)b.Get();
  assert(c.Get() == 111);

  /* hammer one actor from the pool: no lost updates */
  auto h2 = ray::Actor<Counter>(0).Remote();
  std::vector<ray::ObjectRef<int>> refs;
  for (int i = 0; i < 200; i++) refs.push_back(h2.Call(&Counter::Add, 1));
  for (auto &r : refs) (void)r.Get();
  assert(h2.Call(&Counter::Value).Get() == 200);
  printf("actors ok\n");
}

int main() {
  ray::Init();
  assert(ray::IsInitialized());
  test_put_get();
  test_tasks();
  test_task_error();
  test_actors();
  ray::Shutdown();
  assert(!ray::IsInitialized());
  /* re-init works (shutdown/re-init cycle) */
  ray::Init();
  assert(ray::Task(Add, 1, 1).Remote().Get() == 2);
  ray::Shutdown();
  printf("api_test ok\n");
  return 0;
}
