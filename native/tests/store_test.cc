/* Unit + multiprocess tests for rt_store (run via `make test`).
 * Mirrors the coverage style of the reference's plasma tests
 * (reference: src/ray/object_manager/test/) with plain asserts. */
#include "rt_store.h"

#include <assert.h>
#include <stdio.h>
#include <string.h>
#include <sys/wait.h>
#include <unistd.h>

static void make_id(uint8_t *id, int n) {
  memset(id, 0, RT_ID_SIZE);
  memcpy(id, &n, sizeof(n));
}

static void test_basic() {
  const char *name = "/rt_test_basic";
  rt_store_destroy(name);
  rt_store *s = rt_store_create(name, 1 << 20, 256);
  assert(s);
  uint8_t id[RT_ID_SIZE];
  make_id(id, 1);

  int64_t off = rt_obj_create(s, id, 1000);
  assert(off > 0);
  assert(rt_obj_contains(s, id) == RT_STATE_CREATED);
  /* not gettable until sealed */
  uint64_t sz = 0;
  assert(rt_obj_get(s, id, &sz) == RT_ERR_NOT_SEALED);
  /* duplicate create rejected */
  assert(rt_obj_create(s, id, 10) == RT_ERR_EXISTS);

  char *base = nullptr;
  {
    /* write through our own mapping */
    rt_store *s2 = rt_store_attach(name);
    assert(s2);
    rt_store_detach(s2);
  }
  assert(rt_obj_seal(s, id) == RT_OK);
  int64_t off2 = rt_obj_get(s, id, &sz);
  assert(off2 == off && sz == 1000);
  assert(rt_obj_refcount(s, id) == 1);
  /* in-use delete rejected */
  assert(rt_obj_delete(s, id) == RT_ERR_IN_USE);
  assert(rt_obj_release(s, id) == RT_OK);
  assert(rt_obj_delete(s, id) == RT_OK);
  assert(rt_obj_contains(s, id) == RT_STATE_ABSENT);
  assert(rt_store_num_objects(s) == 0);
  (void)base;
  rt_store_detach(s);
  rt_store_destroy(name);
  printf("test_basic ok\n");
}

static void test_alloc_reuse() {
  const char *name = "/rt_test_alloc";
  rt_store_destroy(name);
  rt_store *s = rt_store_create(name, 1 << 20, 256);
  assert(s);
  uint8_t id[RT_ID_SIZE];
  /* fill, free all, then a big alloc must fit again (coalescing) */
  int n = 0;
  for (;; ++n) {
    make_id(id, n);
    int64_t off = rt_obj_create(s, id, 60000);
    if (off == RT_ERR_OOM) break;
    assert(off > 0);
    rt_obj_seal(s, id);
  }
  assert(n >= 16);
  for (int i = 0; i < n; ++i) {
    make_id(id, i);
    assert(rt_obj_delete(s, id) == RT_OK);
  }
  assert(rt_store_used(s) == 0);
  make_id(id, 9999);
  int64_t off = rt_obj_create(s, id, 900000);
  assert(off > 0);
  rt_store_detach(s);
  rt_store_destroy(name);
  printf("test_alloc_reuse ok (%d blocks)\n", n);
}

static void test_eviction_order() {
  const char *name = "/rt_test_evict";
  rt_store_destroy(name);
  rt_store *s = rt_store_create(name, 1 << 20, 256);
  uint8_t id[RT_ID_SIZE];
  for (int i = 0; i < 4; ++i) {
    make_id(id, i);
    assert(rt_obj_create(s, id, 1000) > 0);
    rt_obj_seal(s, id);
  }
  /* touch 0 so 1 becomes LRU; pin 1? no — get 0 bumps its tick */
  make_id(id, 0);
  uint64_t sz;
  rt_obj_get(s, id, &sz);
  rt_obj_release(s, id);
  uint8_t out[4 * RT_ID_SIZE];
  int c = rt_evict_candidates(s, 1500, out, 4);
  assert(c == 2);
  int got0, got1;
  memcpy(&got0, out, sizeof(int));
  memcpy(&got1, out + RT_ID_SIZE, sizeof(int));
  assert(got0 == 1 && got1 == 2); /* oldest ticks first, 0 was refreshed */
  /* pinned objects are never candidates */
  make_id(id, 1);
  rt_obj_get(s, id, &sz);
  c = rt_evict_candidates(s, 100, out, 4);
  memcpy(&got0, out, sizeof(int));
  assert(c >= 1 && got0 == 2);
  rt_store_detach(s);
  rt_store_destroy(name);
  printf("test_eviction_order ok\n");
}

static void test_multiprocess() {
  const char *name = "/rt_test_mp";
  rt_store_destroy(name);
  rt_store *s = rt_store_create(name, 1 << 22, 1024);
  assert(s);
  uint8_t id[RT_ID_SIZE];
  make_id(id, 42);

  pid_t pid = fork();
  if (pid == 0) {
    /* child: create, write, seal */
    rt_store *c = rt_store_attach(name);
    assert(c);
    int64_t off = rt_obj_create(c, id, 256);
    assert(off > 0);
    _exit(0);
  }
  int status = 0;
  waitpid(pid, &status, 0);
  assert(WIFEXITED(status) && WEXITSTATUS(status) == 0);
  /* parent sees the child's object */
  assert(rt_obj_contains(s, id) == RT_STATE_CREATED);
  assert(rt_obj_seal(s, id) == RT_OK);
  uint64_t sz = 0;
  assert(rt_obj_get(s, id, &sz) > 0 && sz == 256);
  rt_store_detach(s);
  rt_store_destroy(name);
  printf("test_multiprocess ok\n");
}

int main() {
  test_basic();
  test_alloc_reuse();
  test_eviction_order();
  test_multiprocess();
  printf("ALL OK\n");
  return 0;
}
