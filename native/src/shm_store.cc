/* Shared-memory object store implementation.  See rt_store.h.
 *
 * Layout of the shm segment (all offsets relative to base):
 *   [Header][Slot x table_slots][heap ...]
 * The heap is managed by a first-fit free list sorted by offset with
 * two-sided coalescing on free.  Everything mutable lives inside the
 * segment under one process-shared robust pthread mutex, so any worker
 * process can allocate/seal/get concurrently and a crashed holder
 * cannot wedge the store.
 */
#include "rt_store.h"

#include <errno.h>
#include <fcntl.h>
#include <pthread.h>
#include <string.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

#include <new>

namespace {

constexpr uint64_t kMagic = 0x52545354'4f524531ULL; /* "RTSTORE1" */
constexpr uint32_t kVersion = 1;
constexpr uint64_t kAlign = 64;
constexpr uint64_t kMinSplit = 128; /* don't split blocks smaller than this */

inline uint64_t align_up(uint64_t v, uint64_t a) { return (v + a - 1) & ~(a - 1); }

enum SlotState : uint32_t {
  SLOT_EMPTY = 0,
  SLOT_CREATED = 1,
  SLOT_SEALED = 2,
  SLOT_TOMBSTONE = 3,
};

struct Slot {
  uint8_t id[RT_ID_SIZE];
  uint32_t state;
  uint64_t offset; /* data offset from base */
  uint64_t size;   /* user size */
  uint32_t refcount;
  uint32_t pad_;
  uint64_t lru_tick;
};

/* Heap block header.  `size` includes the header and is kAlign-aligned.
 * When free, `next` is the offset of the next free block (0 = end of
 * list); when allocated, `next` == kInUse. */
constexpr uint64_t kInUse = ~0ULL;
struct Block {
  uint64_t size;
  uint64_t next;
};

struct Header {
  uint64_t magic;
  uint32_t version;
  uint32_t table_slots;
  uint64_t capacity;   /* heap bytes */
  uint64_t used;       /* sum of live user sizes */
  uint64_t table_offset;
  uint64_t heap_offset;
  uint64_t heap_end;
  uint64_t free_head;  /* offset of first free Block, 0 = none */
  uint64_t lru_clock;
  uint64_t num_objects;
  pthread_mutex_t lock;
};

} // namespace

struct rt_store {
  void *base;
  uint64_t map_bytes;
  Header *hdr() const { return static_cast<Header *>(base); }
  Slot *slots() const {
    return reinterpret_cast<Slot *>(static_cast<char *>(base) +
                                    hdr()->table_offset);
  }
  Block *block_at(uint64_t off) const {
    return reinterpret_cast<Block *>(static_cast<char *>(base) + off);
  }
};

namespace {

/* Robust lock: recover consistency if a holder died. */
void lock_hdr(Header *h) {
  int rc = pthread_mutex_lock(&h->lock);
  if (rc == EOWNERDEAD) pthread_mutex_consistent(&h->lock);
}
void unlock_hdr(Header *h) { pthread_mutex_unlock(&h->lock); }

uint64_t hash_id(const uint8_t *id) {
  /* FNV-1a over the 28-byte id. */
  uint64_t h = 1469598103934665603ULL;
  for (int i = 0; i < RT_ID_SIZE; ++i) {
    h ^= id[i];
    h *= 1099511628211ULL;
  }
  return h;
}

/* Find the slot for `id`; returns nullptr if absent.  If `for_insert`,
 * returns the first reusable slot (empty/tombstone) when absent, or
 * nullptr if the table is full. */
Slot *find_slot(rt_store *s, const uint8_t *id, bool for_insert) {
  Header *h = s->hdr();
  Slot *tab = s->slots();
  uint32_t n = h->table_slots;
  uint64_t start = hash_id(id) & (n - 1);
  Slot *insert_at = nullptr;
  for (uint32_t i = 0; i < n; ++i) {
    Slot *sl = &tab[(start + i) & (n - 1)];
    if (sl->state == SLOT_EMPTY) {
      if (for_insert) return insert_at ? insert_at : sl;
      return nullptr;
    }
    if (sl->state == SLOT_TOMBSTONE) {
      if (!insert_at) insert_at = sl;
      continue;
    }
    if (memcmp(sl->id, id, RT_ID_SIZE) == 0) return sl;
  }
  return for_insert ? insert_at : nullptr;
}

/* First-fit allocation from the sorted free list.  Returns data offset
 * (past the Block header) or 0 on OOM. */
uint64_t heap_alloc(rt_store *s, uint64_t user_size) {
  Header *h = s->hdr();
  uint64_t need = align_up(user_size + sizeof(Block), kAlign);
  uint64_t prev_off = 0;
  uint64_t off = h->free_head;
  while (off) {
    Block *b = s->block_at(off);
    if (b->size >= need) {
      uint64_t remainder = b->size - need;
      uint64_t next = b->next;
      if (remainder >= kMinSplit) {
        uint64_t tail_off = off + need;
        Block *tail = s->block_at(tail_off);
        tail->size = remainder;
        tail->next = next;
        next = tail_off;
        b->size = need;
      }
      if (prev_off)
        s->block_at(prev_off)->next = next;
      else
        h->free_head = next;
      b->next = kInUse;
      return off + sizeof(Block);
    }
    prev_off = off;
    off = b->next;
  }
  return 0;
}

/* Free a block, keeping the list sorted by offset and coalescing with
 * adjacent free blocks on both sides. */
void heap_free(rt_store *s, uint64_t data_off) {
  Header *h = s->hdr();
  uint64_t off = data_off - sizeof(Block);
  Block *b = s->block_at(off);
  b->next = 0;
  /* find insertion point (prev < off < cur) */
  uint64_t prev_off = 0, cur = h->free_head;
  while (cur && cur < off) {
    prev_off = cur;
    cur = s->block_at(cur)->next;
  }
  b->next = cur;
  if (prev_off)
    s->block_at(prev_off)->next = off;
  else
    h->free_head = off;
  /* coalesce forward */
  if (cur && off + b->size == cur) {
    Block *nb = s->block_at(cur);
    b->size += nb->size;
    b->next = nb->next;
  }
  /* coalesce backward */
  if (prev_off) {
    Block *pb = s->block_at(prev_off);
    if (prev_off + pb->size == off) {
      pb->size += b->size;
      pb->next = b->next;
    }
  }
}

rt_store *map_store(int fd, bool init, uint64_t capacity,
                    uint32_t table_slots) {
  uint64_t map_bytes;
  if (init) {
    uint64_t table_bytes = align_up(uint64_t(table_slots) * sizeof(Slot),
                                    kAlign);
    uint64_t hdr_bytes = align_up(sizeof(Header), kAlign);
    /* heap gets `capacity` bytes plus block-header overhead slack */
    uint64_t heap_bytes = align_up(capacity + capacity / 8 + (1 << 20),
                                   kAlign);
    map_bytes = hdr_bytes + table_bytes + heap_bytes;
    if (ftruncate(fd, off_t(map_bytes)) != 0) return nullptr;
  } else {
    struct stat st;
    if (fstat(fd, &st) != 0) return nullptr;
    map_bytes = uint64_t(st.st_size);
    if (map_bytes < sizeof(Header)) return nullptr;
  }
  void *base = mmap(nullptr, map_bytes, PROT_READ | PROT_WRITE, MAP_SHARED,
                    fd, 0);
  if (base == MAP_FAILED) return nullptr;
  rt_store *s = new (std::nothrow) rt_store{base, map_bytes};
  if (!s) {
    munmap(base, map_bytes);
    return nullptr;
  }
  Header *h = s->hdr();
  if (init) {
    memset(base, 0, sizeof(Header));
    h->version = kVersion;
    h->table_slots = table_slots;
    h->capacity = capacity;
    h->table_offset = align_up(sizeof(Header), kAlign);
    uint64_t table_bytes = align_up(uint64_t(table_slots) * sizeof(Slot),
                                    kAlign);
    h->heap_offset = h->table_offset + table_bytes;
    h->heap_end = map_bytes;
    memset(s->slots(), 0, table_bytes);
    /* one big free block */
    Block *b = s->block_at(h->heap_offset);
    b->size = h->heap_end - h->heap_offset;
    b->next = 0;
    h->free_head = h->heap_offset;
    pthread_mutexattr_t at;
    pthread_mutexattr_init(&at);
    pthread_mutexattr_setpshared(&at, PTHREAD_PROCESS_SHARED);
    pthread_mutexattr_setrobust(&at, PTHREAD_MUTEX_ROBUST);
    pthread_mutex_init(&h->lock, &at);
    pthread_mutexattr_destroy(&at);
    __sync_synchronize();
    h->magic = kMagic; /* publish: attachers poll for the magic */
  } else if (h->magic != kMagic) {
    munmap(base, map_bytes);
    delete s;
    return nullptr;
  }
  return s;
}

} // namespace

extern "C" {

rt_store *rt_store_create(const char *name, uint64_t capacity,
                          uint32_t table_slots) {
  /* round table to a power of two */
  uint32_t n = 1;
  while (n < table_slots) n <<= 1;
  int fd = shm_open(name, O_CREAT | O_EXCL | O_RDWR, 0600);
  if (fd < 0) {
    if (errno == EEXIST) return rt_store_attach(name);
    return nullptr;
  }
  rt_store *s = map_store(fd, /*init=*/true, capacity, n);
  close(fd);
  if (!s) shm_unlink(name);
  return s;
}

rt_store *rt_store_attach(const char *name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  rt_store *s = map_store(fd, /*init=*/false, 0, 0);
  close(fd);
  return s;
}

void rt_store_detach(rt_store *s) {
  if (!s) return;
  munmap(s->base, s->map_bytes);
  delete s;
}

int rt_store_destroy(const char *name) {
  return shm_unlink(name) == 0 ? RT_OK : RT_ERR_SYS;
}

uint64_t rt_store_map_bytes(rt_store *s) { return s->map_bytes; }

int64_t rt_obj_create(rt_store *s, const uint8_t *id, uint64_t size) {
  Header *h = s->hdr();
  lock_hdr(h);
  Slot *sl = find_slot(s, id, /*for_insert=*/true);
  if (!sl) {
    unlock_hdr(h);
    return RT_ERR_TABLE_FULL;
  }
  if (sl->state == SLOT_CREATED || sl->state == SLOT_SEALED) {
    unlock_hdr(h);
    return RT_ERR_EXISTS;
  }
  uint64_t off = heap_alloc(s, size ? size : 1);
  if (!off) {
    unlock_hdr(h);
    return RT_ERR_OOM;
  }
  memcpy(sl->id, id, RT_ID_SIZE);
  sl->state = SLOT_CREATED;
  sl->offset = off;
  sl->size = size;
  sl->refcount = 0;
  sl->lru_tick = ++h->lru_clock;
  h->used += size;
  h->num_objects++;
  unlock_hdr(h);
  return int64_t(off);
}

int rt_obj_seal(rt_store *s, const uint8_t *id) {
  Header *h = s->hdr();
  lock_hdr(h);
  Slot *sl = find_slot(s, id, false);
  if (!sl) {
    unlock_hdr(h);
    return RT_ERR_NOT_FOUND;
  }
  sl->state = SLOT_SEALED;
  unlock_hdr(h);
  return RT_OK;
}

static int64_t obj_find(rt_store *s, const uint8_t *id, uint64_t *size_out,
                        bool take_ref) {
  Header *h = s->hdr();
  lock_hdr(h);
  Slot *sl = find_slot(s, id, false);
  if (!sl) {
    unlock_hdr(h);
    return RT_ERR_NOT_FOUND;
  }
  if (sl->state != SLOT_SEALED) {
    unlock_hdr(h);
    return RT_ERR_NOT_SEALED;
  }
  if (take_ref) sl->refcount++;
  sl->lru_tick = ++h->lru_clock;
  if (size_out) *size_out = sl->size;
  int64_t off = int64_t(sl->offset);
  unlock_hdr(h);
  return off;
}

int64_t rt_obj_get(rt_store *s, const uint8_t *id, uint64_t *size_out) {
  return obj_find(s, id, size_out, /*take_ref=*/true);
}

int64_t rt_obj_lookup(rt_store *s, const uint8_t *id, uint64_t *size_out) {
  return obj_find(s, id, size_out, /*take_ref=*/false);
}

int rt_obj_release(rt_store *s, const uint8_t *id) {
  Header *h = s->hdr();
  lock_hdr(h);
  Slot *sl = find_slot(s, id, false);
  if (!sl) {
    unlock_hdr(h);
    return RT_ERR_NOT_FOUND;
  }
  if (sl->refcount > 0) sl->refcount--;
  unlock_hdr(h);
  return RT_OK;
}

int rt_obj_delete(rt_store *s, const uint8_t *id) {
  Header *h = s->hdr();
  lock_hdr(h);
  Slot *sl = find_slot(s, id, false);
  if (!sl) {
    unlock_hdr(h);
    return RT_ERR_NOT_FOUND;
  }
  if (sl->refcount > 0) {
    unlock_hdr(h);
    return RT_ERR_IN_USE;
  }
  heap_free(s, sl->offset);
  h->used -= sl->size;
  h->num_objects--;
  sl->state = SLOT_TOMBSTONE;
  /* Tombstone reclamation: if the next probe slot is EMPTY, this
   * tombstone (and any run of tombstones before it) cannot be part of
   * any live probe chain — convert the run back to EMPTY so absent-id
   * probes stay short even after heavy id churn. */
  {
    Slot *tab = s->slots();
    uint32_t n = h->table_slots;
    uint32_t i = uint32_t(sl - tab);
    if (tab[(i + 1) & (n - 1)].state == SLOT_EMPTY) {
      while (tab[i].state == SLOT_TOMBSTONE) {
        tab[i].state = SLOT_EMPTY;
        i = (i + n - 1) & (n - 1);
      }
    }
  }
  unlock_hdr(h);
  return RT_OK;
}

int rt_obj_contains(rt_store *s, const uint8_t *id) {
  Header *h = s->hdr();
  lock_hdr(h);
  Slot *sl = find_slot(s, id, false);
  int st = RT_STATE_ABSENT;
  if (sl) {
    if (sl->state == SLOT_CREATED) st = RT_STATE_CREATED;
    else if (sl->state == SLOT_SEALED) st = RT_STATE_SEALED;
  }
  unlock_hdr(h);
  return st;
}

uint64_t rt_obj_refcount(rt_store *s, const uint8_t *id) {
  Header *h = s->hdr();
  lock_hdr(h);
  Slot *sl = find_slot(s, id, false);
  uint64_t rc = sl ? sl->refcount : 0;
  unlock_hdr(h);
  return rc;
}

int rt_evict_candidates(rt_store *s, uint64_t nbytes, uint8_t *out_ids,
                        int max_out) {
  Header *h = s->hdr();
  lock_hdr(h);
  Slot *tab = s->slots();
  uint32_t n = h->table_slots;
  int count = 0;
  uint64_t freed = 0;
  /* selection sort over evictable slots by lru_tick — candidate sets are
   * small (bounded by max_out), table scans are cheap vs. an eviction */
  uint64_t last_tick = 0;
  while (count < max_out && freed < nbytes) {
    Slot *best = nullptr;
    for (uint32_t i = 0; i < n; ++i) {
      Slot *sl = &tab[i];
      if (sl->state != SLOT_SEALED || sl->refcount != 0) continue;
      if (sl->lru_tick <= last_tick) continue;
      if (!best || sl->lru_tick < best->lru_tick) best = sl;
    }
    if (!best) break;
    last_tick = best->lru_tick;
    memcpy(out_ids + size_t(count) * RT_ID_SIZE, best->id, RT_ID_SIZE);
    freed += best->size;
    count++;
  }
  unlock_hdr(h);
  return count;
}

uint64_t rt_store_used(rt_store *s) { return s->hdr()->used; }
uint64_t rt_store_capacity(rt_store *s) { return s->hdr()->capacity; }
uint64_t rt_store_num_objects(rt_store *s) { return s->hdr()->num_objects; }

} /* extern "C" */
