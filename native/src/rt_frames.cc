// Native dispatch-frame codec + MPSC ready-ring.  See rt_frames.h and
// ray_tpu/core/rt_frames.py (the byte-identical pure-Python reference
// — tests/test_rt_frames.py fuzzes the parity between the two).
//
// The Python-object adapter at the bottom is called through
// ctypes.PyDLL (GIL held, real PyObject* arguments), so one call
// encodes a whole message with no per-field ctypes overhead.  The
// codec core and the ring are plain C++ so the unit tests
// (tests/frames_test.cc, TSAN target) build without Python.

#include "rt_frames.h"

#include <atomic>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <new>

// ---------------------------------------------------------------------------
// growable buffer

int rtf_buf_init(rtf_buf *b, uint64_t initial_cap) {
  if (initial_cap < 64) initial_cap = 64;
  b->data = static_cast<uint8_t *>(std::malloc(initial_cap));
  b->len = 0;
  b->cap = b->data ? initial_cap : 0;
  return b->data ? 0 : -1;
}

void rtf_buf_free(rtf_buf *b) {
  std::free(b->data);
  b->data = nullptr;
  b->len = b->cap = 0;
}

static int buf_reserve(rtf_buf *b, uint64_t extra) {
  if (b->len + extra <= b->cap) return 0;
  uint64_t cap = b->cap ? b->cap : 64;
  while (cap < b->len + extra) cap *= 2;
  uint8_t *p = static_cast<uint8_t *>(std::realloc(b->data, cap));
  if (!p) return -1;
  b->data = p;
  b->cap = cap;
  return 0;
}

int rtf_buf_put(rtf_buf *b, const void *src, uint64_t n) {
  if (buf_reserve(b, n) != 0) return -1;
  std::memcpy(b->data + b->len, src, n);
  b->len += n;
  return 0;
}

int rtf_buf_put_u8(rtf_buf *b, uint8_t v) { return rtf_buf_put(b, &v, 1); }

int rtf_buf_put_u32(rtf_buf *b, uint32_t v) {
  uint8_t le[4] = {static_cast<uint8_t>(v), static_cast<uint8_t>(v >> 8),
                   static_cast<uint8_t>(v >> 16),
                   static_cast<uint8_t>(v >> 24)};
  return rtf_buf_put(b, le, 4);
}

int rtf_buf_put_u64(rtf_buf *b, uint64_t v) {
  uint8_t le[8];
  for (int i = 0; i < 8; i++) le[i] = static_cast<uint8_t>(v >> (8 * i));
  return rtf_buf_put(b, le, 8);
}

// ---------------------------------------------------------------------------
// wire-grammar writers (tags documented in rt_frames.py)

int rtf_w_none(rtf_buf *b) { return rtf_buf_put_u8(b, 'N'); }

int rtf_w_bool(rtf_buf *b, int v) { return rtf_buf_put_u8(b, v ? 'T' : 'F'); }

int rtf_w_i64(rtf_buf *b, int64_t v) {
  if (rtf_buf_put_u8(b, 'I') != 0) return -1;
  return rtf_buf_put_u64(b, static_cast<uint64_t>(v));
}

int rtf_w_f64(rtf_buf *b, double v) {
  uint64_t bits;
  std::memcpy(&bits, &v, 8);
  if (rtf_buf_put_u8(b, 'D') != 0) return -1;
  return rtf_buf_put_u64(b, bits);
}

int rtf_w_bytes(rtf_buf *b, const uint8_t *p, uint32_t n) {
  if (rtf_buf_put_u8(b, 'B') != 0 || rtf_buf_put_u32(b, n) != 0) return -1;
  return rtf_buf_put(b, p, n);
}

int rtf_w_str(rtf_buf *b, const char *s, uint32_t n) {
  if (rtf_buf_put_u8(b, 'S') != 0 || rtf_buf_put_u32(b, n) != 0) return -1;
  return rtf_buf_put(b, s, n);
}

int rtf_w_list(rtf_buf *b, uint32_t count) {
  if (rtf_buf_put_u8(b, 'L') != 0) return -1;
  return rtf_buf_put_u32(b, count);
}

int rtf_w_tuple(rtf_buf *b, uint32_t count) {
  if (rtf_buf_put_u8(b, 'U') != 0) return -1;
  return rtf_buf_put_u32(b, count);
}

int rtf_w_map(rtf_buf *b, uint32_t count) {
  if (rtf_buf_put_u8(b, 'M') != 0) return -1;
  return rtf_buf_put_u32(b, count);
}

double rtf_monotonic(void) {
  struct timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<double>(ts.tv_sec) + 1e-9 * ts.tv_nsec;
}

// ---------------------------------------------------------------------------
// structural validator (decode-side hardening; also the pure-C++ test
// surface for the grammar)

#define RTF_MAX_DEPTH 32

static int64_t validate_value(const uint8_t *p, uint64_t len, uint64_t pos,
                              int depth) {
  if (pos >= len) return -1;
  uint8_t tag = p[pos++];
  switch (tag) {
    case 'N':
    case 'T':
    case 'F':
      return static_cast<int64_t>(pos);
    case 'I':
    case 'D':
      return pos + 8 <= len ? static_cast<int64_t>(pos + 8) : -1;
    case 'B':
    case 'S': {
      if (pos + 4 > len) return -1;
      uint32_t n;
      std::memcpy(&n, p + pos, 4);
      pos += 4;
      return pos + n <= len ? static_cast<int64_t>(pos + n) : -1;
    }
    case 'L':
    case 'U':
    case 'M': {
      if (depth >= RTF_MAX_DEPTH) return -2;
      if (pos + 4 > len) return -1;
      uint32_t n;
      std::memcpy(&n, p + pos, 4);
      pos += 4;
      uint32_t slots = (tag == 'M') ? 2 * n : n;
      for (uint32_t i = 0; i < slots; i++) {
        if (tag == 'M' && (i % 2) == 0) {
          if (pos >= len || (p[pos] != 'S' && p[pos] != 'B')) return -3;
        }
        int64_t next = validate_value(p, len, pos, depth + 1);
        if (next < 0) return next;
        pos = static_cast<uint64_t>(next);
      }
      return static_cast<int64_t>(pos);
    }
    default:
      return -4;
  }
}

int rtf_validate(const uint8_t *payload, uint64_t len) {
  if (len < 1 || payload[0] != 0x03) return -5;
  if (len < 2 || payload[1] != 'M') return -6;  // top level must be a map
  int64_t end = validate_value(payload, len, 1, 0);
  if (end < 0) return static_cast<int>(end);
  return static_cast<uint64_t>(end) == len ? 0 : -7;
}

// ---------------------------------------------------------------------------
// MPSC ready-ring
//
// Byte slab with two monotonically increasing cursors.  A producer
// reserves [head, head+size) with one CAS, writes payload, then
// commits by storing the record length header with release semantics.
// The single consumer (serialized externally — in ray_tpu the holder
// of the Connection send lock) walks committed records in order and
// stops at the first uncommitted one, so FIFO order is preserved even
// when a slow producer is mid-write behind a fast one.  Record starts
// are 4-byte aligned so the length header can be stored/loaded
// atomically; a record never wraps (a PAD record fills the slab tail).

static const uint32_t RTF_PAD = 0xFFFFFFFFu;

struct rtf_ring {
  uint8_t *slab;
  uint64_t cap;
  std::atomic<uint64_t> head;  // producer reservation cursor
  std::atomic<uint64_t> tail;  // consumer release cursor
};

rtf_ring *rtf_ring_new(uint64_t capacity_bytes) {
  if (capacity_bytes < 4096) capacity_bytes = 4096;
  capacity_bytes = (capacity_bytes + 3) & ~uint64_t(3);
  rtf_ring *r = new (std::nothrow) rtf_ring;
  if (!r) return nullptr;
  r->slab = static_cast<uint8_t *>(std::calloc(1, capacity_bytes));
  if (!r->slab) {
    delete r;
    return nullptr;
  }
  r->cap = capacity_bytes;
  r->head.store(0, std::memory_order_relaxed);
  r->tail.store(0, std::memory_order_relaxed);
  return r;
}

void rtf_ring_free(rtf_ring *r) {
  if (!r) return;
  std::free(r->slab);
  delete r;
}

static inline void hdr_store(uint8_t *at, uint32_t v,
                             std::memory_order order) {
  reinterpret_cast<std::atomic<uint32_t> *>(at)->store(v, order);
}

static inline uint32_t hdr_load(const uint8_t *at, std::memory_order order) {
  return reinterpret_cast<const std::atomic<uint32_t> *>(at)->load(order);
}

int rtf_ring_push(rtf_ring *r, const uint8_t *data, uint64_t len) {
  if (len == 0 || len > r->cap / 2 || len > 0xFFFFFFFEull) return -1;
  uint64_t rec = 4 + ((len + 3) & ~uint64_t(3));
  for (;;) {
    uint64_t h = r->head.load(std::memory_order_relaxed);
    uint64_t off = h % r->cap;
    uint64_t to_end = r->cap - off;
    uint64_t need = (rec <= to_end) ? rec : to_end + rec;
    if (h + need - r->tail.load(std::memory_order_acquire) > r->cap)
      return -1;  // full (caller takes its locked direct-send path)
    if (rec > to_end) {
      // reserve the slab tail as a PAD record so this frame starts at 0
      if (!r->head.compare_exchange_weak(h, h + to_end,
                                         std::memory_order_acq_rel,
                                         std::memory_order_relaxed))
        continue;
      if (to_end >= 4) hdr_store(r->slab + off, RTF_PAD,
                                 std::memory_order_release);
      // (< 4 dead bytes need no marker: the consumer skips short tails)
      continue;
    }
    if (!r->head.compare_exchange_weak(h, h + rec,
                                       std::memory_order_acq_rel,
                                       std::memory_order_relaxed))
      continue;
    std::memcpy(r->slab + off + 4, data, len);
    hdr_store(r->slab + off, static_cast<uint32_t>(len),
              std::memory_order_release);
    return 0;
  }
}

uint64_t rtf_ring_drain(rtf_ring *r, uint8_t *out, uint64_t cap) {
  uint64_t t = r->tail.load(std::memory_order_relaxed);
  uint64_t h = r->head.load(std::memory_order_acquire);
  uint64_t copied = 0;
  while (t < h) {
    uint64_t off = t % r->cap;
    uint64_t to_end = r->cap - off;
    if (to_end < 4) {  // unmarked dead tail
      std::memset(r->slab + off, 0, to_end);
      t += to_end;
      r->tail.store(t, std::memory_order_release);
      continue;
    }
    uint32_t len = hdr_load(r->slab + off, std::memory_order_acquire);
    if (len == 0) break;  // reserved but uncommitted: stop (FIFO)
    if (len == RTF_PAD) {
      hdr_store(r->slab + off, 0, std::memory_order_relaxed);
      if (to_end > 4) std::memset(r->slab + off + 4, 0, to_end - 4);
      t += to_end;
      r->tail.store(t, std::memory_order_release);
      continue;
    }
    uint64_t rec = 4 + ((uint64_t(len) + 3) & ~uint64_t(3));
    if (len > to_end - 4 || copied + len > cap)
      break;  // corrupt-length guard / caller's buffer is full
    std::memcpy(out + copied, r->slab + off + 4, len);
    copied += len;
    // Zero the WHOLE drained region — header AND payload — before
    // releasing it.  Record boundaries shift between laps (sizes
    // vary), so a byte that is record INTERIOR this lap can be a
    // record START next lap: if only headers were zeroed, a consumer
    // arriving at that next-lap record between its reservation and its
    // commit would read stale payload bytes as a committed garbage
    // length (found as rare corrupted frames -> wire desync under the
    // 8-node broadcast load).  Every position behind tail being zero
    // is the invariant that makes `len == 0` mean "uncommitted".
    hdr_store(r->slab + off, 0, std::memory_order_relaxed);
    std::memset(r->slab + off + 4, 0, rec - 4);
    t += rec;
    r->tail.store(t, std::memory_order_release);
  }
  return copied;
}

uint64_t rtf_ring_pending(const rtf_ring *r) {
  return r->head.load(std::memory_order_acquire) -
         r->tail.load(std::memory_order_acquire);
}

uint64_t rtf_ring_capacity(const rtf_ring *r) { return r->cap; }

const uint8_t *rtf_ring_slab(const rtf_ring *r) { return r->slab; }

extern "C" int rtf_abi_version(void) { return 1; }

// ---------------------------------------------------------------------------
// Python-object adapter (ctypes.PyDLL: the GIL is held across calls).
// Excluded from the pure-C++ unit-test builds via RTF_NO_PYTHON.

#ifndef RTF_NO_PYTHON
#include <Python.h>

struct stamp_ctx {
  const char *stage;
  uint32_t stage_len;
  double now;
  int done;
};

// 0 = ok, 1 = ineligible (caller falls back to pickle).  Allocation
// failure is folded into "ineligible" — pickle then takes over.
static int enc_value(rtf_buf *b, PyObject *v, int depth, stamp_ctx *sc);

static int enc_list_stamped(rtf_buf *b, PyObject *list, int depth,
                            stamp_ctx *sc) {
  // the appended (stage, t) tuple sits one level below this list; the
  // Python reference runs its container depth check on that tuple, so
  // the fold must be ineligible at the same boundary or the two
  // encoders diverge (and the frame would nest past what decoders
  // accept)
  if (depth + 1 >= RTF_MAX_DEPTH) return 1;
  Py_ssize_t n = PyList_GET_SIZE(list);
  if (n + 1 > 0xFFFFFFFELL) return 1;
  if (rtf_w_list(b, static_cast<uint32_t>(n + 1)) != 0) return 1;
  for (Py_ssize_t i = 0; i < n; i++) {
    if (enc_value(b, PyList_GET_ITEM(list, i), depth + 1, nullptr) != 0)
      return 1;
  }
  if (rtf_w_tuple(b, 2) != 0) return 1;
  if (rtf_w_str(b, sc->stage, sc->stage_len) != 0) return 1;
  if (rtf_w_f64(b, sc->now) != 0) return 1;
  return 0;
}

static int enc_value(rtf_buf *b, PyObject *v, int depth, stamp_ctx *sc) {
  if (v == Py_None) return rtf_w_none(b) == 0 ? 0 : 1;
  if (PyBool_Check(v)) return rtf_w_bool(b, v == Py_True) == 0 ? 0 : 1;
  if (PyLong_CheckExact(v)) {
    long long x = PyLong_AsLongLong(v);
    if (x == -1 && PyErr_Occurred()) {
      PyErr_Clear();
      return 1;  // out of i64 range
    }
    return rtf_w_i64(b, x) == 0 ? 0 : 1;
  }
  if (PyFloat_CheckExact(v))
    return rtf_w_f64(b, PyFloat_AS_DOUBLE(v)) == 0 ? 0 : 1;
  if (PyBytes_CheckExact(v)) {
    Py_ssize_t n = PyBytes_GET_SIZE(v);
    if (n > 0xFFFFFFFFLL) return 1;
    return rtf_w_bytes(
               b,
               reinterpret_cast<const uint8_t *>(PyBytes_AS_STRING(v)),
               static_cast<uint32_t>(n)) == 0
               ? 0
               : 1;
  }
  if (PyUnicode_CheckExact(v)) {
    Py_ssize_t n;
    const char *s = PyUnicode_AsUTF8AndSize(v, &n);
    if (!s) {
      PyErr_Clear();
      return 1;  // unencodable (lone surrogates)
    }
    if (n > 0xFFFFFFFFLL) return 1;
    return rtf_w_str(b, s, static_cast<uint32_t>(n)) == 0 ? 0 : 1;
  }
  if (depth >= RTF_MAX_DEPTH) return 1;
  if (PyList_CheckExact(v) || PyTuple_CheckExact(v)) {
    int is_list = PyList_CheckExact(v);
    Py_ssize_t n = is_list ? PyList_GET_SIZE(v) : PyTuple_GET_SIZE(v);
    if (n > 0xFFFFFFFFLL) return 1;
    if ((is_list ? rtf_w_list(b, static_cast<uint32_t>(n))
                 : rtf_w_tuple(b, static_cast<uint32_t>(n))) != 0)
      return 1;
    for (Py_ssize_t i = 0; i < n; i++) {
      PyObject *item =
          is_list ? PyList_GET_ITEM(v, i) : PyTuple_GET_ITEM(v, i);
      if (enc_value(b, item, depth + 1, sc) != 0) return 1;
    }
    return 0;
  }
  if (PyDict_CheckExact(v)) {
    Py_ssize_t n = PyDict_GET_SIZE(v);
    if (n > 0xFFFFFFFFLL) return 1;
    if (rtf_w_map(b, static_cast<uint32_t>(n)) != 0) return 1;
    PyObject *k, *val;
    Py_ssize_t pos = 0;
    while (PyDict_Next(v, &pos, &k, &val)) {
      int k_is_str = PyUnicode_CheckExact(k);
      if (!k_is_str && !PyBytes_CheckExact(k)) return 1;
      if (enc_value(b, k, depth + 1, nullptr) != 0) return 1;
      // flight-recorder stamp fold: first "fr" list in pre-order
      if (sc && !sc->done && k_is_str && PyList_CheckExact(val)) {
        Py_ssize_t kn;
        const char *ks = PyUnicode_AsUTF8AndSize(k, &kn);
        if (ks && kn == 2 && ks[0] == 'f' && ks[1] == 'r') {
          sc->done = 1;
          if (enc_list_stamped(b, val, depth + 1, sc) != 0) return 1;
          continue;
        }
        if (!ks) PyErr_Clear();
      }
      if (enc_value(b, val, depth + 1, sc) != 0) return 1;
    }
    return 0;
  }
  return 1;  // outside the wire universe
}

// dict -> complete wire frame bytes (8-byte LE length prefix + 0x03 +
// body), or None when the message is ineligible (caller pickles).
// stage == NULL means no stamp; now < 0 reads CLOCK_MONOTONIC.
extern "C" PyObject *rtf_encode_frame(PyObject *msg, const char *stage,
                                      double now) {
  if (!PyDict_CheckExact(msg)) Py_RETURN_NONE;
  stamp_ctx sc_storage, *sc = nullptr;
  if (stage) {
    sc_storage.stage = stage;
    sc_storage.stage_len = static_cast<uint32_t>(std::strlen(stage));
    sc_storage.now = now < 0 ? rtf_monotonic() : now;
    sc_storage.done = 0;
    sc = &sc_storage;
  }
  rtf_buf b;
  if (rtf_buf_init(&b, 512) != 0) Py_RETURN_NONE;
  // length-prefix placeholder, patched below
  if (rtf_buf_put_u64(&b, 0) != 0 || rtf_buf_put_u8(&b, 0x03) != 0 ||
      enc_value(&b, msg, 0, sc) != 0) {
    rtf_buf_free(&b);
    Py_RETURN_NONE;
  }
  uint64_t payload_len = b.len - 8;
  for (int i = 0; i < 8; i++)
    b.data[i] = static_cast<uint8_t>(payload_len >> (8 * i));
  PyObject *out = PyBytes_FromStringAndSize(
      reinterpret_cast<const char *>(b.data), static_cast<Py_ssize_t>(b.len));
  rtf_buf_free(&b);
  if (!out) {
    PyErr_Clear();
    Py_RETURN_NONE;
  }
  return out;
}

// -- decoding ----------------------------------------------------------

static PyObject *dec_value(const uint8_t *p, uint64_t len, uint64_t *pos,
                           int depth) {
  if (*pos >= len) {
    PyErr_SetString(PyExc_ValueError, "rt_frames: truncated frame");
    return nullptr;
  }
  uint8_t tag = p[(*pos)++];
  switch (tag) {
    case 'N':
      Py_RETURN_NONE;
    case 'T':
      Py_RETURN_TRUE;
    case 'F':
      Py_RETURN_FALSE;
    case 'I': {
      if (*pos + 8 > len) break;
      uint64_t bits = 0;
      std::memcpy(&bits, p + *pos, 8);
      *pos += 8;
      return PyLong_FromLongLong(static_cast<int64_t>(bits));
    }
    case 'D': {
      if (*pos + 8 > len) break;
      double d;
      std::memcpy(&d, p + *pos, 8);
      *pos += 8;
      return PyFloat_FromDouble(d);
    }
    case 'B':
    case 'S': {
      if (*pos + 4 > len) break;
      uint32_t n;
      std::memcpy(&n, p + *pos, 4);
      *pos += 4;
      if (*pos + n > len) break;
      const char *s = reinterpret_cast<const char *>(p + *pos);
      *pos += n;
      if (tag == 'B') return PyBytes_FromStringAndSize(s, n);
      PyObject *u = PyUnicode_DecodeUTF8(s, n, nullptr);
      if (!u) {
        PyErr_Clear();
        PyErr_SetString(PyExc_ValueError, "rt_frames: bad utf-8");
      }
      return u;
    }
    case 'L':
    case 'U': {
      if (depth >= RTF_MAX_DEPTH || *pos + 4 > len) break;
      uint32_t n;
      std::memcpy(&n, p + *pos, 4);
      *pos += 4;
      // a corrupted count must not pre-allocate gigabytes: each item
      // needs >= 1 byte of payload
      if (n > len - (*pos < len ? *pos : len) && n > 0) break;
      PyObject *seq = (tag == 'L') ? PyList_New(n) : PyTuple_New(n);
      if (!seq) return nullptr;
      for (uint32_t i = 0; i < n; i++) {
        PyObject *item = dec_value(p, len, pos, depth + 1);
        if (!item) {
          Py_DECREF(seq);
          return nullptr;
        }
        if (tag == 'L')
          PyList_SET_ITEM(seq, i, item);
        else
          PyTuple_SET_ITEM(seq, i, item);
      }
      return seq;
    }
    case 'M': {
      if (depth >= RTF_MAX_DEPTH || *pos + 4 > len) break;
      uint32_t n;
      std::memcpy(&n, p + *pos, 4);
      *pos += 4;
      PyObject *d = PyDict_New();
      if (!d) return nullptr;
      for (uint32_t i = 0; i < n; i++) {
        if (*pos >= len || (p[*pos] != 'S' && p[*pos] != 'B')) {
          Py_DECREF(d);
          PyErr_SetString(PyExc_ValueError,
                          "rt_frames: map key must be str or bytes");
          return nullptr;
        }
        PyObject *k = dec_value(p, len, pos, depth + 1);
        if (!k) {
          Py_DECREF(d);
          return nullptr;
        }
        PyObject *val = dec_value(p, len, pos, depth + 1);
        if (!val || PyDict_SetItem(d, k, val) != 0) {
          Py_XDECREF(val);
          Py_DECREF(k);
          Py_DECREF(d);
          return nullptr;
        }
        Py_DECREF(k);
        Py_DECREF(val);
      }
      return d;
    }
    default:
      PyErr_Format(PyExc_ValueError, "rt_frames: unknown value tag 0x%02x",
                   tag);
      return nullptr;
  }
  PyErr_SetString(PyExc_ValueError, "rt_frames: truncated frame");
  return nullptr;
}

// tagged payload (0x03 included) -> dict; raises ValueError on a
// malformed frame.  Accepts any buffer-protocol object.
extern "C" PyObject *rtf_decode_payload(PyObject *src) {
  Py_buffer view;
  if (PyObject_GetBuffer(src, &view, PyBUF_SIMPLE) != 0) return nullptr;
  const uint8_t *p = static_cast<const uint8_t *>(view.buf);
  uint64_t len = static_cast<uint64_t>(view.len);
  PyObject *out = nullptr;
  if (len < 1 || p[0] != 0x03) {
    PyErr_SetString(PyExc_ValueError, "rt_frames: not an rt-frames payload");
  } else {
    uint64_t pos = 1;
    out = dec_value(p, len, &pos, 0);
    if (out && pos != len) {
      Py_CLEAR(out);
      PyErr_SetString(PyExc_ValueError, "rt_frames: trailing bytes");
    }
    if (out && !PyDict_CheckExact(out)) {
      Py_CLEAR(out);
      PyErr_SetString(PyExc_ValueError,
                      "rt_frames: top-level value must be a map");
    }
  }
  PyBuffer_Release(&view);
  return out;
}

// drain the ring into one fresh bytes object (may be empty)
extern "C" PyObject *rtf_ring_drain_py(rtf_ring *r) {
  uint64_t bound = rtf_ring_pending(r);
  if (bound == 0) return PyBytes_FromStringAndSize(nullptr, 0);
  PyObject *out = PyBytes_FromStringAndSize(nullptr,
                                            static_cast<Py_ssize_t>(bound));
  if (!out) return nullptr;
  uint64_t n = rtf_ring_drain(
      r, reinterpret_cast<uint8_t *>(PyBytes_AS_STRING(out)), bound);
  if (n < bound &&
      _PyBytes_Resize(&out, static_cast<Py_ssize_t>(n)) != 0)
    return nullptr;
  return out;
}

#endif  // RTF_NO_PYTHON
