/* ray_api runtime implementation (reference analogue:
 * cpp/src/ray/runtime/local_mode_ray_runtime.cc +
 * object/local_mode_object_store.cc — task execution on an in-process
 * pool, objects in the node shm store via rt_store). */

#include "ray_api.h"

#include <fcntl.h>
#include <sys/mman.h>
#include <unistd.h>

#include <chrono>
#include <cstdio>

namespace ray {

Runtime &Runtime::Instance() {
  static Runtime rt;
  return rt;
}

void Runtime::Init(const std::string &store_name, uint64_t capacity) {
  std::lock_guard<std::mutex> lk(mu_);
  if (store_ != nullptr) return;
  if (store_name.empty()) {
    store_name_ = "/ray_api_" + std::to_string(getpid());
    owns_store_ = true;
    store_ = rt_store_create(store_name_.c_str(),
                             capacity ? capacity : (64u << 20), 4096);
  } else {
    /* attach to an existing node store: C++ tasks share the Python
     * workers' object plane */
    store_name_ = store_name;
    owns_store_ = false;
    store_ = rt_store_attach(store_name_.c_str());
  }
  if (store_ == nullptr) throw std::runtime_error("ray: store init failed");

  /* map the data plane (clients resolve offsets against their own map,
   * see rt_store.h header comment).  shm names may or may not carry a
   * leading slash (the Python side's arena names have none) — the
   * filesystem path wants exactly one separator. */
  map_bytes_ = rt_store_map_bytes(store_);
  std::string bare = store_name_;
  while (!bare.empty() && bare.front() == '/') bare.erase(0, 1);
  std::string shm_path = "/dev/shm/" + bare;
  int fd = open(shm_path.c_str(), O_RDWR);
  if (fd < 0) {
    rt_store_detach(store_);   /* roll back: never leave store_ set on a
                                  half-initialized runtime */
    if (owns_store_) rt_store_destroy(store_name_.c_str());
    store_ = nullptr;
    throw std::runtime_error("ray: shm open failed: " + shm_path);
  }
  base_ = static_cast<uint8_t *>(mmap(nullptr, map_bytes_,
                                      PROT_READ | PROT_WRITE,
                                      MAP_SHARED, fd, 0));
  close(fd);
  if (base_ == MAP_FAILED) {
    rt_store_detach(store_);
    if (owns_store_) rt_store_destroy(store_name_.c_str());
    store_ = nullptr;
    throw std::runtime_error("ray: mmap failed");
  }

  stopping_ = false;
  unsigned n = std::thread::hardware_concurrency();
  if (n < 2) n = 2;
  if (n > 8) n = 8;
  for (unsigned i = 0; i < n; i++) {
    workers_.emplace_back([this] { Worker(); });
  }
}

void Runtime::Shutdown() {
  {
    std::lock_guard<std::mutex> lk(mu_);
    if (store_ == nullptr) return;
    stopping_ = true;
  }
  cv_.notify_all();
  for (auto &t : workers_) t.join();
  workers_.clear();
  munmap(base_, map_bytes_);
  base_ = nullptr;
  rt_store_detach(store_);
  if (owns_store_) rt_store_destroy(store_name_.c_str());
  store_ = nullptr;
  {
    std::lock_guard<std::mutex> lk(err_mu_);
    errors_.clear();
  }
}

void Runtime::Worker() {
  for (;;) {
    std::function<void()> job;
    {
      std::unique_lock<std::mutex> lk(mu_);
      cv_.wait(lk, [this] { return stopping_ || !queue_.empty(); });
      if (stopping_ && queue_.empty()) return;
      job = std::move(queue_.front());
      queue_.pop();
    }
    job();
  }
}

ObjectID Runtime::NextId() {
  ObjectID id{};
  uint64_t c;
  {
    std::lock_guard<std::mutex> lk(mu_);
    c = ++counter_;
  }
  uint64_t pid = static_cast<uint64_t>(getpid());
  std::memcpy(id.data(), &c, sizeof(c));
  std::memcpy(id.data() + sizeof(c), &pid, sizeof(pid));
  id[RT_ID_SIZE - 1] = 0xC2;  /* marks C++-api-owned ids */
  return id;
}

void Runtime::StoreResult(const ObjectID &id,
                          const std::vector<uint8_t> &data) {
  /* layout: [u64 payload size][payload] — the header makes empty
   * payloads representable (the store itself has a min object size) */
  uint64_t n = data.size();
  int64_t off = rt_obj_create(store_, id.data(), sizeof(n) + n);
  if (off < 0) throw std::runtime_error("ray: object create failed");
  std::memcpy(base_ + off, &n, sizeof(n));
  if (n) std::memcpy(base_ + off + sizeof(n), data.data(), n);
  if (rt_obj_seal(store_, id.data()) != RT_OK)
    throw std::runtime_error("ray: seal failed");
}

ObjectID Runtime::PutBytes(const std::vector<uint8_t> &data) {
  if (store_ == nullptr) throw std::runtime_error("ray: not initialized");
  ObjectID id = NextId();
  StoreResult(id, data);
  return id;
}

std::vector<uint8_t> Runtime::GetBytes(const ObjectID &id,
                                       double timeout_s) {
  if (store_ == nullptr) throw std::runtime_error("ray: not initialized");
  auto deadline = std::chrono::steady_clock::now() +
                  std::chrono::duration<double>(timeout_s);
  for (;;) {
    uint64_t size = 0;
    int64_t off = rt_obj_get(store_, id.data(), &size);
    if (off >= 0) {
      uint64_t n = 0;
      std::memcpy(&n, base_ + off, sizeof(n));
      std::vector<uint8_t> out(base_ + off + sizeof(n),
                               base_ + off + sizeof(n) + n);
      rt_obj_release(store_, id.data());
      return out;
    }
    std::string err;
    if (FindError(id, &err))
      throw std::runtime_error("ray: task failed: " + err);
    if (std::chrono::steady_clock::now() > deadline)
      throw std::runtime_error("ray: Get timed out");
    std::this_thread::sleep_for(std::chrono::milliseconds(1));
  }
}

void Runtime::RecordError(const ObjectID &id, const std::string &what) {
  std::lock_guard<std::mutex> lk(err_mu_);
  errors_.emplace_back(id, what);
}

bool Runtime::FindError(const ObjectID &id, std::string *out) {
  std::lock_guard<std::mutex> lk(err_mu_);
  for (auto &e : errors_) {
    if (e.first == id) {
      *out = e.second;
      return true;
    }
  }
  return false;
}

ObjectID Runtime::Submit(std::function<std::vector<uint8_t>()> fn) {
  if (store_ == nullptr) throw std::runtime_error("ray: not initialized");
  ObjectID id = NextId();
  {
    std::lock_guard<std::mutex> lk(mu_);
    queue_.push([this, id, fn] {
      try {
        StoreResult(id, fn());
      } catch (const std::exception &e) {
        RecordError(id, e.what());
      } catch (...) {
        RecordError(id, "unknown error");
      }
    });
  }
  cv_.notify_one();
  return id;
}

}  // namespace ray
