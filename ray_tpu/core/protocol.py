"""Control-plane wire protocol: length-prefixed pickled messages.

The analogue of the reference's gRPC control plane (reference: src/ray/rpc/
+ src/ray/protobuf/*.proto).  v1 uses pickled dicts over TCP/Unix sockets —
the message *surface* mirrors the reference's RPC inventory (SURVEY.md
Appendix A); the encoding is an implementation detail behind this module so
it can be swapped for protobuf/gRPC without touching callers.

Bulk object payloads do NOT travel through this plane (they go through the
shared-memory store) except for inline objects ≤ max_direct_call_object_size,
mirroring the reference's inline-return rule (ray_config_def.h:212).
"""

from __future__ import annotations

import pickle
import socket
import struct
import threading
from typing import Any, Optional

_HDR = struct.Struct("<Q")


class ConnectionClosed(Exception):
    pass


class Connection:
    """Framed, thread-safe-send connection over a stream socket."""

    def __init__(self, sock: socket.socket):
        self.sock = sock
        self._send_lock = threading.Lock()
        self._recv_buf = b""
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1) \
            if sock.family != socket.AF_UNIX else None

    def send(self, msg: dict) -> None:
        data = pickle.dumps(msg, protocol=5)
        with self._send_lock:
            try:
                self.sock.sendall(_HDR.pack(len(data)) + data)
            except (BrokenPipeError, ConnectionResetError, OSError) as e:
                raise ConnectionClosed(str(e)) from e

    def recv(self, timeout: Optional[float] = None) -> dict:
        self.sock.settimeout(timeout)
        try:
            hdr = self._recv_exact(_HDR.size)
            (n,) = _HDR.unpack(hdr)
            data = self._recv_exact(n)
        except (ConnectionResetError, OSError) as e:
            if isinstance(e, socket.timeout):
                raise
            raise ConnectionClosed(str(e)) from e
        finally:
            self.sock.settimeout(None)
        return pickle.loads(data)

    def _recv_exact(self, n: int) -> bytes:
        chunks = []
        got = 0
        while got < n:
            chunk = self.sock.recv(min(n - got, 1 << 20))
            if not chunk:
                raise ConnectionClosed("peer closed")
            chunks.append(chunk)
            got += len(chunk)
        return b"".join(chunks)

    def close(self) -> None:
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.sock.close()


def connect(address: str, timeout: float = 30.0) -> Connection:
    if address.startswith("unix://"):
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(timeout)
        sock.connect(address[len("unix://"):])
    else:
        host, port = address.rsplit(":", 1)
        sock = socket.create_connection((host, int(port)), timeout=timeout)
    sock.settimeout(None)
    return Connection(sock)


def dumps_frame(msg: dict) -> bytes:
    data = pickle.dumps(msg, protocol=5)
    return _HDR.pack(len(data)) + data
