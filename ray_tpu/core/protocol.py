"""Control-plane wire protocol: length-prefixed pickled messages.

The analogue of the reference's gRPC control plane (reference: src/ray/rpc/
+ src/ray/protobuf/*.proto).  v1 uses pickled dicts over TCP/Unix sockets —
the message *surface* mirrors the reference's RPC inventory (SURVEY.md
Appendix A); the encoding is an implementation detail behind this module so
it can be swapped for protobuf/gRPC without touching callers.

Bulk object payloads do NOT travel through this plane (they go through the
shared-memory store) except for inline objects ≤ max_direct_call_object_size,
mirroring the reference's inline-return rule (ray_config_def.h:212).
"""

from __future__ import annotations

import pickle
import socket
import struct
import threading
from typing import Any, Optional

from ray_tpu.core import fault_injection as _fi

_HDR = struct.Struct("<Q")

# frame payload = 1 tag byte + body; self-describing so mixed encodings
# coexist on one socket (the reply always matches the request's encoding)
_TAG_PICKLE = b"\x00"
_TAG_PROTO = b"\x01"
# blob frames carry bulk bytes OUT-OF-BAND of the pickle: a small pickled
# meta dict + the raw payload appended verbatim.  Pickling a multi-MiB
# chunk costs a full extra copy per hop on both ends — on the object
# plane that copy dominates transfer CPU.
_TAG_BLOB = b"\x02"
_BLOB_META = struct.Struct("<I")


def encode_payload(msg: dict, encoding: str = "pickle") -> bytes:
    """dict → tagged frame payload. encoding="proto" uses the typed
    wire contract (core/schema.py over native/protos/ray_tpu.proto)."""
    if encoding == "proto":
        from ray_tpu.core import schema
        return _TAG_PROTO + schema.encode(msg)
    return _TAG_PICKLE + pickle.dumps(msg, protocol=5)


def decode_payload(data) -> dict:
    mv = memoryview(data)
    tag = bytes(mv[:1])
    if tag == _TAG_BLOB:
        (meta_len,) = _BLOB_META.unpack_from(mv, 1)
        msg = pickle.loads(mv[5:5 + meta_len])
        # zero extra copy: the consumer writes the view straight into
        # its destination buffer
        msg["data"] = mv[5 + meta_len:]
        return msg
    if tag == _TAG_PROTO:
        from ray_tpu.core import schema
        return schema.decode(bytes(mv[1:]))
    return pickle.loads(mv[1:])


def blob_frame_parts(meta: dict, data) -> list:
    """Length-prefixed blob frame as (header+meta, raw-data) parts —
    callers concatenate/queue without ever pickling `data`."""
    meta_b = pickle.dumps(meta, protocol=5)
    total = 1 + _BLOB_META.size + len(meta_b) + len(data)
    head = b"".join((_HDR.pack(total), _TAG_BLOB,
                     _BLOB_META.pack(len(meta_b)), meta_b))
    return [head, data]


def payload_encoding(data: bytes) -> str:
    return "proto" if data[:1] == _TAG_PROTO else "pickle"


def default_encoding(remote: bool = False) -> str:
    """Wire encoding defaults, overridable by RAY_TPU_WIRE_ENCODING.

    The typed protobuf contract is the DEFAULT on REMOTE links — the
    node↔node and node↔head channels that actually cross machines and
    need a language-neutral, evolvable schema (reference: every
    control-plane RPC is a typed proto, src/ray/protobuf/).  Local
    loopback links (a driver or worker talking to its own node) default
    to pickle: same process image on both ends, and python-side proto
    encode costs ~3-6x per message, which is pure overhead on-host.
    Frames are self-describing, so mixed encodings interoperate."""
    import os
    forced = os.environ.get("RAY_TPU_WIRE_ENCODING", "").lower()
    if forced in ("pickle", "proto"):
        return forced
    return "proto" if remote else "pickle"


class ConnectionClosed(Exception):
    pass


class Connection:
    """Framed, thread-safe-send connection over a stream socket."""

    def __init__(self, sock: socket.socket, encoding: Optional[str] = None,
                 label: Optional[tuple] = None):
        self.sock = sock
        self.encoding = encoding or default_encoding()
        # chaos-plane link label (core/fault_injection.py): who talks to
        # whom, attached at creation; only read when a plan is installed
        self.fi_label = label or ("conn", "?")
        self._send_lock = threading.Lock()
        self._recv_buf = b""
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1) \
            if sock.family != socket.AF_UNIX else None

    def send(self, msg: dict) -> None:
        repeats = 1
        if _fi._active is not None:
            v = _fi._active.message_verdict("send", self.fi_label, msg)
            if v == "drop":
                return
            if v == "dup":
                repeats = 2
            elif type(v) is tuple:
                _fi.apply_delay(v[1])
        data = encode_payload(msg, self.encoding)
        with self._send_lock:
            try:
                for _ in range(repeats):
                    self.sock.sendall(_HDR.pack(len(data)) + data)
            except (BrokenPipeError, ConnectionResetError, OSError) as e:
                raise ConnectionClosed(str(e)) from e

    def send_blob(self, meta: dict, data) -> None:
        if _fi._active is not None:
            v = _fi._active.message_verdict("send", self.fi_label, meta)
            if v == "drop":
                return
            if type(v) is tuple:
                _fi.apply_delay(v[1])
        payload = b"".join(blob_frame_parts(meta, data))
        with self._send_lock:
            try:
                self.sock.sendall(payload)
            except (BrokenPipeError, ConnectionResetError, OSError) as e:
                raise ConnectionClosed(str(e)) from e

    def send_batch(self, msgs: list) -> None:
        """Frame several messages and write them in one syscall — the
        per-message sendall otherwise costs a syscall + GIL drop + a
        receiver wakeup each (hot on the task completion path)."""
        if _fi._active is not None:
            msgs = _chaos_filter(self.fi_label, msgs)
            if not msgs:
                return
        payload = b"".join(
            _HDR.pack(len(d)) + d
            for d in (encode_payload(m, self.encoding) for m in msgs))
        with self._send_lock:
            try:
                self.sock.sendall(payload)
            except (BrokenPipeError, ConnectionResetError, OSError) as e:
                raise ConnectionClosed(str(e)) from e

    def recv(self, timeout: Optional[float] = None) -> dict:
        while True:
            self.sock.settimeout(timeout)
            try:
                hdr = self._recv_exact(_HDR.size)
                (n,) = _HDR.unpack(hdr)
                data = self._recv_exact(n)
            except (ConnectionResetError, OSError) as e:
                if isinstance(e, socket.timeout):
                    raise
                raise ConnectionClosed(str(e)) from e
            finally:
                self.sock.settimeout(None)
            msg = decode_payload(data)
            if _fi._active is not None:
                v = _fi._active.message_verdict("recv", self.fi_label, msg)
                if v == "drop":
                    continue   # the frame "never arrived"
                if type(v) is tuple:
                    _fi.apply_delay(v[1])
            return msg

    def _recv_exact(self, n: int) -> bytes:
        chunks = []
        got = 0
        while got < n:
            chunk = self.sock.recv(min(n - got, 1 << 20))
            if not chunk:
                raise ConnectionClosed("peer closed")
            chunks.append(chunk)
            got += len(chunk)
        return b"".join(chunks)

    def close(self) -> None:
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.sock.close()


def _chaos_filter(label: tuple, msgs: list) -> list:
    """Per-message chaos verdicts over a batch (drop removes, dup
    doubles, delay stalls the whole batch — batches share a syscall, so
    a delayed member delays its neighbors exactly like a real stall)."""
    plan = _fi._active
    out = []
    for m in msgs:
        v = plan.message_verdict("send", label, m)
        if v == "drop":
            continue
        if type(v) is tuple:
            _fi.apply_delay(v[1])
        out.append(m)
        if v == "dup":
            out.append(m)
    return out


def connect(address: str, timeout: float = 30.0,
            remote: bool = False,
            label: Optional[tuple] = None) -> Connection:
    from ray_tpu.core import local_lane
    if local_lane.enabled():
        svc = local_lane.lookup(address)
        if svc is not None:
            # same-process peer: hand messages across threads instead of
            # through the socket stack.  Inter-service links (remote=True)
            # isolate each message with a pickle roundtrip — both ends
            # mutate and retain specs — which is still far cheaper than
            # encode+syscall+select+decode.
            return local_lane.LaneConnection(svc, copy=remote, label=label)
    if address.startswith("unix://"):
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(timeout)
        sock.connect(address[len("unix://"):])
    else:
        from ray_tpu.core import grpc_transport
        if grpc_transport.transport() == "grpc":
            # RAY_TPU_RPC=grpc: the frame stream rides a gRPC bidi
            # method (reference: src/ray/rpc/grpc_server.h hosting)
            sock = grpc_transport.grpc_connect_socket(address,
                                                      timeout=timeout)
            return Connection(sock, encoding=default_encoding(remote),
                              label=label)
        host, port = address.rsplit(":", 1)
        if remote and host in ("127.0.0.1", "localhost", "::1"):
            # the proto wire buys language-neutrality across MACHINES;
            # a loopback "remote" link (virtual clusters, single-host
            # multi-node) pays its 3-6x python encode cost for nothing
            remote = False
        sock = socket.create_connection((host, int(port)), timeout=timeout)
    sock.settimeout(None)
    return Connection(sock, encoding=default_encoding(remote), label=label)


def dumps_frame(msg: dict, encoding: str = "pickle") -> bytes:
    data = encode_payload(msg, encoding)
    return _HDR.pack(len(data)) + data
