"""Control-plane wire protocol: length-prefixed pickled messages.

The analogue of the reference's gRPC control plane (reference: src/ray/rpc/
+ src/ray/protobuf/*.proto).  v1 uses pickled dicts over TCP/Unix sockets —
the message *surface* mirrors the reference's RPC inventory (SURVEY.md
Appendix A); the encoding is an implementation detail behind this module so
it can be swapped for protobuf/gRPC without touching callers.

Bulk object payloads do NOT travel through this plane (they go through the
shared-memory store) except for inline objects ≤ max_direct_call_object_size,
mirroring the reference's inline-return rule (ray_config_def.h:212).
"""

from __future__ import annotations

import os
import pickle
import socket
import struct
import threading
from typing import Any, Optional

from ray_tpu.core import fault_injection as _fi
from ray_tpu.core import rt_frames as _rtf
from ray_tpu.core.rt_frames import (py_decode_payload as _rtf_py_decode,
                                    py_stamp as _rtf_py_stamp)

_HDR = struct.Struct("<Q")

# frame payload = 1 tag byte + body; self-describing so mixed encodings
# coexist on one socket (the reply always matches the request's encoding)
_TAG_PICKLE = b"\x00"
_TAG_PROTO = b"\x01"
# native dispatch frames (core/rt_frames.py + native/src/rt_frames.cc):
# the hot-loop codec — eligible control messages are framed in one C
# call when the codec is armed; the pure-Python decoder keeps peers
# interoperable when this process runs the fallback
_TAG_RTF = b"\x03"
# blob frames carry bulk bytes OUT-OF-BAND of the pickle: a small pickled
# meta dict + the raw payload appended verbatim.  Pickling a multi-MiB
# chunk costs a full extra copy per hop on both ends — on the object
# plane that copy dominates transfer CPU.
_TAG_BLOB = b"\x02"
_BLOB_META = struct.Struct("<I")


def encode_payload(msg: dict, encoding: str = "pickle") -> bytes:
    """dict → tagged frame payload. encoding="proto" uses the typed
    wire contract (core/schema.py over native/protos/ray_tpu.proto)."""
    if encoding == "proto":
        from ray_tpu.core import schema
        return _TAG_PROTO + schema.encode(msg)
    return _TAG_PICKLE + pickle.dumps(msg, protocol=5)


def decode_payload(data) -> dict:
    mv = memoryview(data)
    tag = bytes(mv[:1])
    if tag == _TAG_RTF:
        codec = _rtf._active
        if codec is not None:
            return codec.decode_payload(mv)
        return _rtf_py_decode(mv)
    if tag == _TAG_BLOB:
        (meta_len,) = _BLOB_META.unpack_from(mv, 1)
        msg = pickle.loads(mv[5:5 + meta_len])
        # zero extra copy: the consumer writes the view straight into
        # its destination buffer
        msg["data"] = mv[5 + meta_len:]
        return msg
    if tag == _TAG_PROTO:
        from ray_tpu.core import schema
        return schema.decode(bytes(mv[1:]))
    return pickle.loads(mv[1:])


def blob_frame_parts(meta: dict, data) -> list:
    """Length-prefixed blob frame as (header+meta, raw-data) parts —
    callers concatenate/queue without ever pickling `data`."""
    meta_b = pickle.dumps(meta, protocol=5)
    total = 1 + _BLOB_META.size + len(meta_b) + len(data)
    head = b"".join((_HDR.pack(total), _TAG_BLOB,
                     _BLOB_META.pack(len(meta_b)), meta_b))
    return [head, data]


def payload_encoding(data: bytes) -> str:
    return "proto" if data[:1] == _TAG_PROTO else "pickle"


def default_encoding(remote: bool = False) -> str:
    """Wire encoding defaults, overridable by RAY_TPU_WIRE_ENCODING.

    The typed protobuf contract is the DEFAULT on REMOTE links — the
    node↔node and node↔head channels that actually cross machines and
    need a language-neutral, evolvable schema (reference: every
    control-plane RPC is a typed proto, src/ray/protobuf/).  Local
    loopback links (a driver or worker talking to its own node) default
    to pickle: same process image on both ends, and python-side proto
    encode costs ~3-6x per message, which is pure overhead on-host.
    Frames are self-describing, so mixed encodings interoperate."""
    forced = os.environ.get("RAY_TPU_WIRE_ENCODING", "").lower()
    if forced in ("pickle", "proto"):
        return forced
    return "proto" if remote else "pickle"


class ConnectionClosed(Exception):
    pass


# Ring parking cap: the combining ring earns its keep on small control
# frames (a task_done return is ~200 B).  A parked frame pays two extra
# full memcpys — commit into the slab, then the drain copy, which runs
# with BOTH the GIL (ctypes PyDLL) and the send lock held — where the
# direct path is one sendall with the GIL released for the syscall.
# Past a few KiB that trade is a strict loss, so bigger frames always
# take the locked direct path.
_RING_PARK_MAX = 32 << 10


class Connection:
    """Framed, thread-safe-send connection over a stream socket."""

    def __init__(self, sock: socket.socket, encoding: Optional[str] = None,
                 label: Optional[tuple] = None):
        self.sock = sock
        self.encoding = encoding or default_encoding()
        # chaos-plane link label (core/fault_injection.py): who talks to
        # whom, attached at creation; only read when a plan is installed
        self.fi_label = label or ("conn", "?")
        self._send_lock = threading.Lock()
        self._recv_buf = b""
        # native send-combining ring (core/rt_frames.py): armed by
        # enable_ring() on channels with concurrent senders
        self._ring = None
        # set by a locked sender whose frame cannot park (ring full, or
        # larger than a ring record): _ring_send refuses new parks so
        # concurrent senders queue on the send lock instead, the ring
        # drains DRY in bounded time, and the waiting frame writes
        # directly.  The FIFO contract is for SERIALIZED senders
        # (client.py's _auto_send_lock batching): a frame sent after a
        # previous send() returned is never reordered before it — the
        # locked path drains every already-parked frame first and
        # parks behind any it cannot drain.  Frames from senders
        # racing each other carry no order: a park can slip in between
        # the dry drain and the direct write and go out after it.
        # Benign races: a stale False parks one more frame (drained in
        # the same loop); a stale True queues a parkable frame on the
        # lock (slower, never reordered).
        self._direct_wait = False
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1) \
            if sock.family != socket.AF_UNIX else None

    def enable_ring(self, capacity: int = 1 << 20) -> None:
        """Arm the MPSC ready-ring on this connection: CONTENDED
        senders (driver threads mid-burst, actor executor threads on
        the done-return leg) push completed frames into the C ring and
        whoever holds the send lock drains the batch in one syscall —
        no lock convoy, no per-message sendall.  An uncontended send
        bypasses the ring entirely (_ring_send).  No-op without the
        native codec, or with RAY_TPU_NATIVE_RING=0 (A/B knob)."""
        if os.environ.get("RAY_TPU_NATIVE_RING", "1").lower() \
                in ("0", "false", "no"):
            return
        codec = _rtf._active
        if codec is not None and self.sock is not None \
                and self._ring is None:
            self._ring = codec.make_ring(capacity)

    def _flush_ring(self) -> None:
        """Drain committed ring frames whenever the send lock can be
        had.  EVERY path that releases the send lock must run this
        loop afterwards — a frame pushed while some other thread was
        inside its critical section (which pre-drained BEFORE the push
        landed) would otherwise sit stranded until the next send on
        this connection; the post-release re-check guarantees the last
        releaser sweeps it out.  The non-blocking acquire keeps this a
        combining protocol, not a second lock convoy."""
        ring = self._ring
        if ring is None:
            return
        lock = self._send_lock
        while ring.pending() and lock.acquire(blocking=False):
            try:
                out = ring.drain()
                if out:
                    try:
                        self.sock.sendall(out)
                    except (BrokenPipeError, ConnectionResetError,
                            OSError) as e:
                        raise ConnectionClosed(str(e)) from e
            finally:
                lock.release()
            if not out:
                # head is a mid-commit reservation: yield the core so
                # the producer can finish instead of spinning it out
                # (the in-lock drain loops do the same)
                os.sched_yield()

    def _ring_send(self, payload) -> bool:
        """Contended-send combining.  With the send lock FREE the ring
        round trip (reserve + commit memcpy, then drain memcpy) is pure
        overhead over a direct locked write — measured ~10% of
        tasks_sync on a 1-core box where senders never actually overlap
        — so an uncontended send returns False and the caller writes
        under the lock.  A CONTENDED send parks its preassembled frame
        in the MPSC ring for the lock holder (or this thread's
        post-release sweep) to batch out in one syscall."""
        ring = self._ring
        if ring is None or self._direct_wait \
                or len(payload) > _RING_PARK_MAX \
                or not self._send_lock.locked():
            return False
        if not ring.push(payload):
            return False   # full: caller blocks on the locked path
        self._flush_ring()
        return True

    def send(self, msg: dict) -> None:
        repeats = 1
        if _fi._active is not None:
            v = _fi._active.message_verdict("send", self.fi_label, msg)
            if v == "drop":
                return
            if v == "dup":
                repeats = 2
            elif type(v) is tuple:
                _fi.apply_delay(v[1])
        payload = None
        codec = _rtf._active
        if codec is not None and self.encoding == "pickle":
            payload = codec.encode_frame(msg)
            if payload is not None and repeats == 2:
                payload += payload
        if payload is None:
            data = encode_payload(msg, self.encoding)
            payload = (_HDR.pack(len(data)) + data) * repeats
        if self._ring_send(payload):
            return
        with self._send_lock:
            try:
                ring = self._ring
                if ring is not None:
                    out = ring.drain()
                    if out:
                        self.sock.sendall(out)
                    # An uncommitted reservation at the ring head
                    # hides parked frames behind it: OUR frame must
                    # queue after them — wire FIFO is cross-thread
                    # here (client.py's _auto_send_lock serializes
                    # actor-call batches across threads and relies on
                    # arrival order).  Park ours too; if it cannot
                    # park (ring full, or larger than a ring record),
                    # _direct_wait stops NEW parks so the ring drains
                    # dry in bounded time — concurrent senders queue
                    # on the send lock behind us instead of refilling
                    # the ring under our feet.  (This block is
                    # deliberately inlined in all three senders: a
                    # helper doing I/O under the wire lock would need
                    # a fresh lint-baseline suppression per the locks
                    # pass's helper expansion.)
                    while ring.pending():
                        if len(payload) <= _RING_PARK_MAX \
                                and ring.push(payload):
                            payload = None
                            break
                        self._direct_wait = True
                        try:
                            while ring.pending():
                                out = ring.drain()
                                if out:
                                    self.sock.sendall(out)
                                else:
                                    os.sched_yield()
                        finally:
                            self._direct_wait = False
                        break
                if payload is not None:
                    self.sock.sendall(payload)
            except (BrokenPipeError, ConnectionResetError, OSError) as e:
                raise ConnectionClosed(str(e)) from e
        self._flush_ring()

    def send_blob(self, meta: dict, data) -> None:
        if _fi._active is not None:
            v = _fi._active.message_verdict("send", self.fi_label, meta)
            if v == "drop":
                return
            if type(v) is tuple:
                _fi.apply_delay(v[1])
        payload = b"".join(blob_frame_parts(meta, data))
        if self._ring_send(payload):
            return
        with self._send_lock:
            try:
                ring = self._ring
                if ring is not None:
                    out = ring.drain()
                    if out:
                        self.sock.sendall(out)
                    # cross-thread wire FIFO (see send): park ours
                    # behind any pending frames; a blob too big for a
                    # ring record drains the ring dry via
                    # _direct_wait instead of starving on refill.
                    while ring.pending():
                        if len(payload) <= _RING_PARK_MAX \
                                and ring.push(payload):
                            payload = None
                            break
                        self._direct_wait = True
                        try:
                            while ring.pending():
                                out = ring.drain()
                                if out:
                                    self.sock.sendall(out)
                                else:
                                    os.sched_yield()
                        finally:
                            self._direct_wait = False
                        break
                if payload is not None:
                    self.sock.sendall(payload)
            except (BrokenPipeError, ConnectionResetError, OSError) as e:
                raise ConnectionClosed(str(e)) from e
        self._flush_ring()

    def send_batch(self, msgs: list) -> None:
        """Frame several messages and write them in one syscall — the
        per-message sendall otherwise costs a syscall + GIL drop + a
        receiver wakeup each (hot on the task completion path)."""
        if _fi._active is not None:
            msgs = _chaos_filter(self.fi_label, msgs)
            if not msgs:
                return
        codec = _rtf._active
        if codec is not None and self.encoding == "pickle":
            parts = []
            for m in msgs:
                f = codec.encode_frame(m)
                if f is None:
                    d = encode_payload(m, self.encoding)
                    f = _HDR.pack(len(d)) + d
                parts.append(f)
        else:
            parts = [_HDR.pack(len(d)) + d
                     for d in (encode_payload(m, self.encoding)
                               for m in msgs)]
        # the whole batch is ONE payload (and ONE ring record when it
        # parks), so its frames stay contiguous and ordered
        payload = b"".join(parts)
        if self._ring_send(payload):
            return
        with self._send_lock:
            try:
                ring = self._ring
                if ring is not None:
                    out = ring.drain()
                    if out:
                        self.sock.sendall(out)
                    # cross-thread wire FIFO (see send): park the
                    # batch behind any pending frames; an oversized
                    # batch drains the ring dry via _direct_wait
                    # instead of starving on refill.
                    while ring.pending():
                        if len(payload) <= _RING_PARK_MAX \
                                and ring.push(payload):
                            payload = None
                            break
                        self._direct_wait = True
                        try:
                            while ring.pending():
                                out = ring.drain()
                                if out:
                                    self.sock.sendall(out)
                                else:
                                    os.sched_yield()
                        finally:
                            self._direct_wait = False
                        break
                if payload is not None:
                    self.sock.sendall(payload)
            except (BrokenPipeError, ConnectionResetError, OSError) as e:
                raise ConnectionClosed(str(e)) from e
        self._flush_ring()

    def recv(self, timeout: Optional[float] = None) -> dict:
        while True:
            self.sock.settimeout(timeout)
            try:
                hdr = self._recv_exact(_HDR.size)
                (n,) = _HDR.unpack(hdr)
                data = self._recv_exact(n)
            except (ConnectionResetError, OSError) as e:
                if isinstance(e, socket.timeout):
                    raise
                raise ConnectionClosed(str(e)) from e
            finally:
                self.sock.settimeout(None)
            msg = decode_payload(data)
            if _fi._active is not None:
                v = _fi._active.message_verdict("recv", self.fi_label, msg)
                if v == "drop":
                    continue   # the frame "never arrived"
                if type(v) is tuple:
                    _fi.apply_delay(v[1])
            return msg

    def _recv_exact(self, n: int) -> bytes:
        chunks = []
        got = 0
        while got < n:
            chunk = self.sock.recv(min(n - got, 1 << 20))
            if not chunk:
                raise ConnectionClosed("peer closed")
            chunks.append(chunk)
            got += len(chunk)
        return b"".join(chunks)

    def close(self) -> None:
        try:
            self.sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        self.sock.close()


def _chaos_filter(label: tuple, msgs: list) -> list:
    """Per-message chaos verdicts over a batch (drop removes, dup
    doubles, delay stalls the whole batch — batches share a syscall, so
    a delayed member delays its neighbors exactly like a real stall)."""
    plan = _fi._active
    out = []
    for m in msgs:
        v = plan.message_verdict("send", label, m)
        if v == "drop":
            continue
        if type(v) is tuple:
            _fi.apply_delay(v[1])
        out.append(m)
        if v == "dup":
            out.append(m)
    return out


def connect(address: str, timeout: float = 30.0,
            remote: bool = False,
            label: Optional[tuple] = None) -> Connection:
    from ray_tpu.core import local_lane
    if local_lane.enabled():
        svc = local_lane.lookup(address)
        if svc is not None:
            # same-process peer: hand messages across threads instead of
            # through the socket stack.  Inter-service links (remote=True)
            # isolate each message with a pickle roundtrip — both ends
            # mutate and retain specs — which is still far cheaper than
            # encode+syscall+select+decode.
            return local_lane.LaneConnection(svc, copy=remote, label=label)
    if address.startswith("unix://"):
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(timeout)
        sock.connect(address[len("unix://"):])
    else:
        from ray_tpu.core import grpc_transport
        if grpc_transport.transport() == "grpc":
            # RAY_TPU_RPC=grpc: the frame stream rides a gRPC bidi
            # method (reference: src/ray/rpc/grpc_server.h hosting)
            sock = grpc_transport.grpc_connect_socket(address,
                                                      timeout=timeout)
            return Connection(sock, encoding=default_encoding(remote),
                              label=label)
        host, port = address.rsplit(":", 1)
        if remote and host in ("127.0.0.1", "localhost", "::1"):
            # the proto wire buys language-neutrality across MACHINES;
            # a loopback "remote" link (virtual clusters, single-host
            # multi-node) pays its 3-6x python encode cost for nothing
            remote = False
        sock = socket.create_connection((host, int(port)), timeout=timeout)
    sock.settimeout(None)
    return Connection(sock, encoding=default_encoding(remote), label=label)


def dumps_frame(msg: dict, encoding: str = "pickle",
                stamp: Optional[str] = None) -> bytes:
    """Complete wire frame (header + tagged payload).  With the native
    codec armed, eligible messages are framed — length prefix, body,
    and the optional flight-recorder ``stamp`` fold — in one C call.
    ``stamp`` callers gate on the recorder being armed AND the spec
    carrying an ``"fr"`` record; when the native encode falls back to
    pickle the stamp is applied Python-side so it is never lost."""
    if encoding == "pickle":
        codec = _rtf._active
        if codec is not None:
            frame = codec.encode_frame(msg, stamp)
            if frame is not None:
                return frame
    if stamp is not None:
        _rtf_py_stamp(msg, stamp)
    data = encode_payload(msg, encoding)
    return _HDR.pack(len(data)) + data
