"""Process-wide runtime: submission side of the core API.

The analogue of the reference CoreWorker's submission half + worker.py
globals (reference: python/ray/_private/worker.py global_worker,
core_worker.cc SubmitTask:1815, CreateActor, SubmitActorTask) — holds the
node-client connection, generates deterministic task/object ids, exports
functions once, and owns the driver-side helper threads (node service,
in-process TPU executor, log monitor).
"""

from __future__ import annotations

import atexit
import concurrent.futures
import contextlib
import hashlib
import os
import threading
import time
import contextvars
import uuid
from typing import Any, Optional, Sequence

import cloudpickle

from ray_tpu._config import RayTpuConfig, set_config
from ray_tpu.core import flight_recorder as _fr
from ray_tpu.core.client import NodeClient, TaskError  # noqa: F401
from ray_tpu.core.executor import Executor, _ArgSlot
from ray_tpu.core.ids import (ActorID, JobID, ObjectID, TaskID, _Counter)
from ray_tpu.core.object_ref import ObjectRef, ObjectRefGenerator
from ray_tpu.core.serialization import get_context

# --------------------------------------------------------------------------
# per-task execution context.  Contextvars, not threading.local: async
# actors interleave many in-flight calls as coroutines on ONE event-loop
# thread (reference: fiber.h async actors), and each asyncio.Task carries
# its own Context copy — thread-locals would make interleaved calls stomp
# each other's task ids and put counters.  Plain threads still get
# per-thread isolation (each thread has its own context).


class _TaskContext:
    task_id = contextvars.ContextVar("raytpu_task_id", default=None)
    put_counter = contextvars.ContextVar("raytpu_put_counter", default=0)
    task_counter = contextvars.ContextVar("raytpu_task_counter", default=0)


_ctx = _TaskContext()


@contextlib.contextmanager
def task_context(task_id: TaskID):
    t1 = _TaskContext.task_id.set(task_id)
    t2 = _TaskContext.put_counter.set(0)
    t3 = _TaskContext.task_counter.set(0)
    try:
        yield
    finally:
        _TaskContext.task_id.reset(t1)
        _TaskContext.put_counter.reset(t2)
        _TaskContext.task_counter.reset(t3)


def current_task_id() -> TaskID:
    tid = _TaskContext.task_id.get()
    if tid is None:
        # thread outside any task: derive a stable per-thread driver task id
        tid = TaskID(hashlib.sha1(
            f"thread-{threading.get_ident()}-{uuid.uuid4().hex}".encode()
        ).digest()[:20] + JobID.from_int(0).binary())
        _TaskContext.task_id.set(tid)
    return tid


# --------------------------------------------------------------------------


class Runtime:
    def __init__(self, client: NodeClient, mode: str,
                 executor: Optional[Executor] = None,
                 namespace: str = "default"):
        self.client = client
        self.mode = mode  # "driver" | "worker"
        self.executor = executor
        self.namespace = namespace or "default"
        self.job_id = JobID.from_int(1)
        self._exported: set[str] = set()
        self._export_lock = threading.Lock()
        self._actor_counter = _Counter()
        self._serde = get_context()
        # prepared runtime envs memoized per canonical input: re-zipping
        # / re-checking the KV on EVERY submission would dominate the
        # task hot path for working_dir users
        self._env_cache: dict[str, tuple] = {}
        self._env_cache_lock = threading.Lock()
        self._futures_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=4, thread_name_prefix="raytpu-future")
        # distributed refcount: when this process's last ref to an object
        # dies, tell the node so the owner's storage can be reclaimed
        # (reference: reference_count.h local-count half)
        from ray_tpu.core.object_ref import get_tracker
        get_tracker().set_sink(self._release_refs)
        # driver-owned helpers (populated by init())
        self.node_service = None
        self.tpu_executor_client: Optional[NodeClient] = None
        self.tpu_executor_thread: Optional[threading.Thread] = None
        self.session_dir: str = ""

    # ---------------------------------------------------------- functions

    def export_function(self, fn: Any) -> str:
        pickled = cloudpickle.dumps(fn)
        fid = hashlib.sha1(pickled).hexdigest()
        with self._export_lock:
            if fid not in self._exported:
                self.client.request({"t": "register_function",
                                     "function_id": fid, "pickled": pickled})
                self._exported.add(fid)
        return fid

    # ---------------------------------------------------------- task spec

    def _prepare_args(self, args: Sequence, kwargs: dict, spec: dict) -> None:
        """Top-level ObjectRefs become resolved-by-executor slots; nested
        refs travel as refs (reference: LocalDependencyResolver,
        transport/dependency_resolver.cc)."""
        ref_ids: list[bytes] = []

        def slot(v):
            if isinstance(v, ObjectRef):
                ref_ids.append(v.binary())
                return _ArgSlot(len(ref_ids) - 1)
            return v

        new_args = [slot(a) for a in args]
        new_kwargs = {k: slot(v) for k, v in kwargs.items()}
        so = self._serde.serialize((new_args, new_kwargs))
        data = so.to_bytes()
        inline_limit = self.client.config_dict["max_direct_call_object_size"]
        if len(data) > inline_limit:
            blob_id = ObjectID.for_put(current_task_id(),
                                       self._next_put_index())
            self.client.put_serialized(blob_id, so)
            spec["arg_blob"] = blob_id.binary()
            spec["args"] = b""
            ref_ids.append(blob_id.binary())
        else:
            spec["args"] = data
        spec["arg_ids"] = ref_ids

    def _prepare_env(self, runtime_env: dict) -> tuple:
        """validate + prepare + hash, memoized on the raw input (same
        env dict on every .remote() must not re-zip working_dir)."""
        import json as _json

        from ray_tpu.runtime_env import env_hash, prepare, validate
        try:
            key = _json.dumps(runtime_env, sort_keys=True, default=str)
        except TypeError:
            key = repr(sorted(runtime_env.items()))
        with self._env_cache_lock:
            hit = self._env_cache.get(key)
        if hit is not None:
            return hit
        prepared = prepare(validate(dict(runtime_env)), self.client)
        out = (prepared, env_hash(prepared))
        with self._env_cache_lock:
            self._env_cache[key] = out
        return out

    def _next_put_index(self) -> int:
        n = _TaskContext.put_counter.get() + 1
        _TaskContext.put_counter.set(n)
        return n

    def _next_task_id(self) -> TaskID:
        n = _TaskContext.task_counter.get() + 1
        _TaskContext.task_counter.set(n)
        return TaskID.of(current_task_id(), n)

    # ------------------------------------------------------------- submit

    def make_task_template(self, function_id: str, *,
                           name: str = "", num_returns=1,
                           resources: Optional[dict] = None,
                           num_tpus: float = 0, max_retries: int = 0,
                           placement_group=None, runtime_env=None) -> dict:
        """Static spec fields resolved ONCE per RemoteFunction: env
        preparation/hashing, resource map, descriptor — the per-call
        path only stamps ids and args (reference: the task spec
        builder caches the serialized function descriptor,
        _raylet.pyx TaskSpecification reuse)."""
        env_h = ""
        if runtime_env:
            runtime_env, env_h = self._prepare_env(runtime_env)
        return {
            "task_id": b"",
            "kind": "task",
            "name": name,
            "function_id": function_id,
            "num_returns": num_returns,
            "return_ids": (),
            "resources": resources or {},
            "num_tpus": num_tpus,
            "max_retries": max_retries,
            "placement_group": placement_group,
            "runtime_env": runtime_env,
            "env_hash": env_h,
            # the SUBMITTER owns the returns (reference: ownership model,
            # core_worker.h — the caller, not the executor, owns results)
            "owner": self.client.worker_id,
        }

    def submit_task_template(self, template: dict, args, kwargs):
        task_id = self._next_task_id()
        num_returns = template["num_returns"]
        n_ret = 1 if num_returns == "dynamic" else max(num_returns, 0)
        returns = [ObjectID.for_task_return(task_id, i + 1)
                   for i in range(max(n_ret, 1))]
        spec = dict(template)
        spec["task_id"] = task_id.binary()
        spec["return_ids"] = [o.binary() for o in returns]
        if _fr._active is not None:
            # flight recorder: open the lifecycle record; "encode" below
            # isolates client-side arg serialization from the wire hop
            _fr._active.start(spec)
        from ray_tpu.util.tracing import tracing_enabled
        if tracing_enabled():
            from ray_tpu.util.tracing import start_span
            # the submit span is the PARENT of the worker's execute span
            # (reference: tracing_helper injects the client span's
            # context), so its context — not the ambient one — goes
            # into the spec
            with start_span(f"task::{spec['name']}.remote", kind="client",
                            attributes={"task_id": task_id.hex()}) as sp:
                if sp:
                    spec["trace_ctx"] = {"trace_id": sp["trace_id"],
                                         "span_id": sp["span_id"]}
                self._prepare_args(args, kwargs, spec)
                if _fr._active is not None:
                    _fr._active.stamp(spec, "encode")
                self.client.send_soon({"t": "submit_task", "spec": spec})
        else:
            self._prepare_args(args, kwargs, spec)
            if _fr._active is not None:
                _fr._active.stamp(spec, "encode")
            self.client.send_soon({"t": "submit_task", "spec": spec})
        owner = self.client.worker_id
        refs = [ObjectRef(o, owner=owner) for o in returns]
        if num_returns == "dynamic" or num_returns == 1:
            return refs[0]
        if num_returns == 0:
            return None
        return refs

    def submit_task(self, function_id: str, args, kwargs, *,
                    name: str = "", num_returns=1,
                    resources: Optional[dict] = None,
                    num_tpus: float = 0, max_retries: int = 0,
                    placement_group=None, runtime_env=None):
        template = self.make_task_template(
            function_id, name=name, num_returns=num_returns,
            resources=resources, num_tpus=num_tpus, max_retries=max_retries,
            placement_group=placement_group, runtime_env=runtime_env)
        return self.submit_task_template(template, args, kwargs)

    # ------------------------------------------------------------- actors

    def create_actor(self, function_id: str, args, kwargs, *,
                     class_name: str, methods: list[str],
                     name: str = "", namespace: str = "",
                     get_if_exists: bool = False,
                     resources: Optional[dict] = None, num_tpus: float = 0,
                     max_restarts: int = 0, max_concurrency: int = 1,
                     concurrency_groups: Optional[dict] = None,
                     placement_group=None, runtime_env=None) -> ActorID:
        if runtime_env:
            runtime_env, _ = self._prepare_env(runtime_env)
        actor_id = ActorID.of(self.job_id, current_task_id(),
                              self._actor_counter.next())
        task_id = self._next_task_id()
        spec = {
            "task_id": task_id.binary(),
            "kind": "actor_create",
            "actor_id": actor_id.binary(),
            "name": name,
            "namespace": namespace,
            "get_if_exists": get_if_exists,
            "class_name": class_name,
            "methods": methods,
            "function_id": function_id,
            "num_returns": 0,
            "return_ids": [],
            "resources": resources or {},
            "num_tpus": num_tpus,
            "max_restarts": max_restarts,
            "max_concurrency": max_concurrency,
            "concurrency_groups": dict(concurrency_groups or {}),
            "placement_group": placement_group,
            "runtime_env": runtime_env,
        }
        self._prepare_args(args, kwargs, spec)
        reply = self.client.request({"t": "create_actor", "spec": spec})
        return ActorID(reply["actor_id"])

    def submit_actor_task(self, actor_id: ActorID, caller_nonce: bytes,
                          seq: int, method: str,
                          args, kwargs, *, num_returns=1, name: str = "",
                          concurrency_group: str = ""):
        task_id = TaskID.for_actor_task(actor_id, caller_nonce, seq)
        n_ret = 1 if num_returns == "dynamic" else max(num_returns, 0)
        return_ids = [ObjectID.for_task_return(task_id, i + 1)
                      for i in range(max(n_ret, 1))]
        spec = {
            "task_id": task_id.binary(),
            "kind": "actor_task",
            "actor_id": actor_id.binary(),
            "method": method,
            "name": name or method,
            "seq": seq,
            "num_returns": num_returns,
            "return_ids": [o.binary() for o in return_ids],
            "owner": self.client.worker_id,
        }
        if concurrency_group:
            spec["concurrency_group"] = concurrency_group
        from ray_tpu.util.tracing import inject_context
        tctx = inject_context()
        if tctx is not None:
            spec["trace_ctx"] = tctx
        if _fr._active is not None:
            _fr._active.start(spec)
        self._prepare_args(args, kwargs, spec)
        if _fr._active is not None:
            _fr._active.stamp(spec, "encode")
        self.client.send_soon({"t": "submit_actor_task", "spec": spec})
        refs = [ObjectRef(o, owner=self.client.worker_id) for o in return_ids]
        if num_returns == "dynamic" or num_returns == 1:
            return refs[0]
        if num_returns == 0:
            return None
        return refs

    def kill_actor(self, actor_id: ActorID, no_restart: bool = True) -> None:
        self.client.request({"t": "kill_actor", "actor_id": actor_id.binary(),
                             "no_restart": no_restart})

    # ------------------------------------------------------------ objects

    def put(self, value: Any) -> ObjectRef:
        oid = ObjectID.for_put(current_task_id(), self._next_put_index())
        # explicit puts keep jax.Array leaves device-resident (HBM
        # objects, core/device_objects.py) — no host bounce until a
        # different process actually asks for the value
        self.client.put_object(oid, value, allow_device=True)
        return ObjectRef(oid, owner=self.client.worker_id)

    def get(self, refs: Sequence[ObjectRef],
            timeout: Optional[float] = None) -> list[Any]:
        if _fr._active is None:
            return self.client.get_objects([r.id for r in refs],
                                           timeout=timeout)
        # flight recorder: the caller-visible tail of the lifecycle
        # (result_store → get return) lands in its own histogram.
        # Success only — a timeout would fold the caller's timeout
        # SETTING into the latency histogram as if it were a roundtrip
        t0 = time.monotonic()
        out = self.client.get_objects([r.id for r in refs],
                                      timeout=timeout)
        rec = _fr._active
        if rec is not None:
            rec.observe("get_roundtrip", time.monotonic() - t0)
        return out

    def wait(self, refs: Sequence[ObjectRef], num_returns: int = 1,
             timeout: Optional[float] = None):
        ready_bins = set(self.client.wait([r.id for r in refs], num_returns,
                                          timeout))
        ready, not_ready = [], []
        for r in refs:
            (ready if r.binary() in ready_bins else not_ready).append(r)
        return ready, not_ready

    def free(self, refs: Sequence[ObjectRef]) -> None:
        self.client.free([r.id for r in refs])

    def as_future(self, ref: ObjectRef) -> concurrent.futures.Future:
        return self._futures_pool.submit(
            lambda: self.client.get_objects([ref.id])[0])

    def _release_refs(self, object_ids: list) -> None:
        if not self.client.closed:
            self.client.send({"t": "release_refs",
                              "object_ids": object_ids})

    # ----------------------------------------------------------- shutdown

    def shutdown(self) -> None:
        from ray_tpu.core.object_ref import get_tracker
        try:
            get_tracker().flush()
        except Exception:
            pass
        get_tracker().set_sink(None)
        try:
            self._futures_pool.shutdown(wait=False)
        except Exception:
            pass
        if self.tpu_executor_client is not None:
            try:
                self.tpu_executor_client.close()
            except Exception:
                pass
        try:
            self.client.close()
        except Exception:
            pass
        if self.node_service is not None:
            self.node_service.stop()


# --------------------------------------------------------------------------
# globals

_runtime: Optional[Runtime] = None
_runtime_lock = threading.Lock()


def get_runtime() -> Runtime:
    if _runtime is None:
        raise RuntimeError("ray_tpu is not initialized — call ray_tpu.init()")
    return _runtime


def is_initialized() -> bool:
    return _runtime is not None


def attach_worker_runtime(client: NodeClient, executor: Executor) -> Runtime:
    global _runtime
    # Adopt the node's resolved config (received at registration) so
    # system_config overrides reach worker-side get_config() readers —
    # the reference distributes _system_config cluster-wide the same way
    # (ray_config.cc:29).  Worker-local RAY_TPU_* env still wins.
    from ray_tpu._config import RayTpuConfig, set_config
    set_config(RayTpuConfig(client.config_dict))
    with _runtime_lock:
        _runtime = Runtime(client, mode="worker", executor=executor)
    return _runtime


def _detect_tpu_chips() -> int:
    """Count local TPU chips without initializing jax on them twice."""
    try:
        import jax
        devs = jax.devices()
        return sum(1 for d in devs if d.platform != "cpu")
    except Exception:
        return 0


def init(*, num_cpus: Optional[float] = None, num_tpus: Optional[float] = None,
         resources: Optional[dict] = None, address: Optional[str] = None,
         object_store_memory: Optional[int] = None,
         system_config: Optional[dict] = None,
         namespace: str = "default") -> Runtime:
    """Start (or connect to) a node and attach this process as the driver.

    Reference analogue: ray.init (python/ray/_private/worker.py:1043) —
    starts the control plane + worker pool, connects the driver, and (TPU
    design delta) registers an in-process TPU executor so compiled jax work
    runs in the driver where device ownership lives.
    """
    global _runtime
    with _runtime_lock:
        if _runtime is not None:
            return _runtime

        if address is None:
            # job drivers join their cluster via the env the supervisor
            # sets (reference: RAY_ADDRESS)
            address = os.environ.get("RAY_TPU_ADDRESS") or None

        if address and address.startswith("ray://"):
            # thin-client mode (reference: ray.init("ray://...") routes
            # through util/client — python/ray/_private/worker.py:1043)
            from ray_tpu.util.client import ClientRuntime
            rt = ClientRuntime(address, namespace=namespace)
            _runtime = rt
            atexit.register(shutdown)
            return rt

        cfg_overrides = dict(system_config or {})
        if object_store_memory is not None:
            cfg_overrides["object_store_memory"] = object_store_memory
        config = RayTpuConfig(cfg_overrides)
        set_config(config)

        session = uuid.uuid4().hex
        session_dir = os.path.join("/tmp/ray_tpu", f"session_{session[:8]}")
        os.makedirs(session_dir, exist_ok=True)

        if address is None:
            from ray_tpu.core.node import NodeService
            if num_tpus is None:
                num_tpus = _detect_tpu_chips()
            svc = NodeService(config, session, session_dir,
                              num_cpus=num_cpus, num_tpus=num_tpus,
                              resources=resources)
            svc.start_thread()
            address = svc.address
        else:
            svc = None

        client = NodeClient(address, kind="driver")
        rt = Runtime(client, mode="driver", namespace=namespace)
        rt.node_service = svc
        rt.session_dir = session_dir

        # In-process TPU executor (single-host fast path): tasks/actors with
        # num_tpus>0 execute on this thread, inside the driver process.
        n_tpu = num_tpus if num_tpus is not None else 0
        if svc is not None and n_tpu and config.tpu_gang_in_process:
            from ray_tpu.core.executor import (make_message_queue,
                                               queue_push_handler)
            inbox = make_message_queue()
            cell: dict = {}
            ex_client = NodeClient(address, kind="tpu_executor", tpu=True,
                                   push_handler=queue_push_handler(inbox,
                                                                   cell))
            cell["client"] = ex_client
            ex = Executor(ex_client, msg_queue=inbox)
            t = threading.Thread(target=ex.run_loop, daemon=True,
                                 name="raytpu-tpu-executor")
            t.start()
            rt.tpu_executor_client = ex_client
            rt.tpu_executor_thread = t

        _runtime = rt
        atexit.register(shutdown)
        return rt


def shutdown() -> None:
    global _runtime
    with _runtime_lock:
        if _runtime is None:
            return
        rt = _runtime
        _runtime = None
    rt.shutdown()
    # give worker procs a moment to exit before the session dir vanishes
    time.sleep(0.05)
