"""ObjectRef — a future-like handle to a value in the object plane.

Capability parity with the reference's ObjectRef surface
(reference: python/ray/_raylet.pyx ObjectRef; python/ray/includes/object_ref.pxi):
await-able, hashable, picklable (travels inside task args), and resolvable
via ``ray_tpu.get``.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
from typing import Any, Optional

from ray_tpu.core.ids import ObjectID


class ObjectRef:
    __slots__ = ("_id", "_owner", "__weakref__")

    def __init__(self, object_id: ObjectID, owner: Optional[str] = None):
        self._id = object_id
        self._owner = owner  # worker id string of the owner process

    @property
    def id(self) -> ObjectID:
        return self._id

    @property
    def owner(self) -> Optional[str]:
        return self._owner

    def binary(self) -> bytes:
        return self._id.binary()

    def hex(self) -> str:
        return self._id.hex()

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self.hex()[:16]}…)"

    # Block-on-result convenience (same as calling ray_tpu.get(ref)).
    def get(self, timeout: Optional[float] = None) -> Any:
        from ray_tpu.core.runtime import get_runtime
        return get_runtime().get([self], timeout=timeout)[0]

    def future(self) -> concurrent.futures.Future:
        from ray_tpu.core.runtime import get_runtime
        return get_runtime().as_future(self)

    def __await__(self):
        fut = self.future()
        return asyncio.wrap_future(fut).__await__()

    def __reduce__(self):
        return (ObjectRef, (self._id, self._owner))


class ObjectRefGenerator:
    """Iterator over a dynamic number of task returns
    (reference: num_returns="dynamic" → ObjectRefGenerator, _raylet.pyx:172)."""

    def __init__(self, refs: list[ObjectRef]):
        self._refs = list(refs)

    def __iter__(self):
        return iter(self._refs)

    def __len__(self):
        return len(self._refs)

    def __getitem__(self, i):
        return self._refs[i]
