"""ObjectRef — a future-like handle to a value in the object plane.

Capability parity with the reference's ObjectRef surface
(reference: python/ray/_raylet.pyx ObjectRef; python/ray/includes/object_ref.pxi):
await-able, hashable, picklable (travels inside task args), and resolvable
via ``ray_tpu.get``.
"""

from __future__ import annotations

import asyncio
import concurrent.futures
import threading
from typing import Any, Callable, Optional

from ray_tpu.core.ids import ObjectID


class _RefTracker:
    """Process-local half of distributed refcounting (scoped-down
    reference: core_worker/reference_count.h:61 — local counts here;
    the node releases storage when the OWNER's count drains; borrower
    chains and lineage are out of scope for v1).

    Counts ObjectRef constructions/destructions per object id and, when
    an id's count hits zero, batches a ``release_refs`` notification to
    the node through the sink installed by the runtime."""

    _FLUSH_BATCH = 64
    _FLUSH_DELAY = 0.5

    def __init__(self):
        self._lock = threading.Lock()
        self._counts: dict[bytes, int] = {}
        self._pending: list[bytes] = []
        self._sink: Optional[Callable[[list], None]] = None
        self._timer: Optional[threading.Timer] = None

    def set_sink(self, sink: Optional[Callable[[list], None]]) -> None:
        with self._lock:
            self._sink = sink

    def incref(self, ob: bytes) -> None:
        with self._lock:
            self._counts[ob] = self._counts.get(ob, 0) + 1

    def decref(self, ob: bytes) -> None:
        flush = False
        with self._lock:
            c = self._counts.get(ob)
            if c is None:
                return
            if c <= 1:
                del self._counts[ob]
                if self._sink is not None:
                    self._pending.append(ob)
                    flush = len(self._pending) >= self._FLUSH_BATCH
                    if not flush and self._timer is None:
                        self._timer = threading.Timer(self._FLUSH_DELAY,
                                                      self.flush)
                        self._timer.daemon = True
                        self._timer.start()
            else:
                self._counts[ob] = c - 1
        if flush:
            self.flush()

    def flush(self) -> None:
        with self._lock:
            if self._timer is not None:
                self._timer.cancel()
                self._timer = None
            batch, self._pending = self._pending, []
            sink = self._sink
        if sink is not None and batch:
            try:
                sink(batch)
            except Exception:
                pass   # connection racing shutdown: storage dies with it

    def held_count(self, ob: bytes) -> int:
        with self._lock:
            return self._counts.get(ob, 0)


_tracker = _RefTracker()


def get_tracker() -> _RefTracker:
    return _tracker


class ObjectRef:
    __slots__ = ("_id", "_owner", "__weakref__")

    def __init__(self, object_id: ObjectID, owner: Optional[str] = None):
        self._id = object_id
        self._owner = owner  # worker id string of the owner process
        _tracker.incref(object_id.binary())

    def __del__(self):
        try:
            _tracker.decref(self._id.binary())
        except Exception:
            pass   # interpreter teardown

    @property
    def id(self) -> ObjectID:
        return self._id

    @property
    def owner(self) -> Optional[str]:
        return self._owner

    def binary(self) -> bytes:
        return self._id.binary()

    def hex(self) -> str:
        return self._id.hex()

    def __hash__(self):
        return hash(self._id)

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and other._id == self._id

    def __repr__(self):
        return f"ObjectRef({self.hex()[:16]}…)"

    # Block-on-result convenience (same as calling ray_tpu.get(ref)).
    def get(self, timeout: Optional[float] = None) -> Any:
        from ray_tpu.core.runtime import get_runtime
        return get_runtime().get([self], timeout=timeout)[0]

    def future(self) -> concurrent.futures.Future:
        from ray_tpu.core.runtime import get_runtime
        return get_runtime().as_future(self)

    def __await__(self):
        fut = self.future()
        return asyncio.wrap_future(fut).__await__()

    def __reduce__(self):
        return (ObjectRef, (self._id, self._owner))


class ObjectRefGenerator:
    """Iterator over a dynamic number of task returns
    (reference: num_returns="dynamic" → ObjectRefGenerator, _raylet.pyx:172)."""

    def __init__(self, refs: list[ObjectRef]):
        self._refs = list(refs)

    def __iter__(self):
        return iter(self._refs)

    def __len__(self):
        return len(self._refs)

    def __getitem__(self, i):
        return self._refs[i]
