"""Object-plane half of the node service (split out of core/node.py).

Everything that moves or accounts for object BYTES on a node: the local
object directory (``ObjInfo``), inline/shm/device locations, pins,
waiter resolution, owner-based release sweeps, lineage-backed
reconstruction, the ownership directory protocol (owner nodes — not the
head — serve location queries for objects they own), chunked node-to-
node transfer with relay-chain broadcast, the same-process memcpy fast
path, and node-death recovery for owned objects and forwarded tasks.
Reference: object_manager.h Push/Pull, plasma store.h,
ownership_based_object_directory.cc, object_recovery_manager.h.

``NodeTransferMixin`` holds no state; ``NodeService.__init__``
(core/node.py) owns every attribute.  This module also hosts the record
types and helpers shared by the other node modules (``ObjInfo``,
``OwnedRec``, ``_wire_spec``, ``_gil_free_copy``,
``_LOCAL_NODES_BY_HEX``) so the import graph stays acyclic:
node_sched imports from here, never the reverse.
"""

from __future__ import annotations

import pickle
import sys
import threading
import time
import traceback
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

from ray_tpu.core import fault_injection as _fi
from ray_tpu.core import flight_recorder as _fr
from ray_tpu.core import protocol
from ray_tpu.core.ids import ObjectID
from ray_tpu.core.object_store import ObjectExists
from ray_tpu.core.service import ClientRec


@dataclass
class ObjInfo:
    state: str = "pending"       # pending | ready | error
    loc: str = ""                # inline | shm | device
    data: Optional[bytes] = None  # inline payload (SerializedObject wire bytes)
    size: int = 0
    owner: str = ""
    is_error: bool = False
    # device-resident entries: conn_id of the process holding the HBM
    # buffers (core/device_objects.py); data holds the descriptor
    owner_conn: Optional[int] = None
    loc_reported: bool = False   # location pushed to the head
    nested: tuple = ()           # ids this object's value embeds refs to
    wait_waiters: list = field(default_factory=list)
    # (node_hex, address) of the node that OWNS this object — the
    # submitter's node is the location authority and lineage holder
    # (reference: ownership model, core_worker.h / the owner_address
    # every ObjectReference carries)
    owner_node: tuple = ()


@dataclass
class OwnedRec:
    """Owner-side directory entry for one owned object (reference:
    ownership_based_object_directory.cc — the owner, not the GCS, is
    authoritative for locations of objects it owns)."""
    task_id: bytes = b""                       # producer (b"" for puts)
    locations: dict = field(default_factory=dict)   # node_hex -> address
    watchers: set = field(default_factory=set)      # (node_hex, address)


def _wire_spec(spec: dict) -> dict:
    """Spec copy safe to ship to another service (drop node-local keys)."""
    return {k: v for k, v in spec.items()
            if not k.startswith("_") and k != "submitter"}


def _gil_free_copy(dst, src, size: int) -> None:
    """memcpy that RELEASES the GIL (ctypes foreign calls drop it):
    a multi-hundred-MiB memoryview slice-assign holds the GIL and
    stalls every other event loop thread in the process for its whole
    duration — broadcast copies serialized behind each other."""
    import ctypes
    try:
        dst_c = (ctypes.c_char * size).from_buffer(dst)
        src_mv = memoryview(src)
        if src_mv.readonly:
            src_c = bytes(src_mv[:size])    # rare: readonly source
        else:
            src_c = (ctypes.c_char * size).from_buffer(src_mv)
        ctypes.memmove(dst_c, src_c, size)
    except (TypeError, ValueError):
        dst[:size] = src[:size]


# Same-process node registry: virtual clusters (cluster_utils) run many
# NodeServices as threads of one process.  Object pulls between them can
# hand the bytes over with one memcpy instead of a socket stream — the
# same-host semantics the reference gets from one shared plasma store
# per machine (plasma store.h:55; workers on a host never stream to
# each other).  Real multi-host peers are never in this registry.
# (string annotation: the composed class lives in core/node.py)
_LOCAL_NODES_BY_HEX: dict[str, "NodeService"] = {}  # noqa: F821


class NodeTransferMixin:
    """Object transfer + relay + shm bookkeeping (mixed into
    NodeService)."""

    # -- objects

    def _h_put_inline(self, rec, m):
        oid = ObjectID(m["object_id"])
        info = self.objects.setdefault(oid, ObjInfo())
        info.state = "error" if m.get("is_error") else "ready"
        info.loc = "inline"
        info.data = m["data"]
        info.size = len(m["data"])
        # ownership set at submit time wins (the submitter owns task
        # returns, even when an executor stores them)
        info.owner = info.owner or m.get("owner", rec.worker_id)
        info.is_error = bool(m.get("is_error"))
        if self.head_conn is not None and not info.owner_node:
            # first stored here with no prior claim: this node owns it
            # (ray.put objects — the putter's node is the authority)
            info.owner_node = (self.node_id.hex(), self.address)
        self._track_nested(info, m.get("nested_refs"))
        self._resolve_waiters(oid, info)
        if "reqid" in m:
            self._reply(rec, m["reqid"], ok=True)

    def _h_register_object(self, rec, m):
        oid = ObjectID(m["object_id"])
        info = self.objects.setdefault(oid, ObjInfo())
        info.state = "ready"
        info.loc = "shm"
        info.size = m["size"]
        info.owner = info.owner or m.get("owner", rec.worker_id)
        if self.head_conn is not None and not info.owner_node:
            info.owner_node = (self.node_id.hex(), self.address)
        self._track_nested(info, m.get("nested_refs"))
        self.store.register(oid, m["size"])
        self._resolve_waiters(oid, info)
        if "reqid" in m:
            self._reply(rec, m["reqid"], ok=True)

    def _h_get_objects(self, rec, m):
        """Batched blocking get: reply once ALL requested objects resolve."""
        ids = [ObjectID(b) for b in m["object_ids"]]
        for o in ids:
            info = self.objects.setdefault(o, ObjInfo())
            if (info.loc == "device" and info.state == "ready"
                    and info.owner_conn != rec.conn_id):
                # another process wants a device-resident object: ask the
                # owner to spill it to the host store once (materialize-
                # on-demand), then this get resolves like any other
                self._request_materialize(o, info)
        pending = [o for o in ids
                   if self.objects[o].state == "pending"]
        if not pending:
            self._reply_batch(rec, m["reqid"], ids)
            return
        key = (rec.conn_id, m["reqid"])
        self._multigets[key] = {"ids": ids, "remaining": set(pending)}
        for o in pending:
            self._mg_by_oid.setdefault(o, set()).add(key)
        self._ensure_remote_watch([o for o in pending
                                   if self.objects[o].loc != "device"])
        if rec.state == "busy":
            rec.state = "blocked"
            self._release_task_cpu(rec)
            self._schedule()

    # -- device-resident objects (core/device_objects.py) -------------------

    def _h_put_device(self, rec, m):
        oid = ObjectID(m["object_id"])
        info = self.objects.setdefault(oid, ObjInfo())
        info.state = "ready"
        info.loc = "device"
        info.data = m["descriptor"]
        info.size = m.get("size", 0)
        info.owner = info.owner or m.get("owner", rec.worker_id)
        info.owner_conn = rec.conn_id
        if self.head_conn is not None and not info.owner_node:
            info.owner_node = (self.node_id.hex(), self.address)
        self._track_nested(info, m.get("nested_refs"))
        self._resolve_waiters(oid, info)

    def _h_materialize_failed(self, rec, m):
        oid = ObjectID(m["object_id"])
        info = self.objects.get(oid)
        if (info is not None and info.state == "pending"
                and info.loc == "device"):
            self._seal_error_object(oid, RuntimeError(
                f"device object materialization failed: {m.get('error')}"))

    def _request_materialize(self, oid: ObjectID, info: ObjInfo) -> None:
        owner = self.clients.get(info.owner_conn)
        if owner is None:
            self._device_owner_lost(oid, info)
            return
        info.state = "pending"
        self._push(owner, {"t": "materialize_object",
                           "object_id": oid.binary()})

    def _device_owner_lost(self, oid: ObjectID, info: ObjInfo) -> None:
        """The process holding a device entry's HBM buffers died: the
        value is gone.  Reconstruction via lineage applies exactly as for
        any lost object; without lineage the get errors."""
        info.loc = ""
        info.data = None
        info.owner_conn = None
        info.state = "pending"
        if not self._try_reconstruct_device(oid):
            self._seal_error_object(
                oid, RuntimeError(
                    "owner process of device-resident object died"))

    def _try_reconstruct_device(self, oid: ObjectID) -> bool:
        rec_ = self.owned.get(oid.binary())
        if rec_ is not None and rec_.task_id:
            return self._reconstruct(rec_.task_id)
        return False

    def _reply_batch(self, rec, reqid, ids):
        results = []
        for oid in ids:
            info = self.objects[oid]
            if info.loc == "device":
                # only the owner reaches here with a device loc (others
                # were routed through materialization in _h_get_objects)
                results.append({"loc": "device_local", "data": info.data,
                                "is_error": False})
            elif info.loc == "shm":
                # Pin FIRST, then restore: the pin must already protect
                # the object when a later restore in this same batch (or
                # restore's own capacity-balancing pass) evicts — the
                # reply promises a mapped segment (reference: plasma pins
                # objects for the duration of a Get).
                self.store.pin(oid)
                rec.held_pins.append((oid, time.monotonic()))
                if self.store.is_spilled(oid):
                    self.store.restore(oid)
                self.store.touch(oid)
                results.append({"loc": "shm", "size": info.size,
                                "is_error": info.is_error})
            else:
                results.append({"loc": "inline", "data": info.data,
                                "is_error": info.is_error})
        self._reply(rec, reqid, results=results)

    def _h_need_space(self, rec, m):
        # A client's arena allocation failed: spill unpinned objects
        # (reference: plasma create_request_queue.h queues client creates
        # until eviction frees memory — here the client blocks on this
        # request and retries).
        freed = self.store.evict_for(int(m["nbytes"]))
        self._reply(rec, m["reqid"], freed=freed)

    def _h_release_pins(self, rec, m):
        ids = {ObjectID(b) for b in m["object_ids"]}
        kept = []
        for oid, ts in rec.held_pins:
            if oid in ids:
                ids.discard(oid)
                self.store.unpin(oid)
            else:
                kept.append((oid, ts))
        rec.held_pins[:] = kept

    def _expire_stale_pins(self) -> None:
        """Get-replies whose ack never arrived (client timeout/death race)
        must not pin objects forever."""
        cutoff = time.monotonic() - 120.0
        for rec in self.clients.values():
            if not rec.held_pins:
                continue
            kept = []
            for oid, ts in rec.held_pins:
                if ts < cutoff:
                    self.store.unpin(oid)
                else:
                    kept.append((oid, ts))
            rec.held_pins[:] = kept

    def _object_ready_hook(self, oid: ObjectID, info: ObjInfo) -> None:
        """Cluster bookkeeping when an object becomes ready/error here."""
        ob = oid.binary()
        if info.loc != "device":
            for conn_id, pm in self._device_pending_pulls.pop(ob, []):
                peer = self.clients.get(conn_id)
                if peer is not None:
                    self._h_pull_object(peer, pm)
        self._watched.discard(ob)
        self._pull_attempts.pop(ob, None)
        self._owner_watch.pop(ob, None)
        if self.head_conn is not None and not info.loc_reported:
            info.loc_reported = True
            self._head_send({"t": "report_locations", "adds": [ob]})
        if self.head_conn is not None and info.owner_node:
            # tell the object's OWNER a copy lives here — the owner, not
            # the head, serves location queries for owned objects
            if info.owner_node[0] == self.node_id.hex():
                self._owner_add_location(ob, self.node_id.hex(),
                                         self.address)
            elif info.loc == "inline" and info.data is not None:
                # inline result of forwarded work: ship the VALUE to the
                # owner directly — a location report would cost the owner
                # a locate + pull round trip for ~bytes of payload
                # (reference contrast: small returns ride the
                # PushTaskReply inline, core_worker.cc:2528)
                self._owner_push(
                    info.owner_node[0], info.owner_node[1],
                    {"t": "owner_object_value", "object_id": ob,
                     "data": info.data, "is_error": info.is_error,
                     "node": self.node_id.hex(), "address": self.address})
            else:
                self._owner_push(
                    info.owner_node[0], info.owner_node[1],
                    {"t": "owner_object_at", "object_id": ob,
                     "node": self.node_id.hex(), "address": self.address})
        tid = self._fwd_by_oid.pop(ob, None)
        if tid is not None:
            fw = self._fwd_tasks.get(tid)
            if fw is not None and not any(
                    b in self._fwd_by_oid for b in fw["spec"]["return_ids"]):
                self._fwd_tasks.pop(tid, None)
                tr = self.tasks.get(tid)
                if tr is not None and tr.state == "forwarded":
                    tr.state = "failed" if info.is_error else "finished"
                    tr.finished_at = time.time()
                    self._note_task_finished(tid)
                    self._release_arg_blob(fw["spec"])

    def _resolve_waiters(self, oid: ObjectID, info: ObjInfo) -> None:
        self._object_ready_hook(oid, info)
        for key in self._mg_by_oid.pop(oid, ()):
            mg = self._multigets.get(key)
            if mg is None:
                continue
            mg["remaining"].discard(oid)
            if not mg["remaining"]:
                del self._multigets[key]
                w = self.clients.get(key[0])
                if w is not None:
                    if w.state == "blocked":
                        w.state = "busy"
                    self._reply_batch(w, key[1], mg["ids"])
        for conn_id, reqid, ids, num_returns, deadline in list(info.wait_waiters):
            self._try_finish_wait(conn_id, reqid, ids, num_returns, deadline)
        info.wait_waiters.clear()
        # release tasks waiting on this dependency
        for spec in self.dep_waiting.pop(oid, ()):
            spec["_ndeps"] -= 1
            if spec["_ndeps"] == 0:
                self._make_runnable(spec)
        self._schedule()

    def _h_wait(self, rec, m):
        ids = [ObjectID(b) for b in m["object_ids"]]
        self._ensure_remote_watch(
            [o for o in ids
             if self.objects.setdefault(o, ObjInfo()).state == "pending"])
        self._try_finish_wait(rec.conn_id, m["reqid"], ids, m["num_returns"],
                              time.time() + m["timeout"] if m.get("timeout")
                              is not None else None, first=True)

    def _try_finish_wait(self, conn_id, reqid, ids, num_returns, deadline,
                         first=False):
        rec = self.clients.get(conn_id)
        if rec is None:
            return
        ready = [o for o in ids
                 if self.objects.get(o) is not None
                 and self.objects[o].state != "pending"]
        timed_out = deadline is not None and time.time() >= deadline
        if len(ready) >= num_returns or timed_out:
            if not timed_out:
                ready = ready[:num_returns]
            self._reply(rec, reqid, ready=[o.binary() for o in ready])
            return
        if first:
            for o in ids:
                info = self.objects.setdefault(o, ObjInfo())
                if info.state == "pending":
                    info.wait_waiters.append((conn_id, reqid, ids, num_returns,
                                              deadline))
            if deadline is not None:
                self.post_later(max(0.0, deadline - time.time()),
                                lambda: self._try_finish_wait(
                                    conn_id, reqid, ids, num_returns, deadline))

    def _seal_error_object(self, oid: ObjectID, exc: BaseException) -> None:
        """Make `oid` resolve to an error value and wake its waiters —
        the single encoder of error objects on this node."""
        from ray_tpu.core.serialization import SerializedObject
        info = self.objects.setdefault(oid, ObjInfo())
        info.state = "error"
        info.loc = "inline"
        info.data = SerializedObject(inband=pickle.dumps(exc)).to_bytes()
        info.is_error = True
        self._resolve_waiters(oid, info)

    def _track_nested(self, info: ObjInfo, nested) -> None:
        """Record ids embedded in this object's value so their storage
        outlives the owner's release while the container exists."""
        if not nested or info.nested:
            return   # guard against double-count on a retried put
        info.nested = tuple(nested)
        for nb in info.nested:
            self._nested_count[nb] = self._nested_count.get(nb, 0) + 1

    def _release_owned(self, ob: bytes) -> None:
        """Drop the ownership record and dereference its lineage entry
        (freed objects need no reconstruction path)."""
        orec = self.owned.pop(ob, None)
        if orec is None or not orec.task_id:
            return
        lin = self.lineage.get(orec.task_id)
        if lin is None:
            return
        lin["live"].discard(ob)
        if not lin["live"]:
            if lin["spec"] is not None:
                self._lineage_bytes -= lin["cost"]
            del self.lineage[orec.task_id]
            # compact the eviction queue occasionally: entries for
            # deleted lineage would otherwise accumulate forever
            if len(self._lineage_order) > 256 \
                    and len(self._lineage_order) > 4 * len(self.lineage):
                self._lineage_order = deque(
                    t for t in self._lineage_order if t in self.lineage)

    def _forget_object(self, oid: ObjectID) -> None:
        """Single removal point: drop the entry, its storage, and its
        holds on nested ids."""
        info = self.objects.pop(oid, None)
        self.store.delete(oid)
        ob = oid.binary()
        self._bcast_tail.pop(ob, None)
        if info is not None and info.owner_node \
                and info.owner_node[0] == self.node_id.hex():
            self._release_owned(ob)
        else:
            orec = self.owned.get(ob)
            if orec is not None:
                orec.locations.pop(self.node_id.hex(), None)
        if info is not None and info.nested:
            for nb in info.nested:
                c = self._nested_count.get(nb, 0) - 1
                if c > 0:
                    self._nested_count[nb] = c
                else:
                    self._nested_count.pop(nb, None)

    def _delete_local_object(self, oid: ObjectID) -> None:
        info = self.objects.get(oid)
        # capture BEFORE sealing: _seal_error_object rewrites loc to
        # "inline", which would skip the owner's HBM release below
        was_device = info is not None and info.loc == "device"
        device_owner = info.owner_conn if was_device else None
        if info is not None and (info.state == "pending"
                                 or oid in self._mg_by_oid
                                 or info.wait_waiters
                                 or oid in self.dep_waiting):
            # fail anyone blocked on it before it vanishes
            self._seal_error_object(
                oid, RuntimeError(f"Object {oid.hex()[:16]} was freed"))
        if was_device:
            # tell the owner process to release the HBM buffers
            owner = self.clients.get(device_owner)
            if owner is not None:
                self._push(owner, {"t": "drop_device_object",
                                   "object_id": oid.binary()})
        self._forget_object(oid)

    def _h_free_objects(self, rec, m):
        for b in m["object_ids"]:
            self._delete_local_object(ObjectID(b))
        if self.head_conn is not None:
            self._head_send({"t": "free_objects",
                             "object_ids": list(m["object_ids"])})
        if "reqid" in m:
            self._reply(rec, m["reqid"], ok=True)

    def _h_object_stats(self, rec, m):
        self._reply(rec, m["reqid"], stats=self.store.stats(),
                    num_objects=len(self.objects))

    # -- cluster prefix plane: block-fetch conduit ---------------------------

    def _h_block_fetch(self, rec, m):
        """Replica→replica prefix-block fetch (the transfer half of
        serve/fleet/prefix_directory.py for multi-node fleets): a peer
        adopting a prefix asks this NODE for the K/V bytes of a prefix
        an engine in this process holds, by engine name.  The bytes
        ride the reply's raw envelope over the same peer plane as
        object chunks — no new transport.  Every failure (unknown
        engine, stale generation, evicted prefix, dead engine) replies
        with the error NAME so the caller re-raises the typed
        PrefixTransferError shape and takes its local-recompute
        fallback; a fetch is never allowed to wedge the peer loop."""
        try:
            from ray_tpu.inference import engine as _eng
            eng = _eng._ENGINES.get(m["engine"])
            if eng is None:
                raise KeyError(f"no engine {m['engine']!r} in this process")
            payload = eng.prefix_extract(list(m["tokens"]),
                                         int(m.get("generation", 0)))
        except Exception as e:
            if "reqid" in m:
                self._reply(rec, m["reqid"],
                            error=f"{type(e).__name__}: {e}",
                            error_type=type(e).__name__)
            return
        import numpy as _np
        k = _np.ascontiguousarray(payload["k"])
        v = _np.ascontiguousarray(payload["v"])
        if "reqid" in m:
            self._reply(rec, m["reqid"], ok=True,
                        n_tokens=int(payload["n_tokens"]),
                        block_size=int(payload["block_size"]),
                        generation=int(payload["generation"]),
                        shape=list(k.shape), dtype=str(k.dtype),
                        k=k.tobytes(), v=v.tobytes())

    # -- automatic object lifetime (owner-based release) --------------------

    def _h_release_refs(self, rec, m):
        """The owning process dropped its last local ref to these objects
        — reclaim their storage once nothing on this node still needs
        them (reference: reference_count.h owner-count-zero → delete;
        borrower chains are out of scope, so non-owner releases are
        ignored rather than trusted)."""
        for b in m["object_ids"]:
            oid = ObjectID(b)
            info = self.objects.get(oid)
            if info is None:
                continue
            if info.owner and info.owner != rec.worker_id:
                continue
            self._released_wait.add(oid)
        self._sweep_released()

    def _args_in_flight(self) -> set:
        """Object ids still referenced as args by queued or running work
        on this node — storage for these must survive the owner's
        release until the work completes."""
        s: set = set()
        for q in (self.runnable_cpu, self.runnable_tpu,
                  self.runnable_zero):
            for spec in q:
                s.update(spec.get("arg_ids", ()))
        for specs in self.dep_waiting.values():
            for spec in specs:
                s.update(spec.get("arg_ids", ()))
        for ar in self.actors.values():
            for spec in ar.queue:
                s.update(spec.get("arg_ids", ()))
            for spec in ar.running.values():
                s.update(spec.get("arg_ids", ()))
        # running (non-actor) work hangs off busy workers — iterating
        # clients is O(pool), where iterating self.tasks would be
        # O(task history) per release sweep
        for rec in self.clients.values():
            if rec.current_task is not None:
                tr = self.tasks.get(rec.current_task)
                if tr is not None:
                    s.update(tr.spec.get("arg_ids", ()))
        # forwarded work: the destination node still has to PULL these
        # args from us — our copy must outlive the forward
        for fw in self._fwd_tasks.values():
            s.update(fw["spec"].get("arg_ids", ()))
        for specs in self._awaiting_actor.values():
            for spec in specs:
                s.update(spec.get("arg_ids", ()))
        return s

    def _sweep_released(self) -> None:
        if not self._released_wait:
            return
        in_flight = self._args_in_flight()
        freed: list[bytes] = []
        for oid in list(self._released_wait):
            info = self.objects.get(oid)
            if info is None:
                self._released_wait.discard(oid)
                continue
            if info.state == "pending":
                continue   # producing task still running; re-checked later
            if oid.binary() in in_flight:
                continue
            if oid in self._mg_by_oid or info.wait_waiters:
                continue
            if self._nested_count.get(oid.binary(), 0) > 0:
                continue   # a stored container still embeds this ref
            if info.loc == "shm":
                e = self.store.entries.get(oid)
                if e is not None and e.pin_count > 0:
                    continue   # a get/transfer is mapping it right now
            self._released_wait.discard(oid)
            self._forget_object(oid)
            freed.append(oid.binary())
        if freed and self.head_conn is not None:
            # replicas pulled to other nodes die with the owner's copy
            self._head_send({"t": "free_objects", "object_ids": freed})

    # -- ownership + lineage --------------------------------------------------

    def _record_lineage(self, spec: dict) -> None:
        """Retain the producer spec so lost returns can be re-executed
        (reference: task_manager.h lineage pinning bounded by
        max_lineage_bytes)."""
        tid = spec["task_id"]
        live = set(spec["return_ids"])
        for b in live:
            rec = self.owned.get(b)
            if rec is None:
                self.owned[b] = OwnedRec(task_id=tid)
            else:
                rec.task_id = rec.task_id or tid
        if tid in self.lineage or not live:
            return
        wire = _wire_spec(spec)
        # cheap size estimate: serialized args dominate a spec
        cost = len(wire.get("args") or b"") + 256 * (1 + len(live))
        self.lineage[tid] = {"spec": wire, "cost": cost, "live": live,
                             "recons": 0}
        self._lineage_order.append(tid)
        self._lineage_bytes += cost
        cap = self.config.max_lineage_bytes
        while self._lineage_bytes > cap and self._lineage_order:
            old = self._lineage_order.popleft()
            lin = self.lineage.get(old)
            if lin is not None and lin["spec"] is not None:
                lin["spec"] = None
                self._lineage_bytes -= lin["cost"]

    def _absorb_arg_owners(self, spec: dict) -> None:
        """Adopt the forwarding node's owner hints for arg objects so
        location queries go to owners, not the head."""
        for b, onode in (spec.get("arg_owners") or {}).items():
            info = self.objects.setdefault(ObjectID(b), ObjInfo())
            if not info.owner_node:
                info.owner_node = tuple(onode)

    def _attach_arg_owners(self, wire: dict, spec: dict) -> None:
        """Stamp owner addresses onto a spec leaving this node (the
        reference ships owner_address inside every ObjectReference)."""
        owners = {}
        ids = list(spec.get("arg_ids", ()))
        for b in ids:
            info = self.objects.get(ObjectID(b))
            if info is None:
                continue
            if info.owner_node:
                owners[b] = tuple(info.owner_node)
            elif info.state != "pending":
                # no owner recorded but we hold a copy: we can serve it
                owners[b] = (self.node_id.hex(), self.address)
        if owners:
            wire["arg_owners"] = owners

    # -- node-to-node object transfer ---------------------------------------

    def _peer_conn_async(self, node_hex: str, address: str, cb) -> None:
        """Hand `cb` a Connection to the peer (or None).  The TCP connect
        runs on a helper thread — a blackholed peer must never stall the
        event loop (heartbeats ride it, and a stalled loop gets this
        healthy node declared dead)."""
        conn = self._peer_conns.get(node_hex)
        if conn is not None:
            cb(conn)
            return
        waiters = self._peer_connecting.setdefault(node_hex, [])
        waiters.append(cb)
        if len(waiters) > 1:
            return   # a connect is already in flight

        def work():
            c = None
            try:
                c = protocol.connect(
                    address, timeout=5.0, remote=True,
                    label=(f"node:{self.node_id.hex()[:8]}",
                           f"node:{node_hex[:8]}"))
                c.send({"t": "register", "kind": "peer", "reqid": 0,
                        "node_hex": self.node_id.hex(),
                        "worker_id": f"peer-{self.node_id.hex()[:12]}"})
            except (OSError, protocol.ConnectionClosed):
                if c is not None:
                    try:
                        c.close()
                    except Exception:
                        pass
                c = None
            self.post(lambda: self._peer_connected(node_hex, c))
        threading.Thread(target=work, daemon=True,
                         name=f"raytpu-connect-{node_hex[:8]}").start()

    def _peer_connected(self, node_hex: str,
                        conn: Optional[protocol.Connection]) -> None:
        cbs = self._peer_connecting.pop(node_hex, [])
        if conn is not None:
            self._peer_conns[node_hex] = conn
            from ray_tpu.core.local_lane import LaneConnection
            if isinstance(conn, LaneConnection):
                # same-process peer: deliver from its loop, no recv thread
                conn.on_close = \
                    lambda: self.post(lambda: self._drop_peer(node_hex))
                conn.set_deliver(
                    lambda m: self.post(
                        lambda m=m: self._on_peer_msg(node_hex, m)))
            else:
                t = threading.Thread(target=self._peer_recv_loop,
                                     args=(node_hex, conn), daemon=True,
                                     name=f"raytpu-peer-{node_hex[:8]}")
                t.start()
        for cb in cbs:
            try:
                cb(conn)
            except Exception:
                sys.stderr.write("[node] peer-connect callback failed:\n"
                                 + traceback.format_exc())

    def _peer_recv_loop(self, node_hex: str,
                        conn: protocol.Connection) -> None:
        while not self._stop.is_set():
            try:
                msg = conn.recv()
            except protocol.ConnectionClosed:
                self.post(lambda: self._drop_peer(node_hex))
                return
            except Exception:
                continue
            self.post(lambda m=msg: self._on_peer_msg(node_hex, m))

    def _drop_peer(self, node_hex: str) -> None:
        conn = self._peer_conns.pop(node_hex, None)
        if conn is not None:
            try:
                conn.close()
            except Exception:
                pass
        # pulls in flight from that peer: retry through the head (it may
        # know another location, or the producer will resubmit)
        for ob, st in list(self._pulls.items()):
            if st["src"] == node_hex:
                self._pulls.pop(ob, None)
                self._watched.discard(ob)
                self.post_later(
                    0.1, lambda o=ObjectID(ob): self._ensure_remote_watch([o]))

    def _ensure_remote_watch(self, oids: list) -> None:
        """Route pending objects to their location authority: the OWNER
        node when known (reference: ownership_based_object_directory.cc),
        the head only as fallback for objects with no owner hint.  Safe
        to call repeatedly — each object is watched at most once."""
        if self.head_conn is None:
            return
        me = self.node_id.hex()
        head_want = []
        by_owner: dict[tuple, list] = {}
        for o in oids:
            ob = o.binary()
            if ob in self._watched or ob in self._pulls:
                continue
            info = self.objects.get(o)
            if info is not None and info.state != "pending":
                continue
            onode = tuple(info.owner_node) if info is not None \
                and info.owner_node else ()
            if onode and onode[0] == me:
                # owner-side resolution is idempotent and cheap — don't
                # latch _watched, so demand arriving later re-resolves
                self._owner_self_resolve(ob)
            elif onode:
                self._watched.add(ob)
                by_owner.setdefault(onode, []).append(ob)
            else:
                self._watched.add(ob)
                head_want.append(ob)
        for onode, obs in by_owner.items():
            self._owner_locate_send(onode, obs)
        if head_want:
            self._head_locate(head_want)

    def _head_locate(self, obs: list, fatal_missing: bool = False) -> None:
        """Fallback directory lookup through the head."""

        def cb(reply):
            if reply.get("error"):
                return
            locs = reply.get("locs", {})
            for ob, (node_hex, addr) in locs.items():
                self._request_pull(ObjectID(ob), node_hex, addr)
            if fatal_missing:
                from ray_tpu.core.client import ObjectLostError
                for ob in obs:
                    if ob in locs:
                        continue
                    oid = ObjectID(ob)
                    info = self.objects.get(oid)
                    if info is not None and info.state == "pending":
                        self._seal_error_object(oid, ObjectLostError(
                            f"Object {oid.hex()[:16]} was lost: its "
                            "owner node died and no copy is known"))
        self._head_rpc({"t": "locate_object", "object_ids": list(obs)}, cb)

    # -- ownership directory protocol ----------------------------------------

    def _owner_locate_send(self, onode: tuple, obs: list) -> None:
        """Ask the owner node where these objects live; it replies with
        object_at pushes (or owner_object_lost) and registers us as a
        watcher until then."""
        hexn, addr = onode

        def go(conn):
            if conn is None:
                self._owner_unreachable(hexn, obs)
                return
            try:
                conn.send({"t": "owner_locate", "object_ids": list(obs),
                           "from_hex": self.node_id.hex(),
                           "from_addr": self.address})
                for ob in obs:
                    self._owner_watch[ob] = hexn
            except protocol.ConnectionClosed:
                self._drop_peer(hexn)
                self._owner_unreachable(hexn, obs)
        self._peer_conn_async(hexn, addr, go)

    def _owner_unreachable(self, owner_hex: str, obs: list) -> None:
        """Owner node gone: fall back to the head directory; if it knows
        no copy either, the object is lost for good."""
        retry = []
        for ob in obs:
            self._owner_watch.pop(ob, None)
            info = self.objects.get(ObjectID(ob))
            if info is not None and info.state == "pending":
                info.owner_node = ()
                retry.append(ob)
        if retry:
            self._head_locate(retry, fatal_missing=True)

    def _owner_push(self, node_hex: str, address: str, msg: dict) -> None:
        def go(conn):
            if conn is None:
                return
            # corked: one owner push per finished task — the batch flush
            # turns a per-task send into one send per loop pass (a dead
            # peer is noticed by its recv/on_close path)
            self._conn_send(conn, msg)
        self._peer_conn_async(node_hex, address, go)

    def _owner_add_location(self, ob: bytes, node_hex: str,
                            address: str) -> None:
        """Owner-side: record that a copy of an owned object exists on
        `node_hex`, notify watchers, feed our own pending consumers."""
        orec = self.owned.get(ob)
        if orec is None:
            orec = self.owned[ob] = OwnedRec()
        orec.locations[node_hex] = address
        # a remote location report IS the completion signal for a task we
        # forwarded — settle its record so node-death recovery treats the
        # object as lost-but-reconstructable, not in-flight
        tid = self._fwd_by_oid.pop(ob, None)
        if tid is not None:
            fw = self._fwd_tasks.get(tid)
            if fw is not None and not any(b in self._fwd_by_oid
                                          for b in fw["spec"]["return_ids"]):
                self._fwd_tasks.pop(tid, None)
                tr = self.tasks.get(tid)
                if tr is not None and tr.state == "forwarded":
                    tr.state = "finished"
                    tr.finished_at = time.time()
                    self._note_task_finished(tid)
                    self._release_arg_blob(fw["spec"])
        if orec.watchers:
            watchers, orec.watchers = orec.watchers, set()
            for whex, waddr in watchers:
                if whex == node_hex:
                    continue
                self._owner_push(whex, waddr,
                                 {"t": "object_at", "object_id": ob,
                                  "node": node_hex, "address": address})
        # demand-driven: pull our own copy only if something local waits
        # on it (a get, a wait, or a queued task's dependency)
        oid = ObjectID(ob)
        info = self.objects.get(oid)
        if info is not None and info.state == "pending" \
                and node_hex != self.node_id.hex() \
                and (oid in self._mg_by_oid or oid in self.dep_waiting
                     or info.wait_waiters):
            self._request_pull(oid, node_hex, address)

    def _h_owner_object_at(self, rec, m):
        """A node stored a copy of an object WE own."""
        self._owner_add_location(m["object_id"], m["node"], m["address"])

    def _h_owner_locate(self, rec, m):
        """A consumer asks us (the owner) where our objects live."""
        me = self.node_id.hex()
        watcher = (m.get("from_hex", ""), m.get("from_addr", ""))
        for ob in m["object_ids"]:
            oid = ObjectID(ob)
            info = self.objects.get(oid)
            if info is not None and info.state != "pending":
                self._push(rec, {"t": "object_at", "object_id": ob,
                                 "node": me, "address": self.address})
                continue
            orec = self.owned.get(ob)
            if orec is not None:
                self._prune_dead_locations(orec)
                loc = next(((h, a) for h, a in orec.locations.items()
                            if h != me), None)
                if loc is not None:
                    self._push(rec, {"t": "object_at", "object_id": ob,
                                     "node": loc[0], "address": loc[1]})
                    continue
            tid = (orec.task_id if orec is not None and orec.task_id
                   else oid.task_id().binary())
            if self._producer_in_flight(tid) or self._reconstruct(tid):
                # result will arrive: register the asker for the
                # object_at push that follows
                if watcher[0]:
                    orec = self.owned.get(ob)
                    if orec is None:
                        orec = self.owned[ob] = OwnedRec(task_id=tid)
                    orec.watchers.add(watcher)
                continue
            self._push(rec, {"t": "owner_object_lost", "object_id": ob,
                             "cause": "owner holds no copy and no lineage"})

    def _h_object_at(self, rec, m):
        """Location push from an owner node (same shape as the head's)."""
        self._on_owner_object_at_push(m)

    def _h_owner_object_value(self, rec, m):
        """Inline VALUE pushed by the node that executed forwarded work
        we own — seal it locally, skipping locate/pull round trips."""
        ob = m["object_id"]
        self._owner_watch.pop(ob, None)
        self._watched.discard(ob)
        oid = ObjectID(ob)
        info = self.objects.setdefault(oid, ObjInfo())
        if info.state != "pending":
            return
        info.state = "error" if m.get("is_error") else "ready"
        info.loc = "inline"
        info.data = m["data"]
        info.is_error = bool(m.get("is_error"))
        info.size = len(m["data"] or b"")
        # the executing node still holds a replica — track it like an
        # owner_object_at so release sweeps can reach it
        self._owner_add_location(ob, m["node"], m["address"])
        self._resolve_waiters(oid, info)

    def _on_owner_object_at_push(self, m: dict) -> None:
        self._owner_watch.pop(m["object_id"], None)
        self._hh_object_at(m)

    def _h_owner_object_lost(self, rec, m):
        self._on_owner_object_lost_push(m)

    def _on_owner_object_lost_push(self, m: dict) -> None:
        ob = m["object_id"]
        self._owner_watch.pop(ob, None)
        oid = ObjectID(ob)
        info = self.objects.get(oid)
        if info is None or info.state != "pending":
            return
        from ray_tpu.core.client import ObjectLostError
        self._seal_error_object(oid, ObjectLostError(
            f"Object {oid.hex()[:16]} was lost: {m.get('cause', '')}"))

    def _prune_dead_locations(self, orec: OwnedRec) -> None:
        me = self.node_id.hex()
        for h in list(orec.locations):
            if h != me and h not in self.cluster_view:
                orec.locations.pop(h)

    def _producer_in_flight(self, tid: bytes) -> bool:
        if tid in self._fwd_tasks:
            return True
        tr = self.tasks.get(tid)
        return tr is not None and tr.state in ("pending", "running",
                                               "forwarded")

    def _owner_self_resolve(self, ob: bytes) -> None:
        """We own this pending object: pull a known copy, wait on the
        in-flight producer, or re-execute it from lineage (reference:
        object_recovery_manager.h:41)."""
        oid = ObjectID(ob)
        info = self.objects.get(oid)
        if info is None or info.state != "pending":
            return
        me = self.node_id.hex()
        orec = self.owned.get(ob)
        if orec is not None:
            self._prune_dead_locations(orec)
            loc = next(((h, a) for h, a in orec.locations.items()
                        if h != me), None)
            if loc is not None:
                self._request_pull(oid, loc[0], loc[1])
                return
        # no live copy: wait on an in-flight producer (the owned rec may
        # not exist yet — lineage-less tasks only get one when a
        # location is first reported), reconstruct, or declare the loss
        tid = (orec.task_id if orec is not None and orec.task_id
               else oid.task_id().binary())
        if self._producer_in_flight(tid):
            return
        if self._reconstruct(tid):
            return
        from ray_tpu.core.client import ObjectLostError
        self._seal_error_object(oid, ObjectLostError(
            f"Object {oid.hex()[:16]} was lost and cannot be "
            "reconstructed (no live copy, no retained lineage)"))

    def _reconstruct(self, tid: bytes) -> bool:
        """Re-execute the producer of lost owned objects.  Deterministic
        return ids mean the re-run recreates exactly the lost objects
        (reference: object_recovery_manager.h ReconstructObject)."""
        lin = self.lineage.get(tid)
        if lin is None or lin.get("spec") is None:
            return False
        if lin["recons"] >= self.config.max_object_reconstructions:
            return False
        lin["recons"] += 1
        spec = dict(lin["spec"])
        # fresh flight-recorder record: the captured wire spec shares
        # the original attempt's stamp list, and stamping into it would
        # misattribute the whole loss-detection gap to node_recv
        spec.pop("fr", None)
        spec.pop("fr_w0", None)
        spec.pop("fr_done", None)
        sys.stderr.write(f"[node] reconstructing task "
                         f"{tid.hex()[:12]} (attempt {lin['recons']})\n")
        self._admit_task(spec)
        return True

    def _hh_object_at(self, m: dict) -> None:
        oid = ObjectID(m["object_id"])
        info = self.objects.get(oid)
        if info is not None and info.state == "pending":
            self._request_pull(oid, m["node"], m["address"])

    def _hh_object_lost(self, m: dict) -> None:
        ob = m["object_id"]
        if ob in self._fwd_by_oid:
            return  # our own forwarded task will be resubmitted on node_dead
        oid = ObjectID(ob)
        info = self.objects.get(oid)
        if info is None or info.state != "pending":
            return
        if info.owner_node:
            # the owner, not the head, decides whether this is fatal —
            # it may hold another copy or reconstruct from lineage
            if info.owner_node[0] == self.node_id.hex():
                self._owner_self_resolve(ob)
            elif ob not in self._owner_watch:
                self._owner_locate_send(tuple(info.owner_node), [ob])
            return
        from ray_tpu.core.client import ObjectLostError
        self._seal_error_object(oid, ObjectLostError(
            f"Object {oid.hex()[:16]} was lost: "
            f"{m.get('cause', 'node died')}"))

    def _request_pull(self, oid: ObjectID, node_hex: str,
                      address: str) -> None:
        ob = oid.binary()
        if ob in self._pulls:
            return
        info = self.objects.get(oid)
        if info is None or info.state != "pending":
            return
        if self._try_local_pull(oid, ob, node_hex):
            return
        # reserve the pull slot BEFORE the async connect so concurrent
        # object_at notifications don't start duplicate transfers
        self._pulls[ob] = {"src": node_hex, "view": None, "size": None,
                           "received": 0, "is_error": False}

        def go(conn):
            st = self._pulls.get(ob)
            if st is None or st["src"] != node_hex:
                return   # resolved or re-routed while connecting
            if conn is None:
                self._pulls.pop(ob, None)
                self._watched.discard(ob)
                self.post_later(0.2,
                                lambda: self._ensure_remote_watch([oid]))
                return
            try:
                conn.send({"t": "pull_object", "object_id": ob,
                           # after any failed attempt, insist on a direct
                           # stream — never bounce through a relay again
                           "no_redirect":
                               self._pull_attempts.get(ob, 0) > 0})
            except protocol.ConnectionClosed:
                self._pulls.pop(ob, None)
                self._watched.discard(ob)
                self._drop_peer(node_hex)
                self.post_later(0.2,
                                lambda: self._ensure_remote_watch([oid]))
        self._peer_conn_async(node_hex, address, go)

    # same-process fast path -------------------------------------------------

    def _try_local_pull(self, oid: ObjectID, ob: bytes,
                        node_hex: str) -> bool:
        """Peer lives in THIS process (virtual cluster): hand the bytes
        over with one memcpy.  Thread discipline: the source's loop pins
        + maps, our loop copies into our arena, the source's loop
        unpins.  Falls back to the socket path on any miss."""
        if not self.config.same_host_object_fastpath:
            return False
        src = _LOCAL_NODES_BY_HEX.get(node_hex)
        if src is None or src is self or src._stop.is_set():
            return False
        self._pulls[ob] = {"src": node_hex, "view": None, "size": None,
                           "received": 0, "is_error": False, "local": True}

        def replay_pulls(queued):
            # socket peers that asked for the object mid-memcpy: serve
            # them now (object present -> stream; absent -> pull_failed
            # so they re-route)
            for cid, pm in queued:
                peer = self.clients.get(cid)
                if peer is not None:
                    self._h_pull_object(peer, pm)

        def fallback():
            st = self._pulls.get(ob)
            if st is not None and st.get("local"):
                self._pulls.pop(ob, None)
                self._watched.discard(ob)
                replay_pulls(st.get("replay_pulls", []))
                self.post_later(0.1,
                                lambda: self._ensure_remote_watch([oid]))

        def on_src():
            info = src.objects.get(oid)
            if (info is None or info.state != "ready"
                    or info.loc not in ("shm", "inline")):
                self.post(fallback)
                return
            if info.loc == "inline":
                data, is_err = info.data, info.is_error
                self.post(lambda: self._local_pull_inline(
                    oid, ob, data, is_err))
                return
            if src.store.is_spilled(oid):
                src.store.restore(oid)
            src.store.pin(oid)
            try:
                view = src.store._shm.map(oid)
            except Exception:
                src.store.unpin(oid)
                self.post(fallback)
                return
            size = src.objects[oid].size

            def on_dst():
                try:
                    try:
                        buf = self.store._shm.create(oid, size)
                        _gil_free_copy(buf, view, size)
                        del buf
                        self.store._shm.seal(oid)
                    except ObjectExists:
                        pass
                    st = self._pulls.pop(ob, None)
                    if st is None:
                        return   # resolved another way meanwhile
                    self.store.register(oid, size)
                    info2 = self.objects.setdefault(oid, ObjInfo())
                    info2.state = "ready"
                    info2.loc = "shm"
                    info2.size = size
                    self._resolve_waiters(oid, info2)
                    replay_pulls(st.get("replay_pulls", []))
                except Exception:
                    fallback()
                finally:
                    src.post(lambda: src.store.unpin(oid))
            self.post(on_dst)

        src.post(on_src)
        # safety net: a wedged source loop must not hang the pull
        self.post_later(10.0, fallback)
        return True

    def _local_pull_inline(self, oid: ObjectID, ob: bytes, data,
                           is_err: bool) -> None:
        st = self._pulls.pop(ob, None)
        if st is None:
            return
        info = self.objects.setdefault(oid, ObjInfo())
        if info.state != "pending":
            return
        info.state = "error" if is_err else "ready"
        info.loc = "inline"
        info.data = data
        info.size = len(data or b"")
        info.is_error = is_err
        self._resolve_waiters(oid, info)
        for cid, pm in st.get("replay_pulls", []):
            peer = self.clients.get(cid)
            if peer is not None:
                self._h_pull_object(peer, pm)

    # sender side -----------------------------------------------------------

    def _h_pull_object(self, rec, m):
        """A peer wants an object stored here: inline goes in one frame,
        shm goes in windowed chunks (reference: object_manager.proto:61
        Push with chunked ObjectChunk stream).

        Broadcast shaping (reference: push_manager.h rate-limited
        parallel pushes; here a relay CHAIN): if this node is itself
        still RECEIVING the object, it serves the request as a relay —
        forwarding chunks as they arrive — and if this node is the
        source already streaming to someone, later requesters are
        redirected to the most recent receiver, so an N-node broadcast
        pipelines through the receivers instead of serializing N full
        streams at the source."""
        ob = m["object_id"]
        oid = ObjectID(ob)
        pst = self._pulls.get(ob)
        if pst is not None:
            if pst.get("local"):
                # same-process fast path in flight: chunk relay state
                # never materializes — replay this request when the
                # memcpy lands (or fails) instead of parking it forever
                pst.setdefault("replay_pulls", []).append(
                    (rec.conn_id, dict(m)))
                return
            # mid-pull here: relay chunks to this requester as they land
            self._relay_register(rec, ob, pst)
            return
        if not m.get("no_redirect"):
            tail = self._bcast_tail.get(ob)
            if tail is not None and tail[0] != rec.node_hex \
                    and (rec.conn_id, ob) not in self._out_transfers:
                active = any(o == ob for (_c, o) in self._out_transfers)
                if active:
                    # chain: newest requester fetches from the previous
                    # one; we keep streaming only the first copy
                    self._push(rec, {"t": "pull_redirect", "object_id": ob,
                                     "node": tail[0], "address": tail[1]})
                    self._note_bcast_tail(ob, rec)
                    return
        info = self.objects.get(oid)
        if info is not None and info.loc == "device":
            # device-resident: spill to host first, then serve the pull
            # (the queued request replays when materialization lands)
            self._device_pending_pulls.setdefault(ob, []).append(
                (rec.conn_id, dict(m)))
            if info.state == "ready":
                self._request_materialize(oid, info)
            return
        if info is None or info.state == "pending":
            self._push(rec, {"t": "pull_failed", "object_id": ob,
                             "error": "object not found on this node"})
            return
        if info.loc == "inline":
            self._push(rec, {"t": "obj_inline", "object_id": ob,
                             "data": info.data, "is_error": info.is_error})
            return
        if self.store.is_spilled(oid):
            self.store.restore(oid)
        self.store.touch(oid)
        self.store.pin(oid)
        try:
            view = self.store._shm.map(oid)
        except Exception:
            self.store.unpin(oid)
            self._push(rec, {"t": "pull_failed", "object_id": ob,
                             "error": "object vanished mid-pull"})
            return
        st = {"oid": oid, "view": view, "size": info.size, "next_off": 0,
              "pinned": True}
        self._out_transfers[(rec.conn_id, ob)] = st
        self._note_bcast_tail(ob, rec)
        for _ in range(self.config.object_transfer_window):
            if not self._send_next_chunk(rec, st):
                break

    def _note_bcast_tail(self, ob: bytes, rec: ClientRec) -> None:
        """Remember the most recent receiver as the chain tail for later
        requesters (only peers with a known node identity qualify)."""
        if rec.node_hex and rec.node_hex in self.cluster_view:
            addr = self.cluster_view[rec.node_hex].get("address")
            if addr:
                self._bcast_tail[ob] = (rec.node_hex, addr)

    def _send_next_chunk(self, rec: ClientRec, st: dict) -> bool:
        off = st["next_off"]
        limit = st["size"] if st.get("available") is None \
            else min(st["size"], st["available"])
        if off >= limit or st["view"] is None:
            return False
        n = min(self.config.object_transfer_chunk_size, limit - off)
        st["next_off"] = off + n
        # blob frame: the chunk bytes ride out-of-band of the pickle —
        # one copy into the socket buffer instead of slice+pickle+buffer
        self._push_blob(rec, {"t": "obj_chunk",
                              "object_id": st["oid"].binary(),
                              "offset": off, "total_size": st["size"]},
                        st["view"][off:off + n])
        if st["next_off"] >= st["size"]:
            # final chunk queued: release our references now; remaining
            # acks for this transfer are ignored
            st["view"] = None
            if st.get("pinned"):
                self.store.unpin(st["oid"])
            self._out_transfers.pop((rec.conn_id, st["oid"].binary()), None)
        return True

    def _h_obj_chunk_ack(self, rec, m):
        st = self._out_transfers.get((rec.conn_id, m["object_id"]))
        if st is not None:
            st["outstanding"] = max(0, st.get("outstanding", 1) - 1)
            if self._send_next_chunk(rec, st):
                st["outstanding"] = st.get("outstanding", 0) + 1

    # relay (chain broadcast) ------------------------------------------------

    def _relay_register(self, rec, ob: bytes, pst: dict) -> None:
        """Serve a pull for an object we are still receiving: forward
        already-received bytes now, the rest as chunks arrive."""
        oid = ObjectID(ob)
        if pst.get("size") is None:
            # no chunk yet: start the relay when the first one lands
            pst.setdefault("relay_waiting", []).append(rec.conn_id)
            return
        st = {"oid": oid, "view": pst["view"], "size": pst["size"],
              "next_off": 0, "available": pst["received"],
              "outstanding": 0, "pinned": False, "relay": True}
        self._out_transfers[(rec.conn_id, ob)] = st
        pst.setdefault("relay_conns", []).append(rec.conn_id)
        self._note_bcast_tail(ob, rec)
        self._relay_advance(rec, st)

    def _relay_advance(self, rec, st: dict) -> None:
        window = self.config.object_transfer_window
        while st.get("outstanding", 0) < window:
            if not self._send_next_chunk(rec, st):
                break
            st["outstanding"] = st.get("outstanding", 0) + 1

    def _relay_on_upstream_chunk(self, ob: bytes, pst: dict) -> None:
        """Upstream bytes advanced: wake pending relays and push more."""
        for cid in pst.pop("relay_waiting", []):
            peer = self.clients.get(cid)
            if peer is not None:
                self._relay_register(peer, ob, pst)
        for cid in list(pst.get("relay_conns", [])):
            st = self._out_transfers.get((cid, ob))
            peer = self.clients.get(cid)
            if st is None or peer is None:
                pst["relay_conns"].remove(cid)
                continue
            st["available"] = pst["received"]
            self._relay_advance(peer, st)

    def _relay_on_pull_done(self, oid: ObjectID, pst: dict) -> None:
        """Our pull finished and the buffer was sealed: re-map (pinned)
        for relays that still have bytes to send."""
        ob = oid.binary()
        for cid in pst.get("relay_conns", []):
            st = self._out_transfers.get((cid, ob))
            if st is None:
                continue
            st["available"] = st["size"]
            try:
                st["view"] = self.store._shm.map(oid)
                self.store.pin(oid)
                st["pinned"] = True
            except Exception:
                self._out_transfers.pop((cid, ob), None)
                peer = self.clients.get(cid)
                if peer is not None:
                    self._push(peer, {"t": "pull_failed", "object_id": ob,
                                      "error": "relay source lost the "
                                               "object mid-stream"})
                continue
            peer = self.clients.get(cid)
            if peer is not None:
                self._relay_advance(peer, st)

    # receiver side ----------------------------------------------------------

    def _on_peer_msg(self, node_hex: str, m: dict) -> None:
        t = m.get("t")
        try:
            if t == "obj_chunk":
                self._on_obj_chunk(node_hex, m)
            elif t == "obj_inline":
                self._on_obj_inline(m)
            elif t == "pull_redirect":
                self._on_pull_redirect(m)
            elif t == "pull_failed":
                self._on_pull_failed(m)
            elif t == "object_at":
                # owner's reply to our owner_locate rides this conn
                self._on_owner_object_at_push(m)
            elif t == "owner_object_lost":
                self._on_owner_object_lost_push(m)
            elif t == "owner_object_at":
                # a holder may report on a conn WE opened to it earlier
                self._owner_add_location(m["object_id"], m["node"],
                                         m["address"])
            elif t == "owner_handoff_ack":
                # decommission handoff landed on the survivor: the
                # drain can finish (and this node can exit) safely
                self._drain_ack(node_hex)
            elif t == "shutdown":
                self._drop_peer(node_hex)
            # replies (e.g. to our peer register) are ignored
        except Exception:
            sys.stderr.write(f"[node] peer message {t} failed:\n"
                             + traceback.format_exc())

    def _on_obj_chunk(self, node_hex: str, m: dict) -> None:
        ob = m["object_id"]
        st = self._pulls.get(ob)
        if st is None:
            return  # stale transfer (object resolved another way)
        oid = ObjectID(ob)
        if st["view"] is None:
            st["size"] = m["total_size"]
            try:
                st["view"] = self.store._shm.create(oid, st["size"])
            except Exception as e:
                # arena full beyond eviction (or segment clash): fail pull
                self._pulls.pop(ob, None)
                self._fail_pull(oid, f"store create failed during "
                                     f"transfer: {type(e).__name__}: {e}")
                return
        data = m["data"]
        off = m["offset"]
        st["view"][off:off + len(data)] = data
        st["received"] += len(data)
        conn = self._peer_conns.get(node_hex)
        if conn is not None:
            try:
                conn.send({"t": "obj_chunk_ack", "object_id": ob})
            except protocol.ConnectionClosed:
                pass
        if st.get("relay_waiting") or st.get("relay_conns"):
            # chain broadcast: forward the new bytes downstream
            self._relay_on_upstream_chunk(ob, st)
        if st["received"] >= st["size"]:
            st["view"] = None   # release buffer before seal/register
            self.store._shm.seal(oid)
            self._pulls.pop(ob, None)
            self.store.register(oid, st["size"])
            info = self.objects.setdefault(oid, ObjInfo())
            info.state = "ready"
            info.loc = "shm"
            info.size = st["size"]
            if st.get("relay_conns"):
                self._relay_on_pull_done(oid, st)
            self._resolve_waiters(oid, info)

    def _on_pull_redirect(self, m: dict) -> None:
        """The source is busy broadcasting: fetch from the chain tail it
        named instead.  Ignored once bytes started flowing; a failed
        relay fetch falls back through the normal re-watch path (which
        sets no_redirect, so the source then serves directly)."""
        ob = m["object_id"]
        st = self._pulls.get(ob)
        if st is None or st.get("size") is not None:
            return
        self._pulls.pop(ob, None)
        self._watched.discard(ob)
        # a redirect counts as an attempt: if the relay fetch fails, the
        # re-watch retries the source with no_redirect set (direct serve)
        self._pull_attempts[ob] = self._pull_attempts.get(ob, 0) + 1
        self._request_pull(ObjectID(ob), m["node"], m["address"])

    def _on_obj_inline(self, m: dict) -> None:
        ob = m["object_id"]
        self._pulls.pop(ob, None)
        oid = ObjectID(ob)
        info = self.objects.setdefault(oid, ObjInfo())
        if info.state != "pending":
            return
        info.state = "error" if m.get("is_error") else "ready"
        info.loc = "inline"
        info.data = m["data"]
        info.size = len(m["data"])
        info.is_error = bool(m.get("is_error"))
        self._resolve_waiters(oid, info)

    def _on_pull_failed(self, m: dict) -> None:
        ob = m["object_id"]
        st = self._pulls.pop(ob, None)
        src = st["src"] if st else None
        self._watched.discard(ob)
        oid = ObjectID(ob)
        # a failed source is no longer a valid location for objects we own
        orec = self.owned.get(ob)
        if orec is not None and src:
            orec.locations.pop(src, None)
        attempts = self._pull_attempts.get(ob, 0) + 1
        self._pull_attempts[ob] = attempts
        if attempts <= 5:
            # the location may be stale (freed/evicted+deleted); re-locate
            self.post_later(0.2, lambda: self._ensure_remote_watch([oid]))
        else:
            self._fail_pull(oid, m.get("error", "pull failed"), src=src)

    def _fail_pull(self, oid: ObjectID, cause: str,
                   src: Optional[str] = None) -> None:
        info = self.objects.get(oid)
        if info is None or info.state != "pending":
            return
        ob = oid.binary()
        if info.owner_node and info.owner_node[0] == self.node_id.hex():
            orec = self.owned.get(ob)
            if orec is not None and src:
                orec.locations.pop(src, None)
            self._pull_attempts.pop(ob, None)
            # may pull another copy, wait on the producer, reconstruct,
            # or seal the loss itself
            self._owner_self_resolve(ob)
            return
        from ray_tpu.core.client import ObjectLostError
        self._seal_error_object(oid, ObjectLostError(
            f"Object {oid.hex()[:16]} could not be fetched: {cause}"))

    def _hh_delete_object(self, m: dict) -> None:
        self._delete_local_object(ObjectID(m["object_id"]))

    # -- decommission handoff ------------------------------------------------

    def _drain_handoff(self) -> None:
        """The object-plane half of a graceful decommission: before this
        node exits, (a) objects it OWNS migrate — bytes when held here,
        plus the ownership record (locations, producer task id, retained
        lineage spec) — to one survivor, which becomes their new
        location authority; (b) objects owned ELSEWHERE whose possibly-
        only copy lives here have their VALUE pushed to the owner, so
        the owner never needs lineage re-execution for a PLANNED
        removal.  Consumers holding stale owner hints fall back through
        the head directory (owner-unreachable path), which knows the
        survivor's copies.  Lineage reconstruction remains the safety
        net for anything this handoff didn't ship (chaos-proven by
        killing the node mid-handoff)."""
        fi = _fi._active
        if fi is not None:
            fi.on_drain("node_drain_handoff", {"node": self})
        if self._stop.is_set():
            return      # chaos killed us mid-decommission: no handoff
        me = self.node_id.hex()
        survivor = None
        for h, n in self.cluster_view.items():
            if h != me and n.get("alive") and not n.get("draining"):
                survivor = (h, n.get("address"))
                break
        owned_entries: list[dict] = []
        for oid, info in list(self.objects.items()):
            if info.state not in ("ready", "error") \
                    and not (info.state == "pending" and info.owner_node
                             and info.owner_node[0] == me):
                continue
            if info.loc == "device":
                continue    # HBM buffers die with their process
            ob = oid.binary()
            data = None
            if info.loc == "inline":
                data = info.data
            elif info.loc == "shm":
                try:
                    if self.store.is_spilled(oid):
                        self.store.restore(oid)
                    data = bytes(self.store._shm.map(oid))
                except Exception:
                    data = None
            if info.owner_node and info.owner_node[0] == me:
                # owned here: full record (+ bytes when we hold them)
                orec = self.owned.get(ob)
                lin = None
                if orec is not None and orec.task_id:
                    entry = self.lineage.get(orec.task_id)
                    if entry is not None:
                        lin = entry.get("spec")
                owned_entries.append({
                    "object_id": ob, "data": data,
                    "is_error": info.is_error,
                    "task_id": orec.task_id if orec else b"",
                    "locations": dict(orec.locations) if orec else {},
                    "lineage": lin,
                })
            elif data is not None and info.owner_node:
                # owner elsewhere, bytes here (maybe the only copy):
                # ship the VALUE straight to the owner — the existing
                # forwarded-inline-result push, reused verbatim
                self._owner_push(
                    info.owner_node[0], info.owner_node[1],
                    {"t": "owner_object_value", "object_id": ob,
                     "data": data, "is_error": info.is_error,
                     "node": me, "address": self.address})
        if survivor is not None and owned_entries:
            hexn, addr = survivor
            self._drain_acks_pending.add(hexn)

            def go(conn, hexn=hexn):
                if conn is None:
                    self.post(lambda: self._drain_ack(hexn))
                    return
                try:
                    conn.send({"t": "owner_handoff",
                               "from_hex": me,
                               "from_addr": self.address,
                               "objects": owned_entries})
                except protocol.ConnectionClosed:
                    self.post(lambda: self._drain_ack(hexn))
            self._peer_conn_async(hexn, addr, go)
            sys.stderr.write(f"[node] drain handoff: {len(owned_entries)}"
                             f" owned object(s) -> node {hexn[:8]}\n")
            # bounded ack wait: a wedged survivor must not hold the
            # decommission open forever
            self.post_later(5.0, self._drain_finish)
        else:
            self._drain_finish()

    def _drain_ack(self, node_hex: str) -> None:
        self._drain_acks_pending.discard(node_hex)
        if not self._drain_acks_pending:
            self._drain_finish()

    def _h_owner_handoff(self, rec, m):
        """A draining peer hands us its owned objects: store the bytes,
        ADOPT the ownership records (this node becomes the location
        authority: locations, producer task ids, retained lineage), and
        report the new copies so head-directory fallback finds them the
        moment the drained node exits."""
        from_hex = m.get("from_hex", "")
        adopted = 0
        for ent in m.get("objects", ()):
            ob = ent["object_id"]
            oid = ObjectID(ob)
            info = self.objects.setdefault(oid, ObjInfo())
            lin = ent.get("lineage")
            if lin is not None:
                # install the producer spec FIRST: _record_lineage also
                # creates the OwnedRec entries for its return ids
                self._record_lineage(lin)
            orec = self.owned.get(ob)
            if orec is None:
                orec = self.owned[ob] = OwnedRec()
            orec.task_id = orec.task_id or ent.get("task_id", b"")
            for h, a in (ent.get("locations") or {}).items():
                if h != from_hex:
                    orec.locations[h] = a
            info.owner_node = (self.node_id.hex(), self.address)
            data = ent.get("data")
            if data is not None and info.state == "pending":
                info.state = "error" if ent.get("is_error") else "ready"
                info.loc = "inline"
                info.data = data
                info.size = len(data)
                info.is_error = bool(ent.get("is_error"))
                self._resolve_waiters(oid, info)
            adopted += 1
        sys.stderr.write(f"[node] adopted {adopted} owned object(s) "
                         f"from draining node {from_hex[:8]}\n")
        self._push(rec, {"t": "owner_handoff_ack",
                         "node_hex": self.node_id.hex()})

    # -- node death recovery -------------------------------------------------

    def _hh_node_dead(self, m: dict) -> None:
        node_hex = m["node"]
        self._drop_peer(node_hex)
        self.actor_cache = {k: v for k, v in self.actor_cache.items()
                            if v[0] != node_hex}
        # owned objects whose only copies died: re-resolve (pull another
        # copy / reconstruct) for any object someone is waiting on
        me = self.node_id.hex()
        for ob, orec in list(self.owned.items()):
            if orec.locations.pop(node_hex, None) is None:
                continue
            if orec.locations and any(h == me or h in self.cluster_view
                                      for h in orec.locations):
                continue
            oid = ObjectID(ob)
            info = self.objects.get(oid)
            needed = (orec.watchers
                      or oid in self._mg_by_oid
                      or oid in self.dep_waiting
                      or (info is not None and info.wait_waiters))
            if needed and info is not None and info.state == "pending":
                self._watched.discard(ob)
                self._owner_self_resolve(ob)
        # consumers whose owner-directory authority died: fall back to
        # the head for anything we were watching through that owner
        stale = [ob for ob, h in self._owner_watch.items()
                 if h == node_hex]
        if stale:
            self._owner_unreachable(node_hex, stale)
            for ob in stale:
                self._watched.discard(ob)
        for tid, fw in list(self._fwd_tasks.items()):
            if fw["dst"] != node_hex:
                continue
            self._fwd_tasks.pop(tid, None)
            spec = fw["spec"]
            for b in spec["return_ids"]:
                self._fwd_by_oid.pop(b, None)
            if fw.get("actor"):
                # the actor may restart elsewhere, but this call's
                # execution state died with the node
                self._fail_task(spec, f"Actor's node {node_hex[:8]} died "
                                      "while the method was in flight")
            elif fw["retries"] > 0:
                # lineage-lite: deterministic return ids mean a re-run
                # re-creates exactly the lost objects (reference:
                # object_recovery_manager.h reconstruction)
                spec = dict(spec)
                spec["max_retries"] = fw["retries"] - 1
                if _fr._active is not None:
                    _fr._active.stamp(spec, "retry")
                self._forward_task(spec)
            else:
                self._fail_task(spec, f"Node {node_hex[:8]} died while "
                                      "running forwarded task")
