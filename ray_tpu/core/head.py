"""Head service: the cluster control plane (GCS analogue).

Owns cluster-scope state the reference keeps in the GCS server
(reference: src/ray/gcs/gcs_server/gcs_server.h:77):

  * node membership + health (heartbeats, death detection)
    (reference: gcs_node_manager.cc, gcs_health_check_manager.cc)
  * cluster task routing / spillover scheduling
    (reference: gcs_actor_scheduler.cc, cluster_task_manager.h:33 —
    here routing is head-side because nodes forward what they can't place)
  * actor directory: placement, named actors, state fan-out, node-death
    re-placement (reference: gcs_actor_manager.cc:249,1247)
  * object location directory with watchers (reference: the ownership-era
    object directory, object_directory.h — centralized here, v1)
  * KV store, pubsub, function store (reference: gcs_kv_manager.cc,
    gcs_pubsub, function_manager.py)
  * placement groups with cross-node 2PC bundle reservation
    (reference: gcs_placement_group_scheduler.h:104-169)
  * resource view broadcast to nodes (reference: ray_syncer.h:30-47)

Only NODE services connect here; drivers and workers always talk to their
local node, which proxies cluster-scope requests (the reference's raylet
does the same for GCS-bound client calls).
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from ray_tpu._config import RayTpuConfig
from ray_tpu.core import flight_recorder as _fr
from ray_tpu.core.resources import (bundle_total as _bundle_total,
                                    covers as _covers)
from ray_tpu.core.service import (ClientRec, ClusterStoreMixin,
                                  EventLoopService)


@dataclass
class NodeRec:
    node_hex: str
    address: str
    conn_id: int
    total: dict
    available: dict
    queued: dict = field(default_factory=dict)   # demand waiting locally
    # demand optimistically routed here since the last heartbeat: the
    # debit-only `available` saturates during a burst, and without a
    # backlog signal every post-saturation task tie-broke to the
    # SUBMITTER — one node ended up with ~97% of a 4000-task burst
    # while seven sat idle (measured).  Heartbeats reset this; `queued`
    # then carries the ground truth.
    routed: dict = field(default_factory=dict)
    labels: dict = field(default_factory=dict)   # e.g. provider_node_id
    last_beat: float = field(default_factory=time.monotonic)
    alive: bool = True
    # graceful decommission (ACTIVE -> DRAINING -> TERMINATED): a
    # draining node takes no NEW placements but keeps heartbeating and
    # finishing what it holds until drain_done (or the forced deadline)
    draining: bool = False
    drain_deadline: float = 0.0   # monotonic; forced-removal backstop
    death_cause: str = ""         # why the node left the membership


@dataclass
class ActorDir:
    actor_id: bytes
    node_hex: str
    state: str                    # pending | alive | restarting | dead
    spec: dict
    name: str = ""
    namespace: str = ""
    death_cause: str = ""
    restarts_left: int = 0        # head-side budget for node-death re-place
    watchers: set = field(default_factory=set)   # node_hex wanting actor_at


@dataclass
class PGDir:
    pg_id: bytes
    bundles: list
    strategy: str
    assignment: list              # bundle_idx -> node_hex
    state: str = "created"


class HeadService(ClusterStoreMixin, EventLoopService):
    name = "head"

    def __init__(self, config: RayTpuConfig, session: str,
                 listen_host: str = "127.0.0.1", port: int = 0,
                 persistence_path: Optional[str] = None,
                 recover_from: Optional[str] = None):
        super().__init__(listen_host, port)
        self.config = config
        self.session = session
        self.tick_interval = 0.1
        # flight recorder: a standalone head process must arm itself or
        # the head_route stamp never fires in multi-machine deployments
        if config.flight_recorder and _fr._active is None:
            _fr.enable()

        self.nodes: dict[str, NodeRec] = {}
        self._node_by_conn: dict[int, str] = {}
        # tie-break randomization for the hybrid policy (seeded: test
        # runs stay reproducible per head instance)
        import random as _random
        self._sched_rng = _random.Random(0xC0FFEE)
        self.actors: dict[bytes, ActorDir] = {}
        self.named_actors: dict[tuple[str, str], bytes] = {}
        self._init_stores()   # kv / pubsub / function store (mixin)
        self.object_locs: dict[bytes, set[str]] = {}
        self.obj_watchers: dict[bytes, set[str]] = {}
        # diagnostic: how many locate_object lookups reached the head —
        # with the ownership directory live, owned-object traffic should
        # bypass the head entirely
        self.locate_requests = 0
        self.pgs: dict[bytes, PGDir] = {}
        # creation queue: pg_id -> {"bundles", "strategy", "busy"}
        # (reference: gcs_placement_group_manager pending queue)
        self.pending_pgs: dict[bytes, dict] = {}
        # tasks routed to a still-pending PG, replayed on commit
        self._pg_waiters: dict[bytes, list] = {}

        # durable control-plane state (reference: gcs_server.cc:58-61 —
        # the Redis/file-backed GCS table storage that lets the head
        # restart without losing the cluster's KV/actor/PG directory).
        # Instead of an EXTERNAL store, snapshots also replicate to every
        # node (the cluster IS the database): a replacement head on a
        # fresh machine bootstraps from any surviving node's replica
        # (`recover_from=`), which survives losing the head MACHINE, not
        # just the head process.
        self.persistence_path = persistence_path
        self._dirty = False
        self._last_snapshot = 0.0
        self._snapshot_writing = False
        self._replica_seq = 0
        self._written_seq = 0
        self._snap_write_lock = threading.Lock()
        # replica seq numbers are scoped to one head INCARNATION: a
        # restarted head (seq reset to 0) must not be "stale" vs the
        # replicas its predecessor fanned out
        import uuid as _uuid
        self._boot_id = _uuid.uuid4().hex
        # actors restored as pending get a rejoin grace window; if their
        # node never comes back they re-place or die (reference: GCS
        # reconciles actors after the reconnection grace period)
        self._restored_pending: set = set()
        self._restored_at = 0.0
        if persistence_path and os.path.exists(persistence_path):
            self._restore_snapshot()
        elif recover_from:
            # fresh machine, no local snapshot: pull the newest replica
            # a node holds (head-MACHINE loss recovery)
            self._recover_from_node(recover_from)

    def _cleanup(self) -> None:
        # graceful stop must not lose acknowledged mutations
        if self.persistence_path and self._dirty:
            try:
                self._snapshot(sync=True)
            except Exception:
                import traceback
                traceback.print_exc()
        super()._cleanup()

    # -------------------------------------------------------- persistence

    def mark_dirty(self) -> None:
        self._dirty = True

    def _build_snapshot_state(self) -> dict:
        """Cheap copies on the loop thread; the expensive pickle+write
        happens off-thread so heartbeats never queue behind disk IO."""
        return {
            "kv": dict(self.kv),
            "functions": dict(self.functions),
            "named_actors": dict(self.named_actors),
            "actors": [{"actor_id": ad.actor_id, "node_hex": ad.node_hex,
                        "state": ad.state, "spec": ad.spec,
                        "name": ad.name, "namespace": ad.namespace,
                        "death_cause": ad.death_cause,
                        "restarts_left": ad.restarts_left}
                       for ad in self.actors.values()],
            "pgs": [{"pg_id": p.pg_id, "bundles": p.bundles,
                     "strategy": p.strategy, "assignment": p.assignment,
                     "state": p.state} for p in self.pgs.values()],
        }

    def _write_snapshot(self, state: dict, seq: int = 0) -> None:
        import pickle
        import threading as _threading
        # unique tmp per writer + seq fence: the sync path
        # (snapshot_now) can run while the async snapshot thread is
        # mid-write — a shared tmp would interleave two pickles into
        # garbage, and an older writer finishing LAST would clobber the
        # newer snapshot.  os.replace keeps each install atomic.
        tmp = (f"{self.persistence_path}.tmp."
               f"{_threading.get_ident()}")
        with open(tmp, "wb") as f:
            pickle.dump(state, f)
        with self._snap_write_lock:
            if seq < self._written_seq:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
                return   # a newer snapshot already landed
            os.replace(tmp, self.persistence_path)
            self._written_seq = seq

    def _encode_replica(self, state: dict, seq: int) -> dict:
        import pickle
        return {"t": "head_snapshot", "seq": seq, "boot": self._boot_id,
                "session": self.session, "data": pickle.dumps(state)}

    def _fan_out_replicas(self, msg: dict) -> None:
        """Push the snapshot to every alive node — losing the head
        MACHINE (disk included) then costs nothing: a replacement head
        recovers from the freshest surviving replica (`recover_from=`)."""
        for n in self.nodes.values():
            if n.alive:
                c = self.clients.get(n.conn_id)
                if c is not None:
                    self._push(c, msg)

    def _snapshot(self, sync: bool = False) -> None:
        state = self._build_snapshot_state()
        self._dirty = False
        # seq assigned HERE on the loop thread: both paths get a total
        # order, and nodes use it to drop a stale async replica that
        # fans out after a newer snapshot_now one
        self._replica_seq += 1
        seq = self._replica_seq
        if sync:
            self._write_snapshot(state, seq)
            self._fan_out_replicas(self._encode_replica(state, seq))
            return
        if self._snapshot_writing:
            self._dirty = True   # retry next tick
            return
        self._snapshot_writing = True

        def work():
            try:
                self._write_snapshot(state, seq)
                # the expensive state pickle happens HERE, off-thread —
                # only the per-node sends return to the loop thread
                msg = self._encode_replica(state, seq)
                self.post(lambda: self._fan_out_replicas(msg))
            except Exception:
                import traceback
                traceback.print_exc()
            finally:
                self._snapshot_writing = False
        import threading
        threading.Thread(target=work, daemon=True,
                         name="raytpu-head-snapshot").start()

    def _h_snapshot_now(self, rec: ClientRec, m: dict) -> None:
        """Force a durable snapshot + replica fan-out NOW and reply
        after the fan-out pushes are queued — on each node's head
        channel the replica strictly precedes this reply, so a caller
        that sees the reply can rely on its own node's replica being
        on disk (event-driven replication barrier; used by tests and
        pre-maintenance flushes instead of polling the 0.5 s cycle)."""
        if self.persistence_path:
            self._snapshot(sync=True)
        if "reqid" in m:
            self._reply(rec, m["reqid"], ok=True,
                        replicated=bool(self.persistence_path))

    def _restore_snapshot(self) -> None:
        import pickle
        if not os.path.exists(self.persistence_path):
            return
        with open(self.persistence_path, "rb") as f:
            state = pickle.load(f)
        self._apply_snapshot_state(state)

    def _recover_from_node(self, addresses: str) -> None:
        """Bootstrap a replacement head from node snapshot replicas
        (reference capability: gcs_server.cc Redis-backed storage — here
        the cluster itself is the store; see __init__ comment).

        ``addresses`` may be comma-separated: every reachable node is
        asked and the HIGHEST-seq replica wins — a fan-out that missed
        one node must not resurrect stale state.  Wrong-session replies
        are rejected (two clusters on one host is the normal test
        shape).  All failures surface as RuntimeError so callers can
        distinguish them from listener-bind errors."""
        import pickle
        from ray_tpu.core import protocol
        best = None   # (seq, data)
        errors = []
        for address in [a.strip() for a in addresses.split(",") if a]:
            try:
                conn = protocol.connect(address, timeout=15.0)
                try:
                    conn.send({"t": "fetch_head_snapshot", "reqid": 1})
                    reply = conn.recv(timeout=15.0)
                finally:
                    conn.close()
            except (OSError, protocol.ConnectionClosed) as e:
                errors.append(f"{address}: {e}")
                continue
            if reply.get("session") not in (None, self.session):
                errors.append(f"{address}: replica belongs to session "
                              f"{reply.get('session')!r}")
                continue
            data = reply.get("data")
            if not data:
                errors.append(f"{address}: {reply.get('error')}")
                continue
            seq = reply.get("seq", 0)
            if best is None or seq > best[0]:
                best = (seq, data)
        if best is None:
            raise RuntimeError(
                f"no node holds a usable head snapshot replica: {errors}")
        self._apply_snapshot_state(pickle.loads(best[1]))
        self.mark_dirty()   # persist locally as soon as possible

    def _apply_snapshot_state(self, state: dict) -> None:
        self.kv = state["kv"]
        self.functions = state["functions"]
        self.named_actors = state["named_actors"]
        for a in state["actors"]:
            self.actors[a["actor_id"]] = ActorDir(
                actor_id=a["actor_id"], node_hex=a["node_hex"],
                # alive actors re-assert themselves when their node
                # reconnects and re-reports; until then they are pending
                state=("pending" if a["state"] in ("alive", "restarting",
                                                   "pending")
                       else a["state"]),
                spec=a["spec"], name=a["name"], namespace=a["namespace"],
                death_cause=a["death_cause"],
                restarts_left=a["restarts_left"])
            if self.actors[a["actor_id"]].state == "pending":
                self._restored_pending.add(a["actor_id"])
        self._restored_at = time.monotonic()
        for p in state["pgs"]:
            self.pgs[p["pg_id"]] = PGDir(
                pg_id=p["pg_id"], bundles=p["bundles"],
                strategy=p["strategy"], assignment=p["assignment"],
                state=p["state"])

    # ------------------------------------------------------------- helpers

    def _node_conn(self, node_hex: str) -> Optional[ClientRec]:
        n = self.nodes.get(node_hex)
        if n is None or not n.alive:
            return None
        return self.clients.get(n.conn_id)

    def _view(self) -> dict:
        return {h: {"address": n.address, "total": n.total,
                    "available": n.available, "alive": n.alive,
                    "draining": n.draining}
                for h, n in self.nodes.items() if n.alive}

    def _choose_node(self, demand: dict,
                     prefer: Optional[str] = None,
                     spread_by_actor_count: bool = False,
                     arg_ids: tuple = (),
                     include_draining: bool = False) -> Optional[str]:
        """The hybrid scheduling policy (reference:
        raylet/scheduling/policy/hybrid_scheduling_policy.cc +
        locality-aware lease targeting, core_worker/lease_policy.h:56).

        Ranking, most significant first:
          1. AVAILABLE (demand fits the node's free resources now)
             strictly above merely FEASIBLE (total covers, busy now).
          2. fewest hosted actors when ``spread_by_actor_count`` (the
             GCS actor scheduler's spread; zero-resource actors make
             resource ranking useless and would pile onto one pool).
          3. critical-resource utilization, TRUNCATED below
             ``scheduler_spread_threshold``: lightly-loaded nodes tie
             instead of packing onto the single emptiest node.
          4. locality: nodes already holding more of the task's args
             (the head's object-location view) save transfer bytes.
          5. the submitting node (no forward hop).
        Exact ties resolve by RANDOM choice — the truncation makes all
        lightly-loaded nodes tie, so this is the reference's top-k
        randomization: racing submitters decorrelate instead of all
        stampeding the deterministic argmax."""
        counts: dict[str, int] = {}
        if spread_by_actor_count:
            for ad in self.actors.values():
                if ad.state != "dead":
                    counts[ad.node_hex] = counts.get(ad.node_hex, 0) + 1
        thr = self.config.scheduler_spread_threshold
        best_key, pool = None, []
        for h, n in self.nodes.items():
            if not n.alive:
                continue
            if n.draining and not include_draining:
                # a draining node takes no new placements — unless it is
                # the ONLY feasible host (the fallback pass below): a
                # drain should delay work, never fail it
                continue
            if not all(n.total.get(k, 0.0) + 1e-9 >= v
                       for k, v in demand.items()):
                continue
            fits_now = all(n.available.get(k, 0.0) + 1e-9 >= v
                           for k, v in demand.items())
            util = 0.0
            for k, tot in n.total.items():
                if tot > 0:
                    used = tot - n.available.get(k, 0.0)
                    util = max(util, used / tot)
            util_rank = 0.0 if util < thr else util
            # backlog per unit capacity: once every node is saturated
            # (fits_now False across the board, util ties at 1.0), the
            # spread signal is how much demand is already PARKED there —
            # last heartbeat's queue plus optimistic routes since
            backlog = 0.0
            for k, v in demand.items():
                tot = n.total.get(k, 0.0)
                if tot > 0 and v > 0:
                    parked = n.queued.get(k, 0.0) + n.routed.get(k, 0.0)
                    backlog = max(backlog, parked / tot)
            locality = sum(1 for ob in arg_ids
                           if h in self.object_locs.get(ob, ()))
            key = (fits_now, -counts.get(h, 0), -util_rank, -backlog,
                   locality, h == prefer)
            if best_key is None or key > best_key:
                best_key, pool = key, [h]
            elif key == best_key:
                pool.append(h)
        if not pool:
            # TASK fallback only: a task routed to a draining node still
            # finishes (drain waits for running work), but an ACTOR
            # placed there would just die at decommission — actors fail
            # placement explicitly instead
            if not include_draining and not spread_by_actor_count \
                    and any(n.alive and n.draining
                            for n in self.nodes.values()):
                return self._choose_node(
                    demand, prefer=prefer,
                    arg_ids=arg_ids, include_draining=True)
            return None
        return pool[self._sched_rng.randrange(len(pool))]

    def _choose_actor_node(self, demand: dict,
                           prefer: Optional[str] = None) -> Optional[str]:
        return self._choose_node(demand, prefer=prefer,
                                 spread_by_actor_count=True)

    @staticmethod
    def _demand(spec: dict) -> dict:
        d = dict(spec.get("resources") or {})
        d.setdefault("CPU",
                     0.0 if spec.get("kind") == "actor_create" else 1.0)
        if spec.get("num_tpus"):
            d["TPU"] = float(spec["num_tpus"])
        return d

    # ----------------------------------------------------------- membership

    def _h_register_node(self, rec: ClientRec, m: dict) -> None:
        rec.kind = "node"
        rec.node_hex = m["node_id"]
        self.nodes[m["node_id"]] = NodeRec(
            node_hex=m["node_id"], address=m["address"],
            conn_id=rec.conn_id, total=dict(m["resources"]),
            available=dict(m["available"]),
            labels=dict(m.get("labels") or {}))
        self._node_by_conn[rec.conn_id] = m["node_id"]
        self._reply(rec, m["reqid"], session=self.session,
                    view=self._view())
        self._broadcast_view()

    def _broadcast_view(self) -> None:
        """Push the membership view to every node immediately on change;
        heartbeat replies keep it fresh in between (reference:
        ray_syncer.h broadcast on NodeAdded/NodeRemoved)."""
        view = self._view()
        for n in self.nodes.values():
            if not n.alive:
                continue
            c = self.clients.get(n.conn_id)
            if c is not None:
                self._push(c, {"t": "view_update", "view": view})

    def _h_heartbeat(self, rec: ClientRec, m: dict) -> None:
        n = self.nodes.get(rec.node_hex)
        if n is not None:
            n.last_beat = time.monotonic()
            n.available = dict(m["available"])
            n.total = dict(m["total"])
            n.queued = dict(m.get("queued") or {})
            n.routed = {}
        if self.pending_pgs:
            self._try_place_pending_pgs()
        if "reqid" in m:
            self._reply(rec, m["reqid"], view=self._view())

    def on_tick(self) -> None:
        timeout = self.config.node_death_timeout_ms / 1000.0
        cutoff = time.monotonic() - timeout
        for h, n in list(self.nodes.items()):
            if n.alive and n.last_beat < cutoff:
                self._node_dead(h, "heartbeat timeout")
        # decommission backstop: a node that never reported drain_done
        # (wedged mid-handoff, lost its head channel) is force-removed
        # at its deadline — the EXPLICIT timeout path; peers then run
        # the normal lineage recovery for whatever the drain didn't ship
        now = time.monotonic()
        for h, n in list(self.nodes.items()):
            if n.alive and n.draining and n.drain_deadline \
                    and now >= n.drain_deadline:
                self._node_dead(h, "decommissioned (drain deadline "
                                   "forced)")
        # backstop for a 2PC whose participant is alive but never replies
        # (node death mid-2PC is handled eagerly in _node_dead)
        stuck = time.monotonic() - max(10.0, 3 * timeout)
        for pg_id, info in list(self.pending_pgs.items()):
            if info.get("busy") and info.get("busy_since", 0) < stuck:
                self._reset_stuck_pg_2pc(pg_id, info)
        if (self.persistence_path and self._dirty
                and time.monotonic() - self._last_snapshot > 0.5):
            try:
                self._snapshot()
                self._last_snapshot = time.monotonic()
            except Exception:
                import traceback
                traceback.print_exc()
        if self._restored_pending:
            grace = 3 * timeout + 2.0
            if time.monotonic() - self._restored_at > grace:
                for aid in list(self._restored_pending):
                    ad = self.actors.get(aid)
                    if (ad is not None and ad.state == "pending"
                            and not (self.nodes.get(ad.node_hex)
                                     and self.nodes[ad.node_hex].alive)):
                        # host never rejoined: re-place or declare dead
                        if ad.restarts_left != 0:
                            if ad.restarts_left > 0:
                                ad.restarts_left -= 1
                            self._replace_actor(
                                ad, "host did not rejoin after head "
                                    "restart")
                        else:
                            self._actor_dead(
                                ad, "host node did not rejoin after "
                                    "head restart")
                self._restored_pending.clear()

    def on_client_drop(self, rec: ClientRec) -> None:
        h = self._node_by_conn.pop(rec.conn_id, None)
        if h is not None and self.nodes.get(h) is not None \
                and self.nodes[h].alive:
            self._node_dead(h, "connection closed")

    def _node_dead(self, node_hex: str, cause: str) -> None:
        n = self.nodes.get(node_hex)
        if n is None or not n.alive:
            return
        n.alive = False
        n.death_cause = cause    # planned removals say "decommissioned"
        # tell everyone first so source nodes can start recovery
        for other in self.nodes.values():
            if other.alive:
                c = self.clients.get(other.conn_id)
                if c is not None:
                    self._push(c, {"t": "node_dead", "node": node_hex,
                                   "cause": cause})
        # object locations: objects only there are lost (unless a source
        # node resubmits the producing task — it decides, we just notify)
        for oid, locs in list(self.object_locs.items()):
            locs.discard(node_hex)
            if not locs:
                del self.object_locs[oid]
                for w in self.obj_watchers.pop(oid, ()):
                    c = self._node_conn(w)
                    if c is not None:
                        self._push(c, {"t": "object_lost", "object_id": oid,
                                       "cause": f"node {node_hex[:8]} died"})
        # actors hosted there: re-place if the restart budget allows
        # (reference: gcs_actor_manager.cc OnNodeDead -> reschedule)
        for ad in list(self.actors.values()):
            if ad.node_hex != node_hex or ad.state == "dead":
                continue
            if ad.restarts_left != 0:
                if ad.restarts_left > 0:
                    ad.restarts_left -= 1
                self._replace_actor(ad, cause)
            else:
                self._actor_dead(ad, f"node died: {cause}")
        # pending PGs mid-2PC with the dead node as a participant would
        # never see their prepare complete — roll back and requeue now
        for pg_id, info in list(self.pending_pgs.items()):
            if info.get("busy") and node_hex in (info.get("assignment")
                                                 or []):
                self._reset_stuck_pg_2pc(pg_id, info)
        self._try_place_pending_pgs()
        # cluster prefix directory: every prefix advertised from that
        # node is gone with its pools — a fetch aimed there would only
        # burn the adopter's fallback budget
        d = getattr(self, "_prefix_dir", None)
        if d is not None:
            d.invalidate_node(node_hex)
        self._publish("node_state", {"node_id": node_hex, "state": "dead",
                                     "cause": cause})
        self._broadcast_view()

    def _replace_actor(self, ad: ActorDir, cause: str) -> None:
        target = self._choose_actor_node(self._demand(ad.spec))
        if target is None:
            self._actor_dead(ad, f"node died ({cause}); no feasible "
                                 "node to restart on")
            return
        ad.state = "restarting"
        ad.node_hex = target
        self._publish("actor_state", {"actor_id": ad.actor_id.hex(),
                                      "state": "restarting"})
        c = self._node_conn(target)
        if c is not None:
            self._push(c, {"t": "place_actor", "spec": ad.spec})

    def _actor_dead(self, ad: ActorDir, cause: str) -> None:
        ad.state = "dead"
        ad.death_cause = cause
        self.mark_dirty()
        self._publish("actor_state", {"actor_id": ad.actor_id.hex(),
                                      "state": "dead"})
        for w in ad.watchers:
            c = self._node_conn(w)
            if c is not None:
                self._push(c, {"t": "actor_at", "actor_id": ad.actor_id,
                               "state": "dead", "death_cause": cause})
        ad.watchers.clear()

    # -------------------------------------------------- graceful drain

    def _begin_node_drain(self, node_hex: str,
                          deadline_s: float) -> Optional[str]:
        """Start decommissioning ``node_hex``; returns an error string
        or None.  The node goes ACTIVE -> DRAINING here (no new
        placements the moment the flag is set), gets the ``node_drain``
        push, and leaves the membership only via drain_done — or the
        forced on_tick backstop at deadline + grace."""
        n = self.nodes.get(node_hex)
        if n is None or not n.alive:
            return f"no alive node {node_hex[:12]}"
        deadline_s = max(0.0, float(deadline_s))
        if not n.draining:
            n.draining = True
            # the node enforces deadline_s itself and then hands off;
            # the head's forced backstop waits a grace on top so a
            # healthy handoff is never raced by its own supervisor
            n.drain_deadline = time.monotonic() + deadline_s + 10.0
            c = self.clients.get(n.conn_id)
            if c is not None:
                self._push(c, {"t": "node_drain",
                               "deadline_s": deadline_s})
            # a DRAINING node's replicas stop serving prefix fetches the
            # moment the drain begins (same rule as the fleet-level
            # drain_replicas hook) — not when teardown finishes
            d = getattr(self, "_prefix_dir", None)
            if d is not None:
                d.invalidate_node(node_hex)
            self._publish("node_state", {"node_id": node_hex,
                                         "state": "draining"})
            self._broadcast_view()
        return None

    # ------------------------------------- cluster prefix directory
    # Head-registered half of the serve fleet's cluster prefix plane
    # (serve/fleet/prefix_directory.py): multi-node fleets publish
    # prompt-chunk-hash → holder entries here and look them up before
    # routing, so any node's replicas can adopt a prefix a peer
    # already paid for.  The directory is ADVISORY — holders
    # re-validate generation + trie liveness at extract time — so the
    # head never holds KV bytes, only bookkeeping (and stays jax-free:
    # the module imports nothing from the inference stack).  Entries
    # die with their node (_node_dead) or at drain begin
    # (_begin_node_drain).  The wire vocabulary (prefix_publish /
    # prefix_lookup / prefix_invalidate) rides the raw envelope like
    # every other control message — no proto change.

    @property
    def prefix_dir(self):
        d = getattr(self, "_prefix_dir", None)
        if d is None:
            from ray_tpu.serve.fleet.prefix_directory import \
                PrefixDirectory
            d = self._prefix_dir = PrefixDirectory()
        return d

    def _h_prefix_publish(self, rec: ClientRec, m: dict) -> None:
        n = self.prefix_dir.publish(
            list(m["keys"]), holder=m["holder"],
            n_tokens=int(m["n_tokens"]),
            generation=int(m.get("generation", 0)),
            block_size=int(m["block_size"]),
            node=m.get("node") or rec.node_hex or "",
            blocks=tuple(m.get("blocks") or ()),
            engine=m.get("engine") or "")
        r = _fr._active
        if r is not None:
            r.note_ingress({"t": time.time(), "kind": "prefix_publish",
                            "holder": m["holder"], "entries": n})
        if "reqid" in m:
            self._reply(rec, m["reqid"], ok=True, published=n)

    def _h_prefix_lookup(self, rec: ClientRec, m: dict) -> None:
        hit = self.prefix_dir.lookup(list(m["keys"]))
        if "reqid" in m:
            self._reply(rec, m["reqid"], ok=True, hit=hit)

    def _h_prefix_invalidate(self, rec: ClientRec, m: dict) -> None:
        """One message, three scopes: ``key`` purges a single stale
        entry, ``holder`` (+ optional ``stale_generation``) drops a
        replica's entries, ``node`` drops a machine's."""
        d = self.prefix_dir
        if m.get("key"):
            n = int(d.purge(m["key"]))
        elif m.get("holder") and m.get("stale_generation") is not None:
            n = d.invalidate_stale(m["holder"],
                                   int(m["stale_generation"]))
        elif m.get("holder"):
            n = d.invalidate_holder(m["holder"])
        elif m.get("node"):
            n = d.invalidate_node(m["node"])
        else:
            n = 0
        if "reqid" in m:
            self._reply(rec, m["reqid"], ok=True, invalidated=n)

    def request_drain(self, node_hex: str,
                      deadline_s: float = 30.0) -> None:
        """Thread-safe drain entry point (the autoscaler's scale-down
        path calls this from its own thread)."""
        self.post(lambda: self._begin_node_drain(node_hex, deadline_s))

    def _h_drain_node(self, rec: ClientRec, m: dict) -> None:
        err = self._begin_node_drain(m["node_id"],
                                     m.get("deadline_s", 30.0))
        if "reqid" in m:
            if err is not None:
                self._reply(rec, m["reqid"], error=err)
            else:
                self._reply(rec, m["reqid"], ok=True, draining=True)

    def _h_drain_done(self, rec: ClientRec, m: dict) -> None:
        """The draining node finished (tasks done or its deadline hit,
        handoff shipped): retire it as a PLANNED removal.  The node_dead
        fan-out still runs — it is the safety net that lets lineage
        reconstruction cover anything the handoff didn't."""
        h = m.get("node_id") or rec.node_hex
        if "reqid" in m:
            self._reply(rec, m["reqid"], ok=True)
        cause = ("decommissioned (drain deadline, explicit fallback)"
                 if m.get("timed_out")
                 else "decommissioned (drain complete)")
        self._node_dead(h, cause)

    # ------------------------------------------------------------ routing

    def _h_cluster_submit(self, rec: ClientRec, m: dict) -> None:
        spec = m["spec"]
        # the forwarding node's projection is fresher than its last
        # heartbeat — fold it in before choosing
        src = self.nodes.get(rec.node_hex)
        if src is not None and "src_available" in m:
            src.available = dict(m["src_available"])
        pg = spec.get("placement_group")
        if pg is not None:
            pgd = self.pgs.get(pg[0])
            if pgd is None or pgd.state != "created":
                if pg[0] in self.pending_pgs:
                    # creation is still queued/committing: hold the task
                    # and re-route once the 2PC lands.  Drop the source's
                    # availability snapshot — it will be stale by then
                    # and would overwrite fresher heartbeat truth.
                    held = {k: v for k, v in m.items()
                            if k != "src_available"}
                    self._pg_waiters.setdefault(pg[0], []).append((rec, held))
                    return
                self._reply(rec, m["reqid"],
                            error="placement group unknown or damaged")
                return
            target = pgd.assignment[pg[1]]
        else:
            target = self._choose_node(
                self._demand(spec), prefer=rec.node_hex,
                arg_ids=tuple(spec.get("arg_ids") or ()))
        if target is None:
            self._reply(rec, m["reqid"],
                        error="Infeasible resource demand "
                              f"{self._demand(spec)} on every node: "
                              f"{[n.total for n in self.nodes.values() if n.alive]}")
            return
        # optimistic accounting: debit the choice so back-to-back submits
        # don't all land on the same node; heartbeats re-sync the truth
        tn = self.nodes.get(target)
        if tn is not None:
            for k, v in self._demand(spec).items():
                avail = tn.available.get(k, 0.0)
                tn.available[k] = max(0.0, avail - v)
                if v > 0 and avail < v:
                    # node saturated: the UNMET portion of this routing
                    # parks in its queue — count it so the next choice
                    # spreads (charging full v would overstate backlog
                    # on a fractionally-short node)
                    tn.routed[k] = tn.routed.get(k, 0.0) + (v - max(
                        0.0, avail))
        if target == rec.node_hex:
            self._reply(rec, m["reqid"], local=True, node=target)
            return
        c = self._node_conn(target)
        if c is None:
            self._reply(rec, m["reqid"], error="chosen node vanished")
            return
        spec = dict(spec)
        spec["_routed"] = True
        if _fr._active is not None:
            # flight recorder: attribute the routing decision itself
            # (same-host monotonic stamps are directly comparable)
            _fr._active.stamp(spec, "head_route")
        self._push(c, {"t": "remote_submit", "spec": spec})
        self._reply(rec, m["reqid"], node=target)

    # -------------------------------------------------------------- actors

    def _h_cluster_create_actor(self, rec: ClientRec, m: dict) -> None:
        spec = m["spec"]
        aid = spec["actor_id"]
        name = spec.get("name") or ""
        ns = spec.get("namespace") or "default"
        if name:
            key = (ns, name)
            prev = self.named_actors.get(key)
            if prev is not None and self.actors[prev].state != "dead":
                if spec.get("get_if_exists"):
                    self._reply(rec, m["reqid"], actor_id=prev,
                                existing=True)
                    return
                self._reply(rec, m["reqid"],
                            error=f"Actor name '{name}' already taken in "
                                  f"namespace '{ns}'")
                return
            self.named_actors[key] = aid
        target = self._choose_actor_node(self._demand(spec),
                                         prefer=rec.node_hex)
        if target is None:
            if name:
                self.named_actors.pop((ns, name), None)
            self._reply(rec, m["reqid"],
                        error=f"Infeasible actor resource demand "
                              f"{self._demand(spec)} on every node")
            return
        ad = ActorDir(actor_id=aid, node_hex=target, state="pending",
                      spec=spec, name=name, namespace=ns,
                      restarts_left=spec.get("max_restarts", 0))
        self.actors[aid] = ad
        self.mark_dirty()
        # optimistic accounting (same as _h_cluster_submit): debit the
        # choice so back-to-back creations don't all pile onto the same
        # node; heartbeats re-sync the truth
        tn = self.nodes.get(target)
        if tn is not None:
            for k, v in self._demand(spec).items():
                tn.available[k] = max(0.0, tn.available.get(k, 0.0) - v)
        c = self._node_conn(target)
        spec = dict(spec)
        spec["_routed"] = True
        self._push(c, {"t": "place_actor", "spec": spec})
        self._reply(rec, m["reqid"], actor_id=aid, node=target)

    def _h_actor_state_report(self, rec: ClientRec, m: dict) -> None:
        ad = self.actors.get(m["actor_id"])
        if ad is None:
            return
        state = m["state"]
        if ad.state == "dead":
            # dead is terminal: a rejoining node must not resurrect the
            # directory entry — tell it to kill its orphan instance
            if state != "dead":
                self._push(rec, {"t": "kill_local_actor",
                                 "actor_id": m["actor_id"],
                                 "no_restart": True})
            return
        # a report from a node the actor no longer lives on (e.g. the old
        # host finally noticing a worker death after a re-place, or a
        # transiently-disconnected node whose actor was re-placed) is
        # stale — the reporting node must retire its duplicate
        if rec.node_hex != ad.node_hex:
            if state != "dead":
                self._push(rec, {"t": "kill_local_actor",
                                 "actor_id": m["actor_id"],
                                 "no_restart": True})
            return
        ad.state = state
        self.mark_dirty()
        if state == "dead":
            ad.death_cause = m.get("death_cause", "")
        self._publish("actor_state", {"actor_id": ad.actor_id.hex(),
                                      "state": state})
        if state in ("alive", "dead"):
            n = self.nodes.get(ad.node_hex)
            for w in ad.watchers:
                c = self._node_conn(w)
                if c is not None:
                    self._push(c, {
                        "t": "actor_at", "actor_id": ad.actor_id,
                        "state": state,
                        "node": ad.node_hex,
                        "address": n.address if n else "",
                        "death_cause": ad.death_cause})
            ad.watchers.clear()

    def _h_locate_actor(self, rec: ClientRec, m: dict) -> None:
        ad = self.actors.get(m["actor_id"])
        if ad is None:
            self._reply(rec, m["reqid"], state="unknown")
            return
        if ad.state == "alive":
            n = self.nodes.get(ad.node_hex)
            self._reply(rec, m["reqid"], state="alive", node=ad.node_hex,
                        address=n.address if n else "")
        elif ad.state == "dead":
            self._reply(rec, m["reqid"], state="dead",
                        death_cause=ad.death_cause)
        else:
            ad.watchers.add(rec.node_hex)
            self._reply(rec, m["reqid"], state=ad.state)

    def _h_kill_actor(self, rec: ClientRec, m: dict) -> None:
        ad = self.actors.get(m["actor_id"])
        if ad is None or ad.state == "dead":
            if "reqid" in m:
                self._reply(rec, m["reqid"], ok=False)
            return
        if m.get("no_restart", True):
            ad.restarts_left = 0
        c = self._node_conn(ad.node_hex)
        if c is not None:
            self._push(c, {"t": "kill_local_actor",
                           "actor_id": m["actor_id"],
                           "no_restart": m.get("no_restart", True)})
        else:
            self._actor_dead(ad, "killed (host node gone)")
        if "reqid" in m:
            self._reply(rec, m["reqid"], ok=True)

    def _h_get_named_actor(self, rec: ClientRec, m: dict) -> None:
        key = (m.get("namespace") or "default", m["name"])
        aid = self.named_actors.get(key)
        ad = self.actors.get(aid) if aid is not None else None
        if ad is None or ad.state == "dead":
            self._reply(rec, m["reqid"], error="not found")
            return
        self._reply(rec, m["reqid"], actor_id=aid, spec_meta={
            "methods": ad.spec.get("methods", []),
            "class_name": ad.spec.get("class_name", "")})

    def _h_list_named_actors(self, rec: ClientRec, m: dict) -> None:
        out = [{"namespace": ns, "name": n}
               for (ns, n), aid in self.named_actors.items()
               if self.actors[aid].state != "dead"
               and (m.get("all_namespaces")
                    or ns == (m.get("namespace") or "default"))]
        self._reply(rec, m["reqid"], actors=out)

    # ------------------------------------------------------ object locations

    def _h_report_locations(self, rec: ClientRec, m: dict) -> None:
        n = self.nodes.get(rec.node_hex)
        for oid in m.get("adds", ()):
            self.object_locs.setdefault(oid, set()).add(rec.node_hex)
            watchers = self.obj_watchers.pop(oid, None)
            if watchers:
                for w in watchers:
                    if w == rec.node_hex:
                        continue
                    c = self._node_conn(w)
                    if c is not None:
                        self._push(c, {"t": "object_at", "object_id": oid,
                                       "node": rec.node_hex,
                                       "address": n.address if n else ""})
        for oid in m.get("removes", ()):
            locs = self.object_locs.get(oid)
            if locs is not None:
                locs.discard(rec.node_hex)
                if not locs:
                    del self.object_locs[oid]

    def _h_locate_object(self, rec: ClientRec, m: dict) -> None:
        self.locate_requests += len(m["object_ids"])
        locs_out = {}
        for oid in m["object_ids"]:
            locs = [h for h in self.object_locs.get(oid, ())
                    if h != rec.node_hex and self.nodes.get(h)
                    and self.nodes[h].alive]
            if locs:
                h = locs[0]
                locs_out[oid] = (h, self.nodes[h].address)
            else:
                self.obj_watchers.setdefault(oid, set()).add(rec.node_hex)
        self._reply(rec, m["reqid"], locs=locs_out)

    def _h_free_objects(self, rec: ClientRec, m: dict) -> None:
        for oid in m["object_ids"]:
            for h in self.object_locs.pop(oid, ()):
                if h == rec.node_hex:
                    continue   # the requesting node deletes locally itself
                c = self._node_conn(h)
                if c is not None:
                    self._push(c, {"t": "delete_object", "object_id": oid})
            self.obj_watchers.pop(oid, None)
        if "reqid" in m:
            self._reply(rec, m["reqid"], ok=True)

    # kv / pubsub / function store: inherited from ClusterStoreMixin
    # (mutations mark the persistence snapshot dirty)

    def _h_kv_put(self, rec, m):
        super()._h_kv_put(rec, m)
        self.mark_dirty()

    def _h_kv_del(self, rec, m):
        super()._h_kv_del(rec, m)
        self.mark_dirty()

    def _h_register_function(self, rec, m):
        super()._h_register_function(rec, m)
        self.mark_dirty()

    # ------------------------------------------------------ placement groups

    def _h_create_pg(self, rec: ClientRec, m: dict) -> None:
        pg_id: bytes = m["pg_id"]
        bundles: list = m["bundles"]
        strategy = m.get("strategy", "PACK")
        if not self._pg_feasible(bundles, strategy):
            # will NEVER fit even on an idle cluster — fail creation
            # synchronously (a pending PG that can't ever place would
            # hang ready() forever)
            self._reply(rec, m["reqid"],
                        error=f"Infeasible placement group: bundles "
                              f"{bundles} exceed cluster capacity "
                              f"{[(n.node_hex[:8], n.total) for n in self.nodes.values() if n.alive]}")
            return
        # Creation is asynchronous (reference:
        # gcs_placement_group_manager.h:222 pending queue + retry):
        # reply immediately, queue, and attempt placement; PlacementGroup
        # .ready() gates on pg_state reporting "created".
        self._reply(rec, m["reqid"], ok=True, state="pending")
        self.pending_pgs[pg_id] = {"bundles": bundles,
                                   "strategy": strategy, "busy": False}
        self._try_place_pending_pgs()

    def _pg_feasible(self, bundles: list, strategy: str) -> bool:
        """Could these bundles fit on an IDLE version of today's cluster?
        Exact: runs the real planner against node totals, so a PG that
        can never place fails creation synchronously instead of pending
        forever."""
        return self._plan_pg(bundles, strategy, idle=True) is not None

    def _try_place_pending_pgs(self) -> None:
        """Attempt 2PC placement of queued PGs (called whenever resources
        may have freed: heartbeats, pg removal, 2PC completion)."""
        for pg_id, info in list(self.pending_pgs.items()):
            if info["busy"]:
                continue
            assignment = self._plan_pg(info["bundles"], info["strategy"])
            if assignment is None:
                continue
            info["busy"] = True
            info["busy_since"] = time.monotonic()
            info["assignment"] = assignment
            # epoch fences late callbacks from an abandoned 2PC attempt
            info["epoch"] = info.get("epoch", 0) + 1
            self._start_pg_2pc(pg_id, info, assignment, info["epoch"])

    def _reset_stuck_pg_2pc(self, pg_id: bytes, info: dict) -> None:
        """A participant died (or never replied) mid-2PC: roll back every
        prepared bundle and requeue — without this the closure-held
        prepare count never reaches zero and the PG pends forever."""
        for j, h in enumerate(info.get("assignment") or []):
            c = self._node_conn(h)
            if c is not None:
                self._push(c, {"t": "pg_rollback", "pg_id": pg_id,
                               "bundle_idx": j})
        # Fence the abandoned attempt immediately: a late all-ok prepare
        # reply must not pass the epoch check and commit against bundles
        # the nodes just rolled back.
        info["epoch"] = info.get("epoch", 0) + 1
        info["busy"] = False
        info.pop("busy_since", None)
        info.pop("assignment", None)

    def _start_pg_2pc(self, pg_id: bytes, info: dict,
                      assignment: list, epoch: int) -> None:
        # 2PC (reference: gcs_placement_group_scheduler.h:104 prepare all,
        # then commit all; rollback prepared on any failure)
        bundles, strategy = info["bundles"], info["strategy"]
        state = {"pending": len(bundles), "failed": False}

        def rollback_all() -> None:
            for j, h in enumerate(assignment):
                c = self._node_conn(h)
                if c is not None:
                    self._push(c, {"t": "pg_rollback", "pg_id": pg_id,
                                   "bundle_idx": j})

        def prepared(i: int, reply: dict) -> None:
            state["pending"] -= 1
            if reply.get("error") or not reply.get("ok"):
                state["failed"] = True
            if state["pending"] > 0:
                return
            cur = self.pending_pgs.get(pg_id)
            if cur is not None and cur.get("epoch") != epoch:
                # this attempt was abandoned (participant died, bundles
                # already rolled back); never commit on its late replies
                return
            if state["failed"]:
                rollback_all()
                # a node raced out of resources — back to the queue
                if cur is not None:
                    cur["busy"] = False
                    cur.pop("busy_since", None)
                    cur.pop("assignment", None)
                return
            if cur is None:
                # removed while committing: the reservations are still
                # only PREPARED — roll them back (pg_remove_local frees
                # committed bundles only and would leak the debit)
                rollback_all()
                return
            for j, h in enumerate(assignment):
                c = self._node_conn(h)
                if c is not None:
                    self._push(c, {"t": "pg_commit", "pg_id": pg_id,
                                   "bundle_idx": j})
            del self.pending_pgs[pg_id]
            self.pgs[pg_id] = PGDir(pg_id=pg_id, bundles=bundles,
                                    strategy=strategy,
                                    assignment=assignment)
            self.mark_dirty()
            for wrec, wm in self._pg_waiters.pop(pg_id, []):
                self.post(lambda r=wrec, mm=wm: self._h_cluster_submit(r, mm))

        for i, (b, h) in enumerate(zip(bundles, assignment)):
            c = self._node_conn(h)
            if c is None:
                self.post(lambda i=i: prepared(i, {"error": "node gone"}))
                continue
            self._rpc(c, {"t": "pg_prepare", "pg_id": pg_id,
                          "bundle_idx": i, "bundle": b},
                      lambda reply, i=i: prepared(i, reply))

    def _h_pg_state(self, rec: ClientRec, m: dict) -> None:
        pg_id = m["pg_id"]
        if pg_id in self.pgs:
            st = self.pgs[pg_id].state  # "created"
        elif pg_id in self.pending_pgs:
            st = "pending"
        else:
            st = "removed"
        self._reply(rec, m["reqid"], ok=True, state=st)

    def _plan_pg(self, bundles: list, strategy: str,
                 idle: bool = False) -> Optional[list]:
        """Bundle→node assignment against current availability, or — with
        ``idle=True`` — against an idle cluster's totals (the exact
        feasibility oracle: a PG is worth queueing iff a plan exists on
        the idle cluster)."""
        alive = [n for n in self.nodes.values()
                 if n.alive and not n.draining]
        cap = (lambda n: n.total) if idle else (lambda n: n.available)
        if not alive:
            return None
        if strategy in ("PACK", "STRICT_PACK"):
            total = _bundle_total(bundles)
            for n in sorted(alive, key=lambda n: -sum(cap(n).values())):
                if _covers(cap(n), total):
                    return [n.node_hex] * len(bundles)
            if strategy == "STRICT_PACK":
                return None
            strategy = "SPREAD"   # PACK falls back to spreading
        # SPREAD / STRICT_SPREAD: round-robin with per-node running totals
        budget = {n.node_hex: dict(cap(n)) for n in alive}
        order = sorted(alive, key=lambda n: -sum(cap(n).values()))
        assignment: list[Optional[str]] = []
        used_nodes: set[str] = set()
        for b in bundles:
            placed = None
            for n in order:
                if strategy == "STRICT_SPREAD" and n.node_hex in used_nodes:
                    continue
                bud = budget[n.node_hex]
                if _covers(bud, b):
                    for k, v in b.items():
                        bud[k] = bud.get(k, 0.0) - v
                    placed = n.node_hex
                    used_nodes.add(n.node_hex)
                    break
            if placed is None:
                return None
            assignment.append(placed)
            # rotate so SPREAD actually spreads
            order = order[1:] + order[:1]
        return assignment

    def _h_remove_pg(self, rec: ClientRec, m: dict) -> None:
        pgd = self.pgs.pop(m["pg_id"], None)
        self.pending_pgs.pop(m["pg_id"], None)
        for wrec, wm in self._pg_waiters.pop(m["pg_id"], []):
            self._reply(wrec, wm["reqid"],
                        error="placement group removed before scheduling")
        self.mark_dirty()
        if pgd is not None:
            for i, h in enumerate(pgd.assignment):
                c = self._node_conn(h)
                if c is not None:
                    self._push(c, {"t": "pg_remove_local",
                                   "pg_id": m["pg_id"], "bundle_idx": i})
            self._try_place_pending_pgs()
        if "reqid" in m:
            self._reply(rec, m["reqid"], ok=True)

    # --------------------------------------------------------------- state

    def nodes_snapshot(self) -> list[dict]:
        """Membership view safe to call from ANY thread (the autoscaler
        polls it): retries over list copies while the loop mutates."""
        for attempt in range(4):
            try:
                return [{"node_id": h, "address": n.address,
                         "resources": dict(n.total),
                         "available": dict(n.available),
                         "queued": dict(n.queued),
                         "labels": dict(n.labels), "alive": n.alive,
                         "draining": n.draining}
                        for h, n in list(self.nodes.items())]
            except RuntimeError:   # dict changed size during iteration
                if attempt == 3:
                    raise
        return []

    def _h_state(self, rec: ClientRec, m: dict) -> None:
        what = m["what"]
        if what == "nodes":
            out = self.nodes_snapshot()
        elif what == "actors":
            out = [{"actor_id": ad.actor_id.hex(), "state": ad.state,
                    "name": ad.name, "namespace": ad.namespace,
                    "node_id": ad.node_hex,
                    "class_name": ad.spec.get("class_name", "")}
                   for ad in self.actors.values()]
        elif what == "resources":
            total: dict[str, float] = {}
            avail: dict[str, float] = {}
            for n in self.nodes.values():
                if not n.alive:
                    continue
                for k, v in n.total.items():
                    total[k] = total.get(k, 0.0) + v
                for k, v in n.available.items():
                    avail[k] = avail.get(k, 0.0) + v
            out = {"total": total, "available": avail}
        else:
            out = []
        self._reply(rec, m["reqid"], data=out)

    def _h_ping(self, rec: ClientRec, m: dict) -> None:
        self._reply(rec, m["reqid"], ok=True, time=time.time())


def main() -> None:
    import argparse
    import uuid
    parser = argparse.ArgumentParser(description="ray_tpu head service")
    parser.add_argument("--port", type=int, default=6380)
    parser.add_argument("--session", default=None)
    parser.add_argument("--address-file", default=None,
                        help="write the bound address here once "
                             "listening (cluster-launcher handshake)")
    args = parser.parse_args()
    svc = HeadService(RayTpuConfig(), args.session or uuid.uuid4().hex,
                      port=args.port)
    print(f"ray_tpu head service listening on {svc.address}", flush=True)
    if args.address_file:
        tmp = args.address_file + ".tmp"
        with open(tmp, "w") as f:
            f.write(svc.address)
        import os as _os
        _os.replace(tmp, args.address_file)
    try:
        svc.run()
    except KeyboardInterrupt:
        svc.stop()


if __name__ == "__main__":
    main()
