"""Serialization: cloudpickle + pickle-5 out-of-band buffers.

Capability parity with the reference's SerializationContext
(reference: python/ray/_private/serialization.py:92,438,358) — cloudpickle
for arbitrary Python, protocol-5 buffer callbacks so large numpy / jax host
arrays are carried as raw buffers (zero-copy from the shared-memory object
store on read), and custom reducers for ObjectRef / ActorHandle so they can
travel inside task arguments with ownership information intact.
"""

from __future__ import annotations

import io
import pickle
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

import cloudpickle

from ray_tpu.core import device_objects


@dataclass
class SerializedObject:
    """In-band pickle bytes + out-of-band raw buffers.

    Wire layout (for the object store):
      [8B inband length][inband][8B nbufs][(8B len, payload) ...]
    """

    inband: bytes
    buffers: list = field(default_factory=list)  # buffer-protocol objects
    # ObjectRefs found inside the serialized value (nested refs). The owner
    # must keep these alive while the outer object lives (reference:
    # reference_count.h borrower tracking — scoped down here).
    nested_refs: list = field(default_factory=list)

    def total_bytes(self) -> int:
        return (16 + len(self.inband)
                + sum(8 + len(memoryview(b).cast("B")) for b in self.buffers))

    def to_bytes(self) -> bytes:
        if not self.buffers:
            # hot path: small inline objects (task args/returns)
            return (len(self.inband).to_bytes(8, "little") + self.inband
                    + _ZERO8)
        out = io.BytesIO()
        self.write_to(out)
        return out.getvalue()

    def write_to(self, f) -> None:
        f.write(len(self.inband).to_bytes(8, "little"))
        f.write(self.inband)
        f.write(len(self.buffers).to_bytes(8, "little"))
        for b in self.buffers:
            mv = memoryview(b).cast("B")
            f.write(len(mv).to_bytes(8, "little"))
            f.write(mv)

    @classmethod
    def from_buffer(cls, data) -> "SerializedObject":
        """Parse from a buffer, keeping zero-copy views into `data`."""
        mv = memoryview(data)
        off = 0
        n = int.from_bytes(mv[off:off + 8], "little"); off += 8
        inband = bytes(mv[off:off + n]); off += n
        nbuf = int.from_bytes(mv[off:off + 8], "little"); off += 8
        bufs = []
        for _ in range(nbuf):
            ln = int.from_bytes(mv[off:off + 8], "little"); off += 8
            bufs.append(mv[off:off + ln]); off += ln
        return cls(inband=inband, buffers=bufs)


_ZERO8 = (0).to_bytes(8, "little")


class _ContextPickler(cloudpickle.Pickler):
    """Module-level pickler class (defining it inside serialize() cost a
    __build_class__ per call — measured on the worker hot path)."""

    def __init__(self, f, *, buffer_callback, custom, nested_refs,
                 device_capture, jax_types):
        super().__init__(f, protocol=5, buffer_callback=buffer_callback)
        self._custom = custom
        self._nested_refs = nested_refs
        self._device_capture = device_capture
        self._jax_types = jax_types

    def reducer_override(self, obj):  # noqa: N802
        from ray_tpu.core.object_ref import ObjectRef
        if isinstance(obj, ObjectRef):
            self._nested_refs.append(obj)
            return (_deserialize_object_ref, (obj.binary(), obj.owner))
        jax_types = self._jax_types
        if jax_types is not None and isinstance(obj, jax_types[0]) \
                and not isinstance(obj, jax_types[1]):
            self._device_capture.append(obj)
            return (device_objects._device_leaf,
                    (len(self._device_capture) - 1,))
        for klass, (ser, de) in self._custom.items():
            if isinstance(obj, klass):
                return (_apply_custom, (de, ser(obj)))
        # delegate to cloudpickle's own reducer_override — it is
        # what pickles local functions/classes by value; returning
        # NotImplemented here would skip it and fall back to
        # pickle's by-reference lookup, which fails for closures
        return super().reducer_override(obj)


class SerializationContext:
    """Per-process serializer with pluggable custom reducers."""

    def __init__(self):
        # type -> (serializer, deserializer); applied via a cloudpickle
        # reducer_override-style dispatch table.
        self._custom: dict[type, tuple[Callable, Callable]] = {}
        self._out_of_band_threshold = 1024  # buffers below this stay in-band

    def register_custom_serializer(self, cls: type,
                                   serializer: Callable,
                                   deserializer: Callable) -> None:
        self._custom[cls] = (serializer, deserializer)

    # -- serialize ---------------------------------------------------------

    def serialize(self, value: Any,
                  device_capture: Optional[list] = None) -> SerializedObject:
        """With ``device_capture`` (a list), jax.Array leaves are NOT
        materialized to host bytes: each is appended to the list and the
        pickle stream carries a placeholder (device-resident put path —
        see core/device_objects.py; the reference's plasma cannot do
        this, store.h:55 is host-only)."""
        buffers: list = []
        nested_refs: list = []
        threshold = self._out_of_band_threshold
        jax_types = (device_objects.try_jax_array_types()
                     if device_capture is not None else None)

        def buffer_callback(buf: pickle.PickleBuffer):
            raw = buf.raw()
            if len(raw) < threshold:
                return True  # serialize in-band
            buffers.append(raw)
            return False

        f = io.BytesIO()
        p = _ContextPickler(f, buffer_callback=buffer_callback,
                            custom=self._custom, nested_refs=nested_refs,
                            device_capture=device_capture,
                            jax_types=jax_types)
        p.dump(value)
        return SerializedObject(inband=f.getvalue(), buffers=buffers,
                                nested_refs=nested_refs)

    # -- deserialize -------------------------------------------------------

    def deserialize(self, so: SerializedObject) -> Any:
        return pickle.loads(so.inband, buffers=so.buffers)

    def deserialize_with_leaves(self, so: SerializedObject,
                                leaves: list) -> Any:
        """Deserialize a device-resident descriptor, splicing the process
        -local jax.Array leaves back in (fresh container, shared immutable
        leaves — zero copies)."""
        device_objects.set_splice_leaves(leaves)
        try:
            return pickle.loads(so.inband, buffers=so.buffers)
        finally:
            device_objects.set_splice_leaves(None)


def _apply_custom(deserializer, payload):
    return deserializer(payload)


def _deserialize_object_ref(binary: bytes, owner):
    from ray_tpu.core.object_ref import ObjectRef
    from ray_tpu.core.ids import ObjectID
    return ObjectRef(ObjectID(binary), owner=owner)


_context: SerializationContext | None = None


def get_context() -> SerializationContext:
    global _context
    if _context is None:
        _context = SerializationContext()
    return _context
