"""Pluggable object-spill storage backends.

Reference capability: python/ray/_private/external_storage.py —
``FileSystemStorage`` (:246) and the smart_open-backed cloud URI
backend (:446).  The node's object store spills through ONE interface;
``object_spilling_uri`` selects the target:

    (unset)            -> local disk under the session's spill dir
    file:///some/dir   -> local disk at that path
    s3://bucket/prefix -> S3 via boto3 (gated: a clear error at CONFIG
                          time when boto3 is absent, not a mid-spill
                          crash)

Keys are content-addressed by object id hex, so retried spills are
idempotent on every backend.
"""

from __future__ import annotations

import os
from typing import Optional
from urllib.parse import urlparse


class SpillBackend:
    scheme = "?"

    def put(self, key: str, data) -> str:
        """Store bytes under key; returns the locator to restore with."""
        raise NotImplementedError

    def get(self, locator: str) -> bytes:
        raise NotImplementedError

    def delete(self, locator: str) -> None:
        raise NotImplementedError


class FileSpillBackend(SpillBackend):
    scheme = "file"

    def __init__(self, directory: str):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)

    def put(self, key: str, data) -> str:
        path = os.path.join(self.directory, key)
        with open(path, "wb") as f:
            f.write(data)
        return path

    def get(self, locator: str) -> bytes:
        with open(locator, "rb") as f:
            return f.read()

    def delete(self, locator: str) -> None:
        try:
            os.unlink(locator)
        except FileNotFoundError:
            pass


class S3SpillBackend(SpillBackend):
    """Cloud spilling over boto3 (reference: external_storage.py:446
    smart_open path).  The client is injectable for tests."""

    scheme = "s3"

    def __init__(self, uri: str, client=None):
        parsed = urlparse(uri)
        if not parsed.netloc:
            raise ValueError(f"s3 spill uri needs a bucket: {uri!r}")
        self.bucket = parsed.netloc
        self.prefix = parsed.path.strip("/")
        if client is None:
            try:
                import boto3
            except ImportError as e:
                raise RuntimeError(
                    "object_spilling_uri is s3:// but boto3 is not "
                    "installed; install boto3 or spill to file://") from e
            client = boto3.client("s3")
        self._client = client

    def _key(self, key: str) -> str:
        return f"{self.prefix}/{key}" if self.prefix else key

    def put(self, key: str, data) -> str:
        k = self._key(key)
        self._client.put_object(Bucket=self.bucket, Key=k,
                                Body=bytes(data))
        return f"s3://{self.bucket}/{k}"

    def get(self, locator: str) -> bytes:
        parsed = urlparse(locator)
        obj = self._client.get_object(Bucket=parsed.netloc,
                                      Key=parsed.path.lstrip("/"))
        return obj["Body"].read()

    def delete(self, locator: str) -> None:
        parsed = urlparse(locator)
        self._client.delete_object(Bucket=parsed.netloc,
                                   Key=parsed.path.lstrip("/"))


def make_spill_backend(uri: str, default_dir: str,
                       client=None) -> SpillBackend:
    """uri: '' (session default dir), file://..., or s3://...  Raises at
    construction on unknown schemes / missing cloud deps — spill-time
    failures would silently poison evictions instead."""
    if not uri:
        return FileSpillBackend(default_dir)
    parsed = urlparse(uri)
    if parsed.scheme in ("", "file"):
        return FileSpillBackend(parsed.path or uri)
    if parsed.scheme == "s3":
        return S3SpillBackend(uri, client=client)
    raise ValueError(
        f"unsupported object_spilling_uri scheme {parsed.scheme!r} "
        "(supported: file://, s3://)")
