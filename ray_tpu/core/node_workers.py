"""Worker-pool half of the node service (split out of core/node.py).

Worker process lifecycle for one node: demand-driven pool growth with
capped startup concurrency, the fork-server fast path (core/prefork.py),
containerized worker launches (runtime_env.container), liveness auditing
moved off the per-event path, OOM victim selection, and the worker
observability handlers (logs / profiling / stack dumps).  Reference:
src/ray/raylet/worker_pool.h, memory_monitor.h.

``NodeWorkersMixin`` carries no state of its own — every attribute is
initialized by ``NodeService.__init__`` (core/node.py), which composes
this mixin with the transfer and scheduling halves.  Cross-mixin calls
go through ``self``; ``ray_tpu lint`` (analysis/) resolves them through
the composed class, so the loop-blocking and hotpath invariants keep
gating this module after the split.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from typing import Optional

from ray_tpu.core import fault_injection as _fi


# ---------------------------------------------------------------------------
# fork-server worker handle


class _ForkedProc:
    """Popen-shaped handle for a worker forked by the prefork template
    (core/prefork.py).  The template reaps exits, so liveness is probed
    with signal 0 rather than waitpid."""

    def __init__(self, pid: int):
        self.pid = pid
        self._rc: Optional[int] = None

    def poll(self) -> Optional[int]:
        if self._rc is None:
            try:
                os.kill(self.pid, 0)
            except (ProcessLookupError, PermissionError):
                self._rc = 0
        return self._rc

    def wait(self, timeout: Optional[float] = None) -> int:
        deadline = None if timeout is None else time.time() + timeout
        while self.poll() is None:
            if deadline is not None and time.time() > deadline:
                raise subprocess.TimeoutExpired("forked-worker", timeout)
            time.sleep(0.02)
        return self._rc

    def _signal(self, sig: int) -> None:
        try:
            os.kill(self.pid, sig)
        except (ProcessLookupError, PermissionError):
            pass

    def terminate(self) -> None:
        self._signal(signal.SIGTERM)

    def kill(self) -> None:
        self._signal(signal.SIGKILL)


class _PendingLaunch:
    """Popen-shaped placeholder guarding a container launch that has
    been SCHEDULED but not yet exec'd (e.g. chaos slow-spawn).  poll()
    reads in-flight until the register window expires, then done —
    re-arming retries for a launch that silently died."""

    def __init__(self, ttl_s: float):
        self._deadline = time.monotonic() + ttl_s
        self.pid = 0

    def poll(self) -> Optional[int]:
        return None if time.monotonic() < self._deadline else 0

class NodeWorkersMixin:
    """Worker pool / prefork / liveness (mixed into NodeService)."""

    def _memory_check(self) -> None:
        """OOM protection: when node memory crosses the threshold, kill
        one running worker chosen by the group-by-owner policy; the task
        retries or fails with OutOfMemoryError (reference:
        memory_monitor.h:52, worker_killing_policy_group_by_owner.h:85)."""
        mm = self.memory_monitor
        if mm is None or not mm.due():
            return
        over = mm.over_threshold()
        if over is None:
            return
        used, total = over
        from ray_tpu.core.memory_monitor import pick_victim
        cands = []
        for rec in self.clients.values():
            if (rec.kind != "worker" or rec.dedicated_actor is not None
                    or rec.state != "busy" or rec.current_task is None
                    or not rec.pid):
                continue
            tr = self.tasks.get(rec.current_task)
            if tr is not None and tr.state == "running":
                cands.append((rec, tr))
        victim = pick_victim(cands)
        if victim is None:
            return
        rec, tr = victim
        detail = (f"task used node memory past the threshold "
                  f"({used / (1 << 20):.0f}MiB / {total / (1 << 20):.0f}"
                  f"MiB >= {mm.threshold:.2f}); worker pid={rec.pid} "
                  f"killed to protect the node")
        try:
            os.kill(rec.pid, signal.SIGKILL)
        except OSError:
            return   # already gone: no kill happened, record nothing
        self._oom_kills[rec.current_task] = detail
        self.oom_kill_count += 1
        self._record_event(tr.spec, "OOM_KILLED", worker=rec.conn_id)
        sys.stderr.write(f"[node] OOM: killing worker pid={rec.pid} "
                         f"(task {rec.current_task.hex()[:12]}, "
                         f"{used}/{total} bytes)\n")

    def _maybe_spawn_container_worker(self, container: dict) -> None:
        """Launch a worker exec'd inside the requested image
        (runtime_env.container — ROADMAP 5a).  One launch in flight per
        image: container cold-starts are seconds, and every _schedule
        pass would otherwise stampede podman.  A launcher that dies
        before its worker registers re-arms on the next pass."""
        image = container["image"]
        prev = self._container_spawning.get(image)
        if prev is not None and prev.poll() is None:
            return
        # arm the guard BEFORE the spawn call: a chaos-delayed spawn
        # returns without a Popen, and every _schedule pass until the
        # delay elapsed would otherwise queue another launch.  The
        # placeholder expires after the register window so a silently
        # failed launch re-arms; _do_spawn_worker overwrites it with
        # the real proc.
        self._container_spawning[image] = _PendingLaunch(
            self.config.worker_register_timeout_s)
        try:
            self._spawn_worker_proc(container=dict(container))
        except Exception as e:
            self._container_spawning.pop(image, None)
            # no container runtime / unlaunchable image: a spec that can
            # never dispatch must not wedge the queue head forever —
            # fail the demand with the real problem named
            self._fail_container_demand(
                image, f"containerized worker for image '{image}' "
                       f"cannot launch: {e}")

    def _fail_container_demand(self, image: str, error: str) -> None:
        for q in (self.runnable_cpu, self.runnable_tpu,
                  self.runnable_zero):
            doomed = [s for s in q
                      if (((s.get("runtime_env") or {}).get("container")
                           or {}).get("image")) == image]
            for spec in doomed:
                q.remove(spec)
                # mirror _queue_pop's aggregate accounting
                if spec.get("placement_group"):
                    self._queued_pg = max(0, self._queued_pg - 1)
                else:
                    for k, v in self._demand(spec).items():
                        self._queued_demand[k] = \
                            self._queued_demand.get(k, 0.0) - v
                self._fail_task(spec, error)
        if (not self.runnable_cpu and not self.runnable_tpu
                and not self.runnable_zero):
            self._queued_demand.clear()
            self._queued_pg = 0
        for ar in list(self.actors.values()):
            if (ar.state in ("pending", "restarting")
                    and ar.conn_id is None
                    and (((ar.spec.get("runtime_env") or {})
                          .get("container") or {}).get("image")) == image):
                self._mark_actor_dead(ar, error)

    def _audit_worker_pool(self) -> None:
        """Self-heal the in-flight spawn counter against crashed spawns
        and prune long-dead procs.  Runs on the periodic tick, NOT per
        event: each liveness probe is a waitpid/kill syscall per proc,
        and at thousands of events/s this scan alone was ~45% of the
        node loop (sampled; the 5 ms throttle still admitted it every
        few events)."""
        alive = [p for p in self._worker_procs if p.poll() is None]
        if len(self._worker_procs) - len(alive) > 32:
            self._worker_procs = alive
        registered = sum(1 for c in self.clients.values()
                         if c.kind == "worker" and not c.tpu)
        # on_tick runs _schedule() right after this, so just correct
        # the counter here
        self._spawning = max(0, len(alive) - registered)

    def _maybe_spawn_worker(self, tpu: bool = False) -> None:
        if tpu:
            return  # TPU executors are registered by the driver, not spawned
        # Throttle: this runs on EVERY submit/completion event.  Pool
        # sizing only needs to be right within a few ms; the periodic
        # tick re-audits (and self-heals `_spawning`) regardless.
        now = time.monotonic()
        if now - getattr(self, "_last_spawn_eval", 0.0) < 0.005:
            # re-arm so a lone skipped event still gets its evaluation
            # promptly instead of waiting for the next tick
            if not getattr(self, "_spawn_eval_armed", False):
                self._spawn_eval_armed = True

                def rearm():
                    self._spawn_eval_armed = False
                    self._schedule()
                self.post_later(0.006, rearm)
            return
        self._last_spawn_eval = now
        registered = sum(1 for c in self.clients.values()
                         if c.kind == "worker" and not c.tpu)
        # Demand-driven pool growth (reference: worker_pool.h capped startup
        # concurrency :192): one worker per waiting task/actor, capped.
        n_actors_waiting = sum(
            1 for a in self.actors.values()
            if a.state in ("pending", "restarting") and a.conn_id is None
            and not a.spec.get("num_tpus"))
        # containerized workers don't count as spare capacity here: they
        # can only take matching-image tasks, so an idle one must not
        # mask the need for a host worker
        idle = sum(1 for c in self.clients.values()
                   if c.kind == "worker" and not c.tpu and c.state == "idle"
                   and c.dedicated_actor is None and not c.container_image)
        # Tasks can only run while CPU is available, so a pool larger than
        # the free CPUs is waste; placement-group tasks draw on their
        # bundle reservation, zero-cpu tasks (e.g. PlacementGroup.ready()
        # pollers) run regardless of CPU pressure, and actors hold no CPU
        # — all three always need a process.  Concurrent startups are
        # capped (reference: worker_pool.h maximum_startup_concurrency
        # :192,717).
        n_pg = min(self._queued_pg, len(self.runnable_cpu))
        n_zero = len(self.runnable_zero)
        cpu_demand = min(len(self.runnable_cpu) - n_pg,
                         max(0, int(self.available.get("CPU", 0.0))))
        demand = cpu_demand + n_pg + n_zero + n_actors_waiting
        # cold spawns compete for CPU, so their concurrency is capped at
        # roughly core count; forks from the warm template cost ~ms and
        # can ramp much harder (reference: worker_pool.h:192,717)
        if self._prefork_conn is not None or self._prefork_ready():
            max_concurrent_startup = 16
        else:
            max_concurrent_startup = max(2, os.cpu_count() or 1)
        want = min(demand - idle - self._spawning,
                   self.config.max_workers - registered - self._spawning,
                   max_concurrent_startup - self._spawning)
        for _ in range(max(0, want)):
            self._spawning += 1
            self._spawn_worker_proc()

    def _spawn_worker_proc(self, container: Optional[dict] = None) -> None:
        if _fi._active is not None:
            # chaos plane: slow-spawn (the fork lands late) or a spawn
            # that silently dies; _audit_worker_pool self-heals the
            # in-flight counter either way, exactly as for a real
            # crashed spawn
            v = _fi._active.spawn_verdict(self)
            if v == "fail":
                return
            if type(v) is tuple:
                self.post_later(
                    v[1], lambda: self._do_spawn_worker(container))
                return
        self._do_spawn_worker(container)

    def _do_spawn_worker(self, container: Optional[dict] = None) -> None:
        logdir = os.path.join(self.session_dir, "logs")
        # monotone counter, NOT len(): pruning dead procs shrinks the
        # list and len() would hand a live worker's log index to a new
        # one (interleaved logs, wrong dashboard attribution)
        self._worker_seq = getattr(self, "_worker_seq", 0) + 1
        idx = self._worker_seq
        outp = os.path.join(logdir, f"worker-{idx}.out")
        errp = os.path.join(logdir, f"worker-{idx}.err")
        # containerized workers (runtime_env.container) always bypass
        # the prefork template: the child must be exec'd INSIDE the
        # image, and a fork of this host's pre-imported interpreter is
        # by definition not that (reference:
        # _private/runtime_env/container.py worker command wrapping)
        proc = None if container else self._fork_worker(outp, errp)
        if proc is None:
            env = self._worker_env()
            worker_cmd = [sys.executable, "-m", "ray_tpu.core.worker",
                          "--address", self.worker_address,
                          "--session", self.session]
            if container:
                from ray_tpu.runtime_env import container_command
                worker_cmd = container_command(container, worker_cmd,
                                               self.session_dir)
            out = open(outp, "ab", buffering=0)
            err = open(errp, "ab", buffering=0)
            proc = subprocess.Popen(
                worker_cmd,
                env=env, stdout=out, stderr=err, start_new_session=True)
            if container:
                self._container_spawning[container["image"]] = proc
        self._worker_procs.append(proc)
        # stack dumps / the dashboard log view need pid -> log mapping
        self._worker_log_by_pid[proc.pid] = (outp, errp)

    def _worker_env(self) -> dict:
        env = dict(os.environ)
        # Workers must not steal the TPU from the driver: force CPU jax —
        # and skip ambient TPU-plugin registration entirely (site hooks
        # keyed on this env cost ~2.4 s of pure import time per process
        # and risk contending for the chip the driver owns).
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env.setdefault("XLA_FLAGS", "")
        env["RAY_TPU_SESSION"] = self.session
        # Propagate the driver's import path so functions/classes pickled
        # by reference (module-level defs in driver-side scripts) resolve
        # in workers — the minimal slice of the reference's runtime-env
        # working_dir propagation (reference:
        # python/ray/_private/runtime_env/working_dir.py capability).
        env["PYTHONPATH"] = os.pathsep.join(
            [p for p in sys.path if p] +
            [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p])
        return env

    # -- fork-server template (core/prefork.py)

    def _start_prefork_template(self) -> None:
        """Spawn the pre-imported worker template.  Non-blocking: the
        template warms up (~0.5 s) while the node finishes starting;
        until its socket accepts, spawns fall back to cold Popen."""
        logdir = os.path.join(self.session_dir, "logs")
        os.makedirs(logdir, exist_ok=True)
        self._prefork_path = os.path.join(self.session_dir, "prefork.sock")
        out = open(os.path.join(logdir, "prefork.out"), "ab", buffering=0)
        err = open(os.path.join(logdir, "prefork.err"), "ab", buffering=0)
        self._prefork_proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.core.prefork",
             "--socket", self._prefork_path],
            env=self._worker_env(), stdout=out, stderr=err,
            start_new_session=True)

    def _prefork_ready(self) -> bool:
        if self._prefork_conn is not None:
            return True
        if (self._prefork_proc is None
                or self._prefork_proc.poll() is not None):
            return False
        import socket as _socket
        s = _socket.socket(_socket.AF_UNIX)
        s.settimeout(0.05)
        try:
            s.connect(self._prefork_path)
        except OSError:
            s.close()
            return False
        # short bound: this socket is read on the EVENT-LOOP thread, so
        # a wedged template must not stall scheduling for long — on
        # timeout we drop the template and cold-spawn instead
        s.settimeout(2.0)
        self._prefork_conn = s
        self._prefork_buf = b""
        return True

    def _fork_worker(self, outp: str, errp: str):
        """Request a forked worker from the template; None -> caller
        should cold-spawn instead."""
        if not self.config.prefork_workers or not self._prefork_ready():
            return None
        import json as _json
        try:
            req = {"address": self.worker_address,
                   "stdout": outp, "stderr": errp,
                   "env": {"RAY_TPU_SESSION": self.session}}
            self._prefork_conn.sendall(_json.dumps(req).encode() + b"\n")
            while b"\n" not in self._prefork_buf:
                chunk = self._prefork_conn.recv(4096)
                if not chunk:
                    raise OSError("prefork template closed")
                self._prefork_buf += chunk
            line, self._prefork_buf = self._prefork_buf.split(b"\n", 1)
            return _ForkedProc(_json.loads(line)["pid"])
        except (OSError, ValueError):
            try:
                self._prefork_conn.close()
            except OSError:
                pass
            self._prefork_conn = None
            return None

    def _h_worker_logs(self, rec, m):
        """List this node's worker log files, or tail one (reference:
        the dashboard's per-worker log viewer, dashboard/modules/log/)."""
        logdir = os.path.join(self.session_dir, "logs")
        name = m.get("name")
        if not name:
            files = []
            try:
                for f in sorted(os.listdir(logdir)):
                    full = os.path.join(logdir, f)
                    files.append({"name": f,
                                  "size": os.path.getsize(full)})
            except OSError:
                pass
            self._reply(rec, m["reqid"], files=files)
            return
        # basename only — no path escape out of the log dir
        path = os.path.join(logdir, os.path.basename(str(name)))
        nbytes = int(m.get("nbytes", 64 * 1024))
        try:
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - nbytes))
                data = f.read()
            self._reply(rec, m["reqid"],
                        data=data.decode("utf-8", "replace"), size=size)
        except OSError as e:
            self._reply(rec, m["reqid"], error=str(e))

    def _h_profile_worker(self, rec, m):
        """Sampling-profile a live worker (reference: dashboard
        profile_manager.py py-spy wrapper): route the request to the
        worker's executor, which samples its own interpreter and pushes
        folded stacks back."""
        pid = int(m["pid"])
        target = next((c for c in self.clients.values()
                       if c.kind in ("worker", "tpu_executor")
                       and c.pid == pid), None)
        if target is None:
            self._reply(rec, m["reqid"],
                        error=f"no live worker with pid {pid}")
            return
        self._profile_seq = getattr(self, "_profile_seq", 0) + 1
        prof_id = self._profile_seq
        self._profile_pending = getattr(self, "_profile_pending", {})
        self._profile_pending[prof_id] = (rec.conn_id, m["reqid"])
        duration = float(m.get("duration", 2.0))
        self._push(target, {"t": "profile", "prof_id": prof_id,
                            "duration": duration,
                            "hz": float(m.get("hz", 99.0))})

        def expire():
            pend = self._profile_pending.pop(prof_id, None)
            if pend is not None:
                w = self.clients.get(pend[0])
                if w is not None:
                    self._reply(w, pend[1],
                                error="profile timed out (worker busy "
                                      "outside its message loop?)")
        self.post_later(duration + 30.0, expire)

    def _h_profile_result(self, rec, m):
        pend = getattr(self, "_profile_pending", {}).pop(
            m.get("prof_id"), None)
        if pend is None:
            return
        w = self.clients.get(pend[0])
        if w is None:
            return
        if m.get("error"):
            self._reply(w, pend[1], error=m["error"])
        else:
            self._reply(w, pend[1], folded=m.get("folded", ""))

    def _h_stack_dump(self, rec, m):
        """Dump a live worker's thread stacks (reference: `ray stack`,
        scripts.py:1767 / profile_manager.py): SIGUSR1 triggers the
        worker's faulthandler into its .err log; reply with the fresh
        tail."""
        pid = int(m["pid"])
        target = next((c for c in self.clients.values()
                       if c.kind == "worker" and c.pid == pid), None)
        logs = self._worker_log_by_pid.get(pid)
        if target is None or logs is None:
            self._reply(rec, m["reqid"],
                        error=f"no live spawned worker with pid {pid}")
            return
        err_path = logs[1]
        try:
            start = os.path.getsize(err_path)
        except OSError:
            start = 0
        try:
            os.kill(pid, signal.SIGUSR1)
        except OSError as e:
            self._reply(rec, m["reqid"], error=str(e))
            return

        def collect(attempt: int = 0, last: int = -1):
            # The dump is async — poll THIS worker's own .err for growth
            # (other workers' stderr chatter must not be misattributed),
            # then wait until it QUIESCES: faulthandler writes the
            # threads one at a time with the CURRENT thread (the one
            # executing the task) LAST, so replying on first growth
            # captured a partial dump missing exactly the frames the
            # caller wants (`ray stack` showed only the recv thread).
            try:
                size = os.path.getsize(err_path)
            except OSError:
                size = start
            if attempt < 40 and (size <= start or size != last):
                self.post_later(0.05, lambda: collect(attempt + 1, size))
                return
            if size <= start:
                self._reply(rec, m["reqid"],
                            error="worker produced no stack dump "
                                  "(faulthandler unavailable?)")
                return
            with open(err_path, "rb") as f:
                f.seek(start)
                data = f.read()
            self._reply(rec, m["reqid"], pid=pid,
                        data=data.decode("utf-8", "replace"),
                        log=os.path.basename(err_path))
        collect()
