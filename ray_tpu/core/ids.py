"""Binary identifiers for jobs, tasks, actors and objects.

Mirrors the capability (not the layout code) of the reference's ID scheme
(reference: src/ray/common/id.h — JobID 4B, ActorID 16B, TaskID 24B,
ObjectID 28B = TaskID + return index).  Deterministic derivation lets any
process compute a task's return ObjectIds without coordination.
"""

from __future__ import annotations

import hashlib
import os
import threading

JOB_ID_SIZE = 4
ACTOR_ID_SIZE = 16
TASK_ID_SIZE = 24
OBJECT_ID_SIZE = 28
NODE_ID_SIZE = 16
PG_ID_SIZE = 16

_NIL_TASK = b"\xff" * TASK_ID_SIZE


class BaseId:
    SIZE = 0
    __slots__ = ("_bytes",)

    def __init__(self, b: bytes):
        if len(b) != self.SIZE:
            raise ValueError(
                f"{type(self).__name__} requires {self.SIZE} bytes, got {len(b)}")
        self._bytes = b

    @classmethod
    def from_random(cls):
        return cls(os.urandom(cls.SIZE))

    @classmethod
    def nil(cls):
        return cls(b"\xff" * cls.SIZE)

    def is_nil(self) -> bool:
        return self._bytes == b"\xff" * self.SIZE

    def binary(self) -> bytes:
        return self._bytes

    def hex(self) -> str:
        return self._bytes.hex()

    def __hash__(self):
        return hash(self._bytes)

    def __eq__(self, other):
        return type(other) is type(self) and other._bytes == self._bytes

    def __repr__(self):
        return f"{type(self).__name__}({self.hex()})"

    def __reduce__(self):
        return (type(self), (self._bytes,))


class JobID(BaseId):
    SIZE = JOB_ID_SIZE

    @classmethod
    def from_int(cls, i: int) -> "JobID":
        return cls(i.to_bytes(JOB_ID_SIZE, "little"))


class NodeID(BaseId):
    SIZE = NODE_ID_SIZE


class ActorID(BaseId):
    SIZE = ACTOR_ID_SIZE

    @classmethod
    def of(cls, job_id: JobID, parent_task: "TaskID", counter: int) -> "ActorID":
        h = hashlib.sha1(parent_task.binary())
        h.update(counter.to_bytes(8, "little"))
        return cls(h.digest()[: ACTOR_ID_SIZE - JOB_ID_SIZE] + job_id.binary())

    def job_id(self) -> JobID:
        return JobID(self._bytes[-JOB_ID_SIZE:])


class TaskID(BaseId):
    SIZE = TASK_ID_SIZE

    @classmethod
    def for_driver(cls, job_id: JobID) -> "TaskID":
        return cls(b"\x00" * (TASK_ID_SIZE - JOB_ID_SIZE) + job_id.binary())

    @classmethod
    def of(cls, parent: "TaskID", counter: int) -> "TaskID":
        h = hashlib.sha1(parent.binary())
        h.update(counter.to_bytes(8, "little"))
        return cls(h.digest()[: TASK_ID_SIZE - JOB_ID_SIZE]
                   + parent.binary()[-JOB_ID_SIZE:])

    @classmethod
    def for_actor_task(cls, actor_id: ActorID, caller_nonce: bytes,
                      seq: int) -> "TaskID":
        # caller_nonce disambiguates handles held by different processes —
        # without it, two callers' seq counters would collide on the same
        # task id (reference: TaskID embeds the caller's task id).
        h = hashlib.sha1(b"actor:" + actor_id.binary() + caller_nonce)
        h.update(seq.to_bytes(8, "little"))
        return cls(h.digest()[: TASK_ID_SIZE - JOB_ID_SIZE]
                   + actor_id.binary()[-JOB_ID_SIZE:])

    def job_id(self) -> JobID:
        return JobID(self._bytes[-JOB_ID_SIZE:])


class ObjectID(BaseId):
    SIZE = OBJECT_ID_SIZE

    @classmethod
    def for_task_return(cls, task_id: TaskID, index: int) -> "ObjectID":
        """Return `index` (1-based, like the reference) of `task_id`."""
        return cls(task_id.binary() + index.to_bytes(4, "little"))

    @classmethod
    def for_put(cls, task_id: TaskID, put_index: int) -> "ObjectID":
        # Put ids use the high bit of the index to avoid colliding with
        # return ids.
        return cls(task_id.binary()
                   + (put_index | 0x8000_0000).to_bytes(4, "little"))

    def task_id(self) -> TaskID:
        return TaskID(self._bytes[:TASK_ID_SIZE])

    def return_index(self) -> int:
        return int.from_bytes(self._bytes[TASK_ID_SIZE:], "little")


class PlacementGroupID(BaseId):
    SIZE = PG_ID_SIZE


class _Counter:
    """Thread-safe monotonically increasing counter."""

    def __init__(self):
        self._v = 0
        self._lock = threading.Lock()

    def next(self) -> int:
        with self._lock:
            self._v += 1
            return self._v
