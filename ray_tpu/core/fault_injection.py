"""Deterministic, seedable fault-injection plane ("chaos plane").

Every fault-tolerance behavior this framework ships — worker-death
retries, actor re-placement, gang re-formation, head failover — used to
be tested by killing real processes and racing wall-clock sleeps, which
made each FT test a flake budget (the round-5 head-FT load flake was
exactly this).  This module turns faults into a *scripted schedule*:
counters, not clocks, decide when a fault fires, and a seeded RNG makes
probabilistic faults replayable.

The plane hooks the three choke points every message and process
already passes through:

  * transport — ``protocol.Connection.send/send_batch/send_blob/recv``
    and ``local_lane.LaneConnection._post/_deliver``: drop / delay /
    duplicate individual messages, or partition a link, selected by a
    (link-label, message) predicate.  Link labels are attached where
    connections are created (node→head ``("node:<hex8>", "head")``,
    node→node ``("node:<a>", "node:<b>")``, clients
    ``("client:<kind>", <address>)``).
  * process — ``node.NodeService``: kill worker N's process at the K-th
    task dispatch, delay or fail worker spawns (slow-spawn / spawn
    outage).
  * control — ``EventLoopService._dispatch`` and ``HeadService
    .on_tick``: run a scripted trigger (e.g. stop the head — a
    deterministic "head dies mid-operation") at the N-th matching
    service message or tick, or drop the message outright.

Higher layers add their own gated points on the same contract:
serve/drain (``serve_route``, ``serve_stream``, ``replica_drain*``,
``node_drain*``), inference (``infer_admit``, ``infer_block_alloc``,
``infer_speculate``, ``prefix_dir_lookup``, ``prefix_fetch``,
``prefix_install``), the streaming data plane (``data_dispatch``,
``data_shuffle_reduce`` — see ``on_data``), and elastic gang
membership (``gang_readmit`` — see ``on_gang``).

Zero-overhead contract: when no plan is installed (the default,
production state) every hook is a single module-global ``is None``
check — nothing else executes on the hot path.  The acceptance gate
for this file is the committed PERF artifact staying within noise of
the previous round with the plane compiled in but disabled.

In-process only by default: ``install()`` arms the plan for the current
process (the normal shape — virtual clusters run head+nodes in the test
process, so the control plane is fully covered).  For faults inside
spawned node/worker processes, write the plan to disk
(``FaultPlan.save``) and set ``RAY_TPU_FAULT_PLAN_PATH=<path>`` in
their environment; ``autoinstall_from_env()`` runs at node/worker
startup.
"""

from __future__ import annotations

import os
import pickle
import random
import signal as _signal
import threading
import time
from typing import Any, Callable, Optional

# The armed plan.  Hooks read this module attribute directly
# (``_active is not None``) so the disabled path costs one global load.
_active: Optional["FaultPlan"] = None


def active() -> Optional["FaultPlan"]:
    return _active


def install(plan: "FaultPlan") -> "FaultPlan":
    global _active
    _active = plan
    return plan


def uninstall() -> None:
    global _active
    _active = None


class injected:
    """``with fault_injection.injected(plan): ...`` — scoped install."""

    def __init__(self, plan: "FaultPlan"):
        self.plan = plan

    def __enter__(self) -> "FaultPlan":
        return install(self.plan)

    def __exit__(self, *exc) -> bool:
        uninstall()
        return False


def autoinstall_from_env() -> None:
    """Arm a pickled plan in a freshly spawned process (node daemon or
    worker) when the ``fault_plan_path`` config flag (env:
    RAY_TPU_FAULT_PLAN_PATH) names one.  Callable-free plans
    (message/spawn/dispatch rules) pickle cleanly; scripted ``fn``
    rules are in-process only."""
    if _active is not None:
        return
    path = os.environ.get("RAY_TPU_FAULT_PLAN_PATH")
    if not path:
        try:
            from ray_tpu._config import get_config
            path = get_config().fault_plan_path
        except Exception:
            path = ""
    if not path:
        return
    try:
        with open(path, "rb") as f:
            install(pickle.load(f))
    except Exception:
        pass   # a missing/garbled plan must never break startup


# ---------------------------------------------------------------------------
# rules


class Rule:
    """One scripted fault.  Deterministic: the rule keeps a match
    counter; ``nth`` fires on the n-th match (1-based), ``times`` caps
    total firings, ``prob`` draws from the PLAN's seeded RNG — same
    seed, same schedule, every run."""

    def __init__(self, point: str, action: str, *,
                 msg_type: Optional[str] = None,
                 link: Optional[str] = None,
                 service: Optional[str] = None,
                 where: Optional[Callable] = None,
                 nth: Optional[int] = None,
                 times: Optional[int] = None,
                 prob: Optional[float] = None,
                 delay: float = 0.0,
                 sig: int = _signal.SIGKILL,
                 fn: Optional[Callable] = None):
        self.point = point          # send|recv|deliver|spawn|dispatch|
        #                             service_msg|service_tick
        self.action = action        # drop|delay|dup|kill|fail|script
        self.msg_type = msg_type    # match msg["t"]
        self.link = link            # substring matched against the link label
        self.service = service      # match EventLoopService.name
        self.where = where          # extra predicate(label_or_svc, msg_or_spec)
        self.nth = nth
        self.times = times
        self.prob = prob
        self.delay = delay
        self.sig = sig
        self.fn = fn
        self.matches = 0
        self.fired = 0

    def _matches_link(self, label: tuple) -> bool:
        if self.link is None:
            return True
        return any(self.link in str(part) for part in label)

    def decide(self, plan: "FaultPlan", label: Any, payload: Any) -> bool:
        """Count a candidate event; True = the fault fires now."""
        if self.times is not None and self.fired >= self.times:
            return False
        self.matches += 1
        if self.nth is not None and self.matches != self.nth:
            return False
        if self.prob is not None and plan.rng.random() >= self.prob:
            return False
        self.fired += 1
        return True


class Partition:
    """An active network partition between two link-label patterns.
    Messages on any link whose label matches both sides are dropped (in
    BOTH directions) until ``heal()``."""

    def __init__(self, a: str, b: str):
        self.a = a
        self.b = b
        self.healed = False

    def heal(self) -> None:
        self.healed = True

    def severs(self, label: tuple) -> bool:
        if self.healed:
            return False
        text = [str(part) for part in label]
        return (any(self.a in t for t in text)
                and any(self.b in t for t in text))


class FaultPlan:
    """A scripted fault schedule.  Build rules, ``install()`` it, run
    the scenario, ``uninstall()``.  All decisions are counter-driven
    (plus an explicitly seeded RNG), so a failing chaos test replays
    byte-identically."""

    def __init__(self, seed: int = 0):
        self.seed = seed
        self.rng = random.Random(seed)
        self.rules: list[Rule] = []
        self.partitions: list[Partition] = []
        self.log: list[tuple] = []   # (point, action, detail) audit trail
        self._lock = threading.Lock()

    # ------------------------------------------------------------ authoring

    def add(self, rule: Rule) -> Rule:
        self.rules.append(rule)
        return rule

    def drop_messages(self, *, msg_type: Optional[str] = None,
                      link: Optional[str] = None, nth: Optional[int] = None,
                      times: Optional[int] = None,
                      prob: Optional[float] = None,
                      point: str = "send",
                      where: Optional[Callable] = None) -> Rule:
        return self.add(Rule(point, "drop", msg_type=msg_type, link=link,
                             nth=nth, times=times, prob=prob, where=where))

    def delay_messages(self, seconds: float, *,
                       msg_type: Optional[str] = None,
                       link: Optional[str] = None, nth: Optional[int] = None,
                       times: Optional[int] = None,
                       prob: Optional[float] = None,
                       point: str = "send",
                       where: Optional[Callable] = None) -> Rule:
        return self.add(Rule(point, "delay", delay=seconds,
                             msg_type=msg_type, link=link, nth=nth,
                             times=times, prob=prob, where=where))

    def duplicate_messages(self, *, msg_type: Optional[str] = None,
                           link: Optional[str] = None,
                           nth: Optional[int] = None,
                           times: Optional[int] = None,
                           prob: Optional[float] = None,
                           point: str = "send",
                           where: Optional[Callable] = None) -> Rule:
        return self.add(Rule(point, "dup", msg_type=msg_type, link=link,
                             nth=nth, times=times, prob=prob, where=where))

    def partition(self, a: str, b: str) -> Partition:
        p = Partition(a, b)
        self.partitions.append(p)
        return p

    def kill_worker_at_dispatch(self, k: int, *,
                                sig: int = _signal.SIGKILL,
                                where: Optional[Callable] = None,
                                times: int = 1) -> Rule:
        """SIGKILL the worker that receives the k-th dispatched task
        (counted across this process's node services, or per ``where``
        predicate on (node_service, spec))."""
        return self.add(Rule("dispatch", "kill", nth=k, sig=sig,
                             where=where, times=times))

    def slow_spawn(self, seconds: float, *,
                   times: Optional[int] = None) -> Rule:
        return self.add(Rule("spawn", "delay", delay=seconds, times=times))

    def fail_spawn(self, *, times: Optional[int] = None,
                   nth: Optional[int] = None) -> Rule:
        return self.add(Rule("spawn", "fail", times=times, nth=nth))

    def script(self, fn: Callable, *, point: str = "service_msg",
               service: Optional[str] = None,
               msg_type: Optional[str] = None,
               nth: int = 1, times: int = 1,
               drop: bool = False) -> Rule:
        """Run ``fn(service)`` (tick point) or ``fn(service, rec, msg)``
        (message point) at the nth matching event — e.g. stop the head
        at the 3rd cluster_submit to script a head death mid-burst.
        ``drop=True`` also swallows the triggering message (the crash
        happened "before" it was processed)."""
        r = Rule(point, "script", service=service, msg_type=msg_type,
                 nth=nth, times=times, fn=fn)
        r.drop_message = drop
        return self.add(r)

    def save(self, path: str) -> str:
        """Persist for RAY_TPU_FAULT_PLAN autoinstall in spawned
        processes (callable-free plans only)."""
        with open(path, "wb") as f:
            pickle.dump(self, f)
        return path

    def _note(self, point: str, action: str, detail: Any) -> None:
        """Audit-trail append (shape unchanged: 3-tuples) + a timestamped
        copy into the flight recorder when one is armed, so injected
        chaos shows up ATTRIBUTED in the merged `ray_tpu timeline`
        instead of as mystery latency."""
        self.log.append((point, action, detail))
        from ray_tpu.core import flight_recorder as _fr
        if _fr._active is not None:
            _fr._active.note_fault(point, action, detail)

    def __getstate__(self):
        st = dict(self.__dict__)
        del st["_lock"]
        return st

    def __setstate__(self, st):
        self.__dict__.update(st)
        self._lock = threading.Lock()

    # -------------------------------------------------------------- hooks
    #
    # Called from hot paths ONLY when this plan is installed.  Each hook
    # takes the lock: chaos-test rates are far below the contention
    # threshold, and deterministic counters beat racy ones.

    def message_verdict(self, point: str, label: tuple,
                        msg: dict) -> Optional[Any]:
        """None = pass through, "drop", "dup", or ("delay", seconds).
        Partitions are checked first and drop silently in both
        directions."""
        with self._lock:
            for p in self.partitions:
                if p.severs(label):
                    self._note(point, "partition_drop", msg.get("t"))
                    return "drop"
            for r in self.rules:
                if r.point != point:
                    continue
                if r.msg_type is not None and msg.get("t") != r.msg_type:
                    continue
                if not r._matches_link(label):
                    continue
                if r.where is not None and not r.where(label, msg):
                    continue
                if not r.decide(self, label, msg):
                    continue
                self._note(point, r.action, msg.get("t"))
                if r.action == "drop":
                    return "drop"
                if r.action == "dup":
                    return "dup"
                if r.action == "delay":
                    return ("delay", r.delay)
        return None

    def on_dispatch(self, node, worker_rec, spec: dict) -> None:
        """After a task is pushed to a worker: scripted worker kill."""
        with self._lock:
            for r in self.rules:
                if r.point != "dispatch":
                    continue
                if r.where is not None and not r.where(node, spec):
                    continue
                if not r.decide(self, node, spec):
                    continue
                self._note("dispatch", r.action,
                           (worker_rec.pid,
                            spec.get("task_id", b"").hex()[:12]
                            if isinstance(spec.get("task_id"), bytes)
                            else ""))
                if r.action == "kill" and worker_rec.pid:
                    try:
                        os.kill(worker_rec.pid, r.sig)
                    except OSError:
                        pass

    def spawn_verdict(self, node) -> Optional[Any]:
        """None = spawn normally, "fail" = spawn silently dies,
        ("delay", seconds) = spawn lands late."""
        with self._lock:
            for r in self.rules:
                if r.point != "spawn":
                    continue
                if r.where is not None and not r.where(node, None):
                    continue
                if not r.decide(self, node, None):
                    continue
                self._note("spawn", r.action, r.delay)
                if r.action == "fail":
                    return "fail"
                if r.action == "delay":
                    return ("delay", r.delay)
        return None

    def on_service_msg(self, svc, rec, msg: dict) -> bool:
        """Scripted triggers at a service's message dispatch; True =
        swallow the message."""
        fire = []
        drop = False
        with self._lock:
            for r in self.rules:
                if r.point != "service_msg":
                    continue
                if r.service is not None and svc.name != r.service:
                    continue
                if r.msg_type is not None and msg.get("t") != r.msg_type:
                    continue
                if r.where is not None and not r.where(svc, msg):
                    continue
                if not r.decide(self, svc, msg):
                    continue
                self._note("service_msg", "script", msg.get("t"))
                fire.append(r)
                drop = drop or getattr(r, "drop_message", False)
        for r in fire:   # outside the lock: fn may re-enter hooks
            if r.fn is not None:
                r.fn(svc, rec, msg)
        return drop

    def _scripted_ctx_rules(self, point: str, ctx: dict,
                            detail) -> None:
        """Shared matcher for the ctx-dict trigger hooks (on_serve /
        on_drain): fire every rule on ``point``, noting ``detail``;
        scripted fns run OUTSIDE the lock (they may re-enter hooks)."""
        fire = []
        with self._lock:
            for r in self.rules:
                if r.point != point:
                    continue
                if r.where is not None and not r.where(point, ctx):
                    continue
                if not r.decide(self, point, ctx):
                    continue
                self._note(point, r.action, detail)
                fire.append(r)
        for r in fire:
            if r.fn is not None:
                r.fn(ctx)

    def on_serve(self, point: str, ctx: dict) -> None:
        """Scripted triggers in the serve fleet path (points:
        ``serve_route`` — after the router picks a replica;
        ``serve_stream`` — per streamed chunk).  ``ctx`` carries
        {"fleet", "replica", ...}; a scripted ``fn(ctx)`` can e.g. kill
        the routed replica mid-stream (fleet.kill_replica) to prove the
        request resumes elsewhere or fails cleanly — never hangs."""
        self._scripted_ctx_rules(
            point, ctx, getattr(ctx.get("replica"), "tag", None))

    def on_drain(self, point: str, ctx: dict) -> None:
        """Scripted triggers at drain/decommission choke points (the
        graceful-removal state machine, chaos-provable like everything
        else).  Points:

          * ``replica_drain``          — serve controller moved a
            replica ACTIVE -> DRAINING (ctx: {"state", "replica"})
          * ``replica_drain_timeout``  — a drain hit its deadline and
            fell back to the explicit kill+resume path
          * ``node_drain``             — a node received the
            decommission request (ctx: {"node"})
          * ``node_drain_handoff``     — just before the node ships its
            owned-object/ownership handoff to a survivor

        A scripted ``fn(ctx)`` can e.g. hard-kill the node mid-handoff
        to prove lineage reconstruction still covers what the handoff
        didn't (tests/test_drain_chaos.py)."""
        self._scripted_ctx_rules(
            point, ctx,
            getattr(ctx.get("replica"), "tag", None)
            or getattr(ctx.get("node"), "address", None))

    def on_infer(self, point: str, ctx: dict) -> None:
        """Scripted triggers in the inference engine's paged-cache path
        (gated through ``InferenceEngine._chaos``).  Points:

          * ``infer_admit``       — a request was granted rows/blocks at
            a prefill boundary (ctx: {"engine", "req", "need",
            "hit_tokens"})
          * ``infer_block_alloc`` — decode-time block growth (a row
            crossed a block boundary; ctx: {"engine", "row"})
          * ``infer_speculate``   — a speculative pass is about to
            verify its drafts (ctx: {"engine", "rows", "drafted"}).
            A scripted ``fn(ctx)`` may set ``ctx["reject_all"] = True``
            to force full draft rejection (verify still runs, every
            draft is discarded, the block-charge rollback path is
            exercised, and output stays token-exact); raising instead
            injects a verify-step failure into the recovery path
          * ``prefix_dir_lookup`` — the cluster prefix plane consulted
            the head directory for a request's prompt (ctx:
            {"deployment", "keys", "tokens"}); raising forces a
            directory miss (the request routes by occupancy alone)
          * ``prefix_fetch``      — a replica is about to pull cached
            K/V blocks from a directory-confirmed holder (ctx:
            {"deployment", "holder", "replica", "key", "n_tokens",
            "holder_replica"}).  A scripted ``fn(ctx)`` can raise to
            fail the transfer, or kill/drain ``holder_replica`` to
            prove the mid-fetch death path — either way the adopter
            silently falls back to chunked-prefill recompute
          * ``prefix_install``    — fetched blocks are about to be
            installed into the adopter's pool/trie (ctx: same as
            ``prefix_fetch``); raising exercises the install-failure
            fallback (fresh blocks freed, no refcount leak)

        A scripted ``fn(ctx)`` can raise to inject a pool failure at
        the exact choke point — the engine's recovery path (fail
        in-flight, clear the prefix index, reallocate the donated pool)
        is chaos-provable like everything else
        (tests/test_paged_cache.py)."""
        self._scripted_ctx_rules(point, ctx, ctx.get("engine"))

    def on_data(self, point: str, ctx: dict) -> None:
        """Scripted triggers in the streaming data plane (gated through
        ``data.execution.PhysicalOperator._chaos`` and the trainer's
        ``train.ingest.DatasetShard._chaos``).  Points:

          * ``data_dispatch``       — a block entered a streaming
            operator (ctx: {"operator", "idx", "port", "nbytes"}), or
            a trainer-side ingest shard fetched its next step batch
            (ctx: {"shard", "rank", "step", "epoch"}).  A scripted
            ``fn(ctx)`` can raise to fail the pipeline or the training
            step at an EXACT block/step — the elastic-recovery path is
            what's under test — or kill a gang member's process to
            script a mid-epoch shrink with no wall-clock race
          * ``data_shuffle_reduce`` — the streaming shuffle is about to
            dispatch the merge for one partition (ctx: {"operator",
            "partition", "num_parts"}); raising fails the shuffle at
            the all-to-all barrier, ``delay`` simulates a straggling
            reducer the budget accounting must absorb
        """
        self._scripted_ctx_rules(
            point, ctx, ctx.get("operator") or ctx.get("shard"))

    def on_gang(self, point: str, ctx: dict) -> None:
        """Scripted triggers at gang-membership choke points (gated
        through ``parallel.gang.MultiHostGang._chaos``).  Points:

          * ``gang_readmit`` — replacement members are about to be
            re-admitted at a re-gang boundary (ctx: {"world",
            "target", "want"}); raising forces the readmission-failure
            path — the elastic trainer must keep making progress at
            the shrunken world instead of crashing
        """
        self._scripted_ctx_rules(point, ctx, ctx.get("world"))

    def on_service_tick(self, svc) -> None:
        fire = []
        with self._lock:
            for r in self.rules:
                if r.point != "service_tick":
                    continue
                if r.service is not None and svc.name != r.service:
                    continue
                if not r.decide(self, svc, None):
                    continue
                self._note("service_tick", "script", svc.name)
                fire.append(r)
        for r in fire:
            if r.fn is not None:
                r.fn(svc)


def apply_delay(seconds: float) -> None:
    """Shared delay primitive so hooks stay one-liners.  Sleeping on
    the calling thread is deliberate: a slow link stalls its sender —
    exactly the backpressure shape real congestion has."""
    time.sleep(seconds)
