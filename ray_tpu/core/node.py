"""Node service: the per-node daemon (raylet analogue).

The node was ONE ~4,000-line module through round 10; it is now split
along its three planes, with this file left as the service shell —
composition, lifecycle, and the head channel:

  * ``node_workers.py`` — worker pool / prefork / liveness / OOM
    (reference: worker_pool.h, memory_monitor.h)
  * ``node_transfer.py`` — object directory + transfer + relay + shm
    bookkeeping + ownership/lineage recovery (reference:
    object_manager.h, plasma store.h, object_recovery_manager.h)
  * ``node_sched.py`` — task/actor/placement-group scheduling, parking,
    spillover + rebalance (reference: local_task_manager.h,
    cluster_task_manager.h)

State stays SINGLE-OWNER: every attribute is created in
``NodeService.__init__`` here, and the mixins are stateless method
bundles over that state (the event loop remains one thread, so no new
synchronization appears with the split).  ``ray_tpu lint`` resolves
cross-mixin ``self`` calls through this composed class — the protocol /
blocking / hotpath / locks invariants that made the split safe keep
gating all four modules.

Cluster half (active when ``head_address`` is set): head channel
(register / heartbeat / view sync, reference: ray_syncer.h:30), task
spillover routing, cluster-scope request proxying, and node-death
recovery hooks.  Without a head this service runs standalone: the
single-node control plane fused into one loop.  Runs as a thread inside
the driver (default, ``ray_tpu.init()``) or standalone
(``python -m ray_tpu.core.node``).
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
import traceback
from collections import deque
from typing import Any, Optional

from ray_tpu._config import RayTpuConfig
from ray_tpu.core import fault_injection as _fi
from ray_tpu.core import flight_recorder as _fr
from ray_tpu.core import protocol
from ray_tpu.core.ids import ActorID, NodeID, ObjectID
from ray_tpu.core.object_store import (NativeObjectStoreCore,
                                       make_object_store_core)
from ray_tpu.core.service import (ClientRec, ClusterStoreMixin,
                                  EventLoopService)
from ray_tpu.core.node_workers import (NodeWorkersMixin, _ForkedProc,
                                       _PendingLaunch)
from ray_tpu.core.node_transfer import (NodeTransferMixin, ObjInfo,
                                        OwnedRec, _LOCAL_NODES_BY_HEX,
                                        _gil_free_copy, _wire_spec)
from ray_tpu.core.node_sched import (NodeSchedMixin, ActorRec, PGRec,
                                     TaskRec)

__all__ = [
    "NodeService", "ObjInfo", "OwnedRec", "TaskRec", "ActorRec",
    "PGRec", "_ForkedProc", "_PendingLaunch", "_LOCAL_NODES_BY_HEX",
    "_gil_free_copy", "_wire_spec",
]


class NodeService(NodeWorkersMixin, NodeTransferMixin, NodeSchedMixin,
                  ClusterStoreMixin, EventLoopService):
    name = "node"

    def __init__(self, config: RayTpuConfig, session: str,
                 session_dir: str, listen_host: str = "127.0.0.1",
                 port: int = 0, num_cpus: Optional[float] = None,
                 num_tpus: Optional[float] = None,
                 resources: Optional[dict] = None,
                 head_address: Optional[str] = None,
                 stop_on_driver_exit: bool = True,
                 labels: Optional[dict] = None):
        super().__init__(listen_host, port)
        _fi.autoinstall_from_env()   # chaos plane in spawned node daemons
        self.config = config
        self.session = session
        self.session_dir = session_dir
        self.node_id = NodeID.from_random()
        _LOCAL_NODES_BY_HEX[self.node_id.hex()] = self
        self.stop_on_driver_exit = stop_on_driver_exit
        os.makedirs(os.path.join(session_dir, "logs"), exist_ok=True)
        # same-host workers connect over a unix socket (cheaper per
        # message than TCP loopback); falls back to the TCP address
        self.worker_address = self.address
        try:
            port = self.address.rsplit(":", 1)[1]
            self.worker_address = self.add_unix_listener(
                os.path.join(session_dir, f"node-{port}.sock"))
        except OSError:
            pass

        ncpu = num_cpus if num_cpus is not None else float(os.cpu_count() or 1)
        self.total_resources: dict[str, float] = {"CPU": ncpu}
        if num_tpus:
            self.total_resources["TPU"] = float(num_tpus)
            # advertise the generation so accelerator_type constraints
            # can pin placement (util/accelerators.accelerator_resource)
            try:
                from ray_tpu.util.accelerators import (
                    accelerator_resource, detect_tpu_type)
                tpu_type = detect_tpu_type()
                if tpu_type:
                    self.total_resources[
                        accelerator_resource(tpu_type)] = float(num_tpus)
            except Exception:   # noqa: BLE001 - detection is best-effort
                pass
        if resources:
            self.total_resources.update(resources)
        self.available = dict(self.total_resources)

        spill_dir = config.object_spilling_dir or os.path.join(session_dir, "spill")
        self.store = make_object_store_core(session,
                                            config.object_store_memory,
                                            spill_dir,
                                            spill_uri=config.object_spilling_uri)

        self.objects: dict[ObjectID, ObjInfo] = {}
        self.tasks: dict[bytes, TaskRec] = {}
        # Two-queue dispatch (reference: local_task_manager.h waiting →
        # dispatch queues): tasks wait on deps, then join a runnable FIFO
        # per executor class.
        self.runnable_cpu: deque[dict] = deque()
        self.runnable_zero: deque[dict] = deque()   # zero-demand specs
        self.runnable_tpu: deque[dict] = deque()
        # incremental aggregates over the runnable queues: admission and
        # spawn decisions run PER EVENT, so recomputing by iterating a
        # deep queue would be O(backlog) per task -> O(n^2) per burst
        self._queued_demand: dict[str, float] = {}
        self._queued_pg = 0
        self.dep_waiting: dict[ObjectID, list] = {}  # oid -> waiting specs
        self.actors: dict[ActorID, ActorRec] = {}
        self.named_actors: dict[tuple[str, str], ActorID] = {}
        self._actors_wanting_worker: deque = deque()
        self._init_stores()   # kv / pubsub / function store (mixin)
        self.pgs: dict[PlacementGroupID, PGRec] = {}
        self.pg_available: dict[tuple[bytes, int], dict] = {}  # (pg,bundle)->free
        self.task_events: deque = deque(maxlen=config.task_events_buffer_size)
        # bounded retention of finished TaskRecs: the state API wants
        # recent history, but an unbounded dict makes every scan over
        # self.tasks O(everything ever run)
        self._done_order: deque = deque()
        self._spawning = 0
        self._worker_procs: list = []   # Popen | _ForkedProc
        self._worker_log_by_pid: dict[int, tuple] = {}  # pid -> (out, err)
        # fork-server template (reference: worker_pool.h:352
        # PrestartWorkers amortization; here startup cost is paid once
        # in the template and workers fork in ~ms — core/prefork.py)
        self._prefork_proc: Optional[subprocess.Popen] = None
        self._prefork_conn = None       # control socket to the template
        self._prefork_buf = b""
        self._prefork_path = ""
        if config.prefork_workers:
            self._start_prefork_template()
        # containerized-worker spawns in flight: image -> Popen.  One
        # at a time per image (a container cold-start is seconds; a
        # burst would stampede podman), re-armed when the worker
        # registers or its launcher process dies.
        self._container_spawning: dict[str, Any] = {}
        # Batched-get bookkeeping: (conn_id, reqid) -> {ids, remaining}.
        self._multigets: dict[tuple, dict] = {}
        self._mg_by_oid: dict[ObjectID, set] = {}

        # ---- cluster plane state (dormant when head_address is None) ----
        self.head_address = head_address
        self.labels = dict(labels or {})
        self._owner_driver: Optional[int] = None
        self.head_conn: Optional[protocol.Connection] = None
        self.cluster_view: dict[str, dict] = {}
        self._head_seq = 0
        self._head_pending: dict[int, Any] = {}
        self._head_subs: set[str] = set()
        self._hb_inflight = False
        self._peer_conns: dict[str, protocol.Connection] = {}
        self._peer_connecting: dict[str, list] = {}   # node_hex -> [cb]
        # actor_id(bytes) -> ("alive", node_hex, address)
        self.actor_cache: dict[bytes, tuple] = {}
        self._awaiting_actor: dict[bytes, list] = {}   # aid -> queued specs
        # aid -> when its locate was orphaned by a head failover
        self._actor_wait_parked: dict[bytes, float] = {}
        self._pulls: dict[bytes, dict] = {}            # oid bytes -> state
        self._pull_attempts: dict[bytes, int] = {}
        self._out_transfers: dict[tuple, dict] = {}    # (conn_id, oid) -> st
        self._bcast_tail: dict[bytes, tuple] = {}      # ob -> (hex, addr)
        self._watched: set[bytes] = set()              # locate sent for oid
        self._fwd_tasks: dict[bytes, dict] = {}        # task_id -> fwd info
        self._fwd_by_oid: dict[bytes, bytes] = {}      # return oid -> task_id
        self._pg_prepared: dict[tuple, dict] = {}      # (pg,idx) -> bundle
        self._pg_bundles: dict[tuple, dict] = {}       # committed originals
        self._pending_local_pgs: dict[bytes, dict] = {}  # single-node queue
        self._device_pending_pulls: dict[bytes, list] = {}  # ob -> [(conn,m)]
        self._released_wait: set[ObjectID] = set()     # owner-released oids
        self._nested_count: dict[bytes, int] = {}      # id -> container holds
        # ---- ownership + lineage (reference: reference_count.h /
        # object_recovery_manager.h / ownership_based_object_directory.cc)
        self.owned: dict[bytes, OwnedRec] = {}         # oid -> directory rec
        self.lineage: dict[bytes, dict] = {}           # tid -> {spec,cost,live,recons}
        self._lineage_bytes = 0
        self._lineage_order: deque[bytes] = deque()
        self._owner_watch: dict[bytes, str] = {}       # oid -> owner hex asked

        # OOM protection (reference: memory_monitor.h + worker killing
        # policy; N15 MemoryMonitor slice)
        self.memory_monitor = None
        if config.memory_monitor_refresh_ms > 0:
            from ray_tpu.core.memory_monitor import MemoryMonitor
            self.memory_monitor = MemoryMonitor(
                config.memory_usage_threshold,
                config.memory_monitor_refresh_ms)
        self._oom_kills: dict[bytes, str] = {}     # task_id -> detail
        self.oom_kill_count = 0

        # per-iteration coalescing for head/peer channels: handlers emit
        # several small messages per task (location reports, owner
        # pushes, forwards); one batched send per loop pass replaces a
        # send (syscall or lane post + peer wakeup) per message
        self._head_out: list = []
        self._peer_out: dict[int, tuple] = {}   # id(conn) -> (conn, [msgs])

        # ---- graceful decommission (ACTIVE -> DRAINING -> TERMINATED):
        # armed by the head's node_drain push.  While draining: no new
        # work is queued here (specs forward to the head unless the head
        # explicitly routed them back), running tasks finish under the
        # deadline, then owned objects / ownership records hand off to a
        # survivor and the node exits via drain_done.
        self._draining = False
        self._drain_deadline = 0.0
        self._drain_state = ""           # "" | waiting | handoff | done
        self._drain_timed_out = False
        self._drain_acks_pending: set[str] = set()   # survivor node hexes

        self._last_hb = 0.0
        self._hb_period = config.heartbeat_period_ms / 1000.0
        # ticks must run at least as often as heartbeats are due
        self.tick_interval = min(self.tick_interval, self._hb_period)

        # flight recorder (core/flight_recorder.py): armed per process
        # by config/env; workers stamp data-driven off the spec instead
        if config.flight_recorder and _fr._active is None:
            _fr.enable()

        self.metrics_exporter = None
        if config.metrics_export_port:
            from ray_tpu.metrics import MetricsExporter, node_metrics_snapshot
            self.metrics_exporter = MetricsExporter(
                lambda: node_metrics_snapshot(self),
                port=config.metrics_export_port)

        if head_address:
            self._connect_head()

    # ------------------------------------------------------------------ run

    def on_tick(self) -> None:
        # periodic re-dispatch: recovers from missed wakeups and
        # re-evaluates worker-pool health (dead spawns etc.)
        self._audit_worker_pool()
        self._schedule()
        self._rebalance()
        self._expire_stale_pins()
        self._sweep_released()
        self._memory_check()
        self._expire_parked_actor_waits()
        if self._draining:
            self._drain_check()
        self._heartbeat()

    def _cleanup(self) -> None:
        from ray_tpu.core import local_lane
        local_lane.unregister_service(self)
        _LOCAL_NODES_BY_HEX.pop(self.node_id.hex(), None)
        for rec in list(self.clients.values()):
            try:
                self._push(rec, {"t": "shutdown"})
                self._flush(rec)
            except Exception:
                pass
        # closing the control connection tells the template to exit
        if self._prefork_conn is not None:
            try:
                self._prefork_conn.close()
            except OSError:
                pass
            self._prefork_conn = None
        deadline = time.time() + 2.0
        for p in self._worker_procs:
            try:
                p.wait(timeout=max(0.0, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
        if self._prefork_proc is not None:
            try:
                self._prefork_proc.wait(timeout=max(0.0,
                                                    deadline - time.time()))
            except subprocess.TimeoutExpired:
                self._prefork_proc.kill()
        for rec in list(self.clients.values()):
            try:
                rec.sock.close()
            except OSError:
                pass
            if rec.lane is not None:
                rec.lane._mark_closed()
        self.listener.close()
        self._close_extra_listeners()
        self.sel.close()
        for conn in self._peer_conns.values():
            try:
                conn.close()
            except Exception:
                pass
        if self.head_conn is not None:
            try:
                self.head_conn.close()
            except Exception:
                pass
        if self.metrics_exporter is not None:
            self.metrics_exporter.stop()
        self.store.shutdown()

    # ------------------------------------------------------- head channel

    def _connect_head(self) -> None:
        conn = protocol.connect(
            self.head_address, remote=True,
            label=(f"node:{self.node_id.hex()[:8]}", "head"))
        conn.send({"t": "register_node", "reqid": 0,
                   "node_id": self.node_id.hex(), "address": self.address,
                   "resources": self.total_resources,
                   "available": dict(self.available),
                   "labels": self.labels})
        reply = conn.recv(timeout=30.0)
        if reply.get("error"):
            raise RuntimeError(f"head registration failed: {reply['error']}")
        self.cluster_view = reply.get("view", {})
        # the head's session differs from this node's DERIVED session
        # (per-node shm arenas) — replica validation uses the head's
        self.head_session = reply.get("session", "")
        self.head_conn = conn
        self._start_head_recv(conn)

    def _start_head_recv(self, conn) -> None:
        """Route head pushes onto the event loop.  A lane connection
        (same-process head) delivers straight from the head's loop —
        no dedicated recv thread, one wakeup fewer per message."""
        from ray_tpu.core.local_lane import LaneConnection
        if isinstance(conn, LaneConnection):
            conn.on_close = lambda: self.post(self._head_lost)
            conn.set_deliver(
                lambda m: self.post(lambda m=m: self._on_head_msg(m)))
            return
        t = threading.Thread(target=self._head_recv_loop, daemon=True,
                             name="raytpu-node-head")
        t.start()

    def _head_recv_loop(self) -> None:
        while not self._stop.is_set():
            try:
                msg = self.head_conn.recv()
            except protocol.ConnectionClosed:
                self.post(self._head_lost)
                return
            except Exception:
                continue
            self.post(lambda m=msg: self._on_head_msg(m))

    def _head_lost(self) -> None:
        # Head death orphans the cluster plane; keep serving local work
        # (reference: raylets survive transient GCS outages), fail
        # everything mid-flight through the head so callers see errors
        # instead of hanging forever, and keep trying to REJOIN — a
        # persistent head restarting on the same address picks the
        # cluster back up (reference: GCS-FT reconnection,
        # gcs_client reconnection loop).
        if self.head_conn is None:
            return
        sys.stderr.write("[node] lost connection to head service\n")
        self.head_conn = None
        self._hb_inflight = False
        pending = list(self._head_pending.values())
        self._head_pending.clear()
        for cb in pending:
            try:
                cb({"error": "head connection lost"})
            except Exception:
                sys.stderr.write("[node] head-lost callback failed:\n"
                                 + traceback.format_exc())
        # actor-bound tasks whose locate was cut off stay PARKED for the
        # failover grace window (config actor_locate_failover_grace_s):
        # failing them instantly turned every head failover into
        # client-visible actor errors.  _head_rejoined re-issues the
        # locates; on_tick expires the ones the grace ran out on.
        now = time.monotonic()
        for ab in self._awaiting_actor:
            self._actor_wait_parked.setdefault(ab, now)
        self.post_later(1.0, self._try_reconnect_head)

    def _try_reconnect_head(self) -> None:
        if self.head_conn is not None or self._stop.is_set():
            return

        def work():
            try:
                conn = protocol.connect(
                    self.head_address, timeout=3.0, remote=True,
                    label=(f"node:{self.node_id.hex()[:8]}", "head"))
                conn.send({"t": "register_node", "reqid": 0,
                           "node_id": self.node_id.hex(),
                           "address": self.address,
                           "resources": self.total_resources,
                           "available": dict(self.available),
                           "labels": self.labels})
                reply = conn.recv(timeout=10.0)
                if reply.get("error"):
                    raise RuntimeError(reply["error"])
            except Exception:
                self.post_later(2.0, self._try_reconnect_head)
                return
            self.post(lambda: self._head_rejoined(conn, reply))
        threading.Thread(target=work, daemon=True,
                         name="raytpu-head-reconnect").start()

    def _head_rejoined(self, conn: protocol.Connection,
                       reply: dict) -> None:
        if self.head_conn is not None:
            try:
                conn.close()
            except Exception:
                pass
            return
        sys.stderr.write("[node] rejoined head service\n")
        self.head_conn = conn
        self.cluster_view = reply.get("view", {})
        self.head_session = reply.get("session",
                                      getattr(self, "head_session", ""))
        self._start_head_recv(conn)
        try:
            # re-establish cluster-visible state: subscriptions, object
            # locations, actor liveness (a restarted head restored its
            # durable directory but not this live state)
            for ch in self._head_subs:
                conn.send({"t": "subscribe", "channel": ch})
            adds = []
            for oid, info in self.objects.items():
                if info.state in ("ready", "error"):
                    info.loc_reported = True
                    adds.append(oid.binary())
            if adds:
                conn.send({"t": "report_locations", "adds": adds})
            for ar in self.actors.values():
                if ar.state != "dead":
                    self._report_actor_state(ar)
            # re-ask for every actor whose locate the failover orphaned;
            # the parked specs resume the moment the new head answers
            for ab in list(self._awaiting_actor):
                self._head_rpc(
                    {"t": "locate_actor", "actor_id": ab},
                    lambda reply, ab=ab: self._on_actor_located(ab, reply))
        except protocol.ConnectionClosed:
            self._head_lost()

    def _head_send(self, msg: dict) -> None:
        """Queue a head-bound message; the loop flushes the batch once
        per iteration (_flush_corked).  Send failures surface there and
        run the normal head-loss path."""
        if self.head_conn is None:
            return
        self._head_out.append(msg)

    def _conn_send(self, conn, msg: dict) -> None:
        """Queue a peer-bound message for the per-iteration batch
        flush."""
        ent = self._peer_out.get(id(conn))
        if ent is None:
            self._peer_out[id(conn)] = (conn, [msg])
        else:
            ent[1].append(msg)

    def _flush_corked(self) -> None:
        if self._head_out:
            out, self._head_out = self._head_out, []
            conn = self.head_conn
            if conn is not None:
                try:
                    if len(out) == 1:
                        conn.send(out[0])
                    else:
                        conn.send_batch(out)
                except protocol.ConnectionClosed:
                    self._head_lost()
        if self._peer_out:
            batches, self._peer_out = self._peer_out, {}
            for conn, msgs in batches.values():
                try:
                    if len(msgs) == 1:
                        conn.send(msgs[0])
                    else:
                        conn.send_batch(msgs)
                except (protocol.ConnectionClosed, OSError):
                    pass   # peer drop is handled by its recv/on_close path
        super()._flush_corked()

    def _head_rpc(self, msg: dict, cb=None) -> None:
        if self.head_conn is None:
            if cb is not None:
                cb({"error": "no head connection"})
            return
        if cb is not None:
            self._head_seq += 1
            msg["reqid"] = self._head_seq
            self._head_pending[self._head_seq] = cb
        self._head_send(msg)

    def _on_head_msg(self, m: dict) -> None:
        if m.get("t") == "reply":
            cb = self._head_pending.pop(m.get("reqid"), None)
            if cb is not None:
                try:
                    cb(m)
                except Exception:
                    sys.stderr.write("[node] head rpc callback failed:\n"
                                     + traceback.format_exc())
            return
        handler = getattr(self, "_hh_" + m["t"], None)
        if handler is None:
            return
        try:
            handler(m)
        except Exception:
            sys.stderr.write(f"[node] head push {m['t']} failed:\n"
                             + traceback.format_exc())

    def _head_reply(self, reqid: int, **kw) -> None:
        kw["t"] = "reply"
        kw["reqid"] = reqid
        self._head_send(kw)

    def _heartbeat(self) -> None:
        if self.head_conn is None or self._hb_inflight:
            return
        now = time.monotonic()
        if now - self._last_hb < self._hb_period:
            return
        self._last_hb = now
        self._hb_inflight = True

        def cb(reply):
            self._hb_inflight = False
            if not reply.get("error"):
                self.cluster_view = reply.get("view", self.cluster_view)
        queued = {k: v for k, v in self._queued_demand.items()
                  if v > 1e-9}
        self._head_rpc({"t": "heartbeat",
                        "available": self._projected_available(),
                        "total": self.total_resources,
                        "queued": queued}, cb)

    # -------------------------------------------------------- registration

    def _h_register(self, rec, m):
        rec.kind = m["kind"]
        rec.worker_id = m.get("worker_id", "")
        rec.pid = m.get("pid", 0)
        rec.tpu = bool(m.get("tpu", False))
        rec.node_hex = m.get("node_hex", "")
        rec.container_image = m.get("container_image", "")
        if rec.kind == "driver" and self._owner_driver is None:
            # the FIRST driver owns this node's lifetime; later drivers
            # (job entrypoints, attached shells) come and go freely
            self._owner_driver = rec.conn_id
        if rec.kind in ("worker", "tpu_executor"):
            if rec.container_image:
                # container launches track per-image (_container_
                # spawning), never the host _spawning counter — a
                # decrement here would mark an unrelated in-flight host
                # spawn as done
                self._container_spawning.pop(rec.container_image, None)
            else:
                self._spawning = max(0, self._spawning - 1)
        self._reply(rec, m["reqid"], session=self.session,
                    node_id=self.node_id.hex(), address=self.address,
                    config=self.config.to_dict(),
                    native_store=isinstance(self.store,
                                            NativeObjectStoreCore))
        while self._actors_wanting_worker:
            ar = self._actors_wanting_worker.popleft()
            if ar.state in ("pending", "restarting") and ar.conn_id is None:
                self._place_actor(ar)
                break   # one new worker hosts one actor
        self._schedule()

    # -- functions

    def _h_register_function(self, rec, m):
        self._store_function(m["function_id"], m["pickled"])
        if self.head_conn is not None:
            # cluster-wide export so any node's workers can fetch it
            self._head_send({"t": "register_function",
                             "function_id": m["function_id"],
                             "pickled": m["pickled"]})
        if "reqid" in m:
            self._reply(rec, m["reqid"], ok=True)

    def _h_fetch_function(self, rec, m):
        fid = m["function_id"]
        if fid in self.functions:
            self._reply(rec, m["reqid"], pickled=self.functions[fid])
            return
        first = fid not in self._fn_waiters
        self._fn_waiters.setdefault(fid, []).append((rec.conn_id, m["reqid"]))
        if first and self.head_conn is not None:
            # the head parks the fetch until some node registers the
            # function (functions are exported once, cluster-wide)
            def cb(reply):
                if reply.get("pickled"):
                    self._store_function(fid, reply["pickled"])
                elif reply.get("error"):
                    # head gone: fail waiters instead of hanging workers
                    for conn_id, reqid in self._fn_waiters.pop(fid, []):
                        w = self.clients.get(conn_id)
                        if w is not None:
                            self._reply(w, reqid,
                                        error="function fetch failed: "
                                              f"{reply['error']}")
            self._head_rpc({"t": "fetch_function", "function_id": fid}, cb)

    # -- head proxying ------------------------------------------------------

    def _cluster_scope(self, rec: ClientRec, m: dict) -> bool:
        """Route a cluster-scope client request.  True = handled here
        (proxied to the head, or failed transiently); False = this node
        is STANDALONE and should serve it from its local stores.

        The distinction matters during a head failover: a cluster
        node with its head temporarily gone must NOT silently fall back
        to its (empty) local store — that's a split-brain read.  It
        answers with a transient, RetryPolicy-retryable error instead,
        so clients ride out the failover and then read the truth."""
        if self.head_address is None:
            return False
        if self.head_conn is None:
            if "reqid" in m:
                self._reply(rec, m["reqid"],
                            error="head connection lost (failover in "
                                  "progress)")
            return True
        self._proxy_to_head(rec, m)
        return True

    def _proxy_to_head(self, rec: ClientRec, m: dict) -> None:
        """Forward a cluster-scope client request to the head verbatim and
        relay the reply (errors included)."""
        reqid = m.get("reqid")
        fwd = {k: v for k, v in m.items() if k != "reqid"}
        if reqid is None:
            self._head_send(fwd)
            return

        def cb(reply):
            w = self.clients.get(rec.conn_id)
            if w is None:
                return
            out = {k: v for k, v in reply.items() if k not in ("t", "reqid")}
            self._reply(w, reqid, **out)
        self._head_rpc(fwd, cb)

    # 2PC participant handlers (pushed by the head over the head channel;
    # reference: gcs_placement_group_scheduler.h Prepare/Commit on raylets)

    def _hh_head_snapshot(self, m: dict) -> None:
        """Persist the head's replicated snapshot (the cluster-as-the-
        database head-FT store — see head.py _fan_out_replicas)."""
        if m.get("session") not in (None, getattr(self, "head_session",
                                                  "")):
            return   # a different cluster's state must never land here
        # seq fence per head incarnation: a slow async snapshot can fan
        # out AFTER a newer snapshot_now one — applying it would undo
        # the barrier's guarantee (and lose whatever the newer snapshot
        # captured on a later head-machine recovery)
        boot = m.get("boot")
        if boot != getattr(self, "_head_replica_boot", None):
            self._head_replica_boot = boot
            self._head_replica_seq = 0
        if m.get("seq", 0) < getattr(self, "_head_replica_seq", 0):
            return   # stale replica from an older snapshot
        path = os.path.join(self.session_dir, "head_replica.state")
        tmp = path + ".tmp"
        try:
            with open(tmp, "wb") as f:
                f.write(m["data"])
            os.replace(tmp, path)
            self._head_replica_seq = m.get("seq", 0)
        except OSError:
            pass  # a missed replica is refreshed by the next snapshot

    def _h_fetch_head_snapshot(self, rec, m):
        """A replacement head bootstraps from this node's replica; the
        reply carries this node's session so a head recovering against
        the wrong cluster rejects it."""
        path = os.path.join(self.session_dir, "head_replica.state")
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            self._reply(rec, m["reqid"],
                        session=getattr(self, "head_session", ""),
                        error="no head snapshot replica on this node")
            return
        self._reply(rec, m["reqid"], ok=True, data=data,
                    session=getattr(self, "head_session", ""),
                    seq=getattr(self, "_head_replica_seq", 0))

    # -- kv / pubsub

    def _h_kv_put(self, rec, m):
        if self._cluster_scope(rec, m):
            return
        super()._h_kv_put(rec, m)

    def _h_kv_get(self, rec, m):
        if self._cluster_scope(rec, m):
            return
        super()._h_kv_get(rec, m)

    def _h_kv_del(self, rec, m):
        if self._cluster_scope(rec, m):
            return
        super()._h_kv_del(rec, m)

    def _h_kv_keys(self, rec, m):
        if self._cluster_scope(rec, m):
            return
        super()._h_kv_keys(rec, m)

    # -- cluster prefix directory (the head hosts it; see core/head.py
    # _h_prefix_* and serve/fleet/prefix_directory.py).  Standalone
    # nodes answer with benign no-ops: a single-node session has
    # exactly one fleet process, whose in-proc directory already IS the
    # whole prefix plane — there is nothing cluster-scope to mirror.

    def _h_prefix_publish(self, rec, m):
        if self._cluster_scope(rec, m):
            return
        if "reqid" in m:
            self._reply(rec, m["reqid"], ok=True, published=0)

    def _h_prefix_lookup(self, rec, m):
        if self._cluster_scope(rec, m):
            return
        if "reqid" in m:
            self._reply(rec, m["reqid"], ok=True, hit=None)

    def _h_prefix_invalidate(self, rec, m):
        if self._cluster_scope(rec, m):
            return
        if "reqid" in m:
            self._reply(rec, m["reqid"], ok=True, invalidated=0)

    def _h_subscribe(self, rec, m):
        ch = m["channel"]
        if self.head_conn is not None and ch not in self._head_subs:
            # subscribe this NODE at the head once per channel; local
            # clients fan out from the node (reference: pubsub long-poll
            # through the raylet)
            self._head_subs.add(ch)
            self._head_send({"t": "subscribe", "channel": ch})
        super()._h_subscribe(rec, m)

    def _publish(self, channel: str, data: Any) -> None:
        if self.head_conn is not None:
            # cluster-wide: the head fans out to subscribed nodes
            # (including this one), which deliver locally on _hh_pub
            self._head_send({"t": "publish", "channel": channel,
                             "data": data})
            return
        self._publish_local(channel, data)

    def _hh_pub(self, m: dict) -> None:
        self._publish_local(m["channel"], m["data"])

    def _hh_view_update(self, m: dict) -> None:
        self.cluster_view = m["view"]

    def _h_flight_recorder(self, rec, m):
        """Observer query: completed lifecycle records + chaos events +
        serve-ingress events + the per-stage summary (the `ray_tpu
        timeline` source)."""
        fr = _fr._active
        if fr is None:
            self._reply(rec, m["reqid"], enabled=False, records=[],
                        faults=[], ingress=[], stages={})
            return
        self._reply(rec, m["reqid"], enabled=True,
                    records=fr.export_records(
                        limit=int(m.get("limit", 2000))),
                    faults=fr.export_faults(),
                    ingress=fr.export_ingress(),
                    stages=fr.stage_summary())

    def _h_state(self, rec, m):
        what = m["what"]
        if what in ("nodes", "resources", "cluster_actors") \
                and self.head_conn is not None:
            # cluster-scope views come from the head (ray.nodes() /
            # ray.cluster_resources() are cluster-wide in the reference)
            fwd = dict(m)
            fwd["what"] = {"cluster_actors": "actors"}.get(what, what)
            self._proxy_to_head(rec, fwd)
            return
        if what == "tasks":
            out = [{"task_id": tid.hex(), "name": tr.spec.get("name", ""),
                    "state": tr.state, "error": tr.error,
                    "submitted_at": tr.submitted_at,
                    "duration": (tr.finished_at - tr.started_at)
                    if tr.finished_at else None}
                   for tid, tr in self.tasks.items()]
        elif what == "actors":
            out = [{"actor_id": aid.hex(), "state": ar.state,
                    "name": ar.name, "namespace": ar.namespace,
                    "class_name": ar.spec.get("class_name", ""),
                    "pending_calls": len(ar.queue)}
                   for aid, ar in self.actors.items()]
        elif what == "objects":
            out = [{"object_id": oid.hex(), "state": info.state,
                    "loc": info.loc, "size": info.size}
                   for oid, info in self.objects.items()]
        elif what == "workers":
            out = [{"worker_id": c.worker_id, "kind": c.kind, "pid": c.pid,
                    "state": c.state, "tpu": c.tpu,
                    "log": os.path.basename(
                        self._worker_log_by_pid.get(c.pid, ("", ""))[0])
                    or None}
                   for c in self.clients.values()
                   if c.kind in ("worker", "tpu_executor")]
        elif what == "nodes":
            out = [{"node_id": self.node_id.hex(), "address": self.address,
                    "resources": self.total_resources,
                    "available": self.available, "alive": True}]
        elif what == "task_events":
            out = list(self.task_events)
        elif what == "resources":
            out = {"total": self.total_resources, "available": self.available}
        else:
            out = []
        self._reply(rec, m["reqid"], data=out)

    def _h_ping(self, rec, m):
        self._reply(rec, m["reqid"], ok=True, time=time.time())

    def _h_head_flush(self, rec, m):
        """Replication barrier: force the head to snapshot + fan out
        replicas, reply once THIS node's replica has landed (the
        head_snapshot push precedes the head's reply on this channel)."""
        if self.head_conn is None:
            self._reply(rec, m["reqid"], ok=True, replicated=False)
            return
        reqid = m["reqid"]

        def cb(reply):
            w = self.clients.get(rec.conn_id)
            if w is None:
                return
            if reply.get("error"):
                self._reply(w, reqid, error=reply["error"])
            else:
                self._reply(w, reqid, ok=True,
                            replicated=bool(reply.get("replicated")))
        self._head_rpc({"t": "snapshot_now"}, cb)

    # ------------------------------------------------- graceful drain

    def _h_drain_node(self, rec, m):
        """Client entry point for decommissioning a cluster node: the
        request proxies to the head (which owns membership and flips the
        target to DRAINING).  Standalone nodes have nowhere to drain
        to."""
        if self._cluster_scope(rec, m):
            return
        self._reply(rec, m["reqid"],
                    error="standalone node: nothing to drain to "
                          "(drain_node needs a cluster)")

    def _hh_node_drain(self, m: dict) -> None:
        """Head push: decommission this node gracefully.  From here on
        the lifecycle is DRAINING: queued specs re-park to the head,
        new local submissions forward, running tasks get ``deadline_s``
        to finish, then the owned-object handoff ships and the node
        exits via drain_done (node.py hosts the state machine; the
        handoff itself lives in node_transfer)."""
        if self._draining:
            return
        self._draining = True
        self._drain_state = "waiting"
        self._drain_deadline = (time.monotonic()
                                + float(m.get("deadline_s", 30.0)))
        sys.stderr.write("[node] draining for decommission "
                         f"(deadline {m.get('deadline_s', 30.0)}s)\n")
        fi = _fi._active
        if fi is not None:
            fi.on_drain("node_drain", {"node": self})
        self._repark_queued_to_head()
        self._drain_check()

    def _drain_busy(self) -> bool:
        """Work the drain must wait for — everything that will still
        EXECUTE here: tasks running on workers, actor method calls in
        flight OR queued (an actor can't move, so its queue drains
        here), specs still in the runnable queues (only PG-bound and
        head-routed-back specs remain there during a drain — both run
        here by design), and dep-waiting specs (they either forward on
        resolution or run here; either way exiting under them drops
        work).  Conservative signals are safe: the deadline caps the
        wait, and past it the EXPLICIT timeout path runs."""
        for rec in self.clients.values():
            if rec.current_task is not None:
                return True
        for ar in self.actors.values():
            # an actor whose CREATION is still in flight (worker
            # spawning) must reach alive before the drain can judge its
            # queue — exiting under it strands calls parked at their
            # submitters awaiting the locate
            if ar.state in ("pending", "restarting"):
                return True
            if ar.state != "dead" and (ar.running or ar.queue):
                return True
        if self.runnable_cpu or self.runnable_tpu or self.runnable_zero:
            return True
        if self.dep_waiting:
            return True
        return False

    def _drain_check(self) -> None:
        if self._drain_state != "waiting":
            return
        timed_out = time.monotonic() >= self._drain_deadline
        if self._drain_busy() and not timed_out:
            return
        self._drain_timed_out = timed_out and self._drain_busy()
        self._drain_state = "handoff"
        self._drain_handoff()

    def _drain_finish(self) -> None:
        """Handoff shipped (and acked, or the ack window closed): tell
        the head this removal is COMPLETE — never a surprise — then
        stop.  The head's node_dead fan-out still runs as the safety
        net for anything the handoff didn't cover."""
        if self._drain_state == "done":
            return
        self._drain_state = "done"
        self._head_rpc({"t": "drain_done",
                        "node_id": self.node_id.hex(),
                        "timed_out": self._drain_timed_out},
                       lambda reply: self._stop.set())
        # backstop: head unreachable / reply lost — exit anyway
        self.post_later(5.0, self._stop.set)

    def _h_stop_node(self, rec, m):
        """Hard-stop this node on request — the chaos-testing kill switch
        (reference: the NodeKiller in _private/test_utils.py:1337 and
        `ray kill-random-node`).  Workers die with the node; the head
        notices through the dropped connection / missed heartbeats."""
        if "reqid" in m:
            self._reply(rec, m["reqid"], ok=True)
        for p in self._worker_procs:
            if p.poll() is None:
                try:
                    p.kill()
                except OSError:
                    pass
        if self._prefork_proc is not None and self._prefork_proc.poll() is None:
            try:
                self._prefork_proc.kill()
            except OSError:
                pass
        self._stop.set()

    # -- disconnect handling

    def on_client_drop(self, rec: ClientRec) -> None:
        for oid, _ts in rec.held_pins:
            self.store.unpin(oid)
        rec.held_pins.clear()
        # device-resident entries die with their owner process
        for oid, info in list(self.objects.items()):
            if info.loc == "device" and info.owner_conn == rec.conn_id:
                self._device_owner_lost(oid, info)
        # drop any outbound transfers to this peer
        for key in [k for k in self._out_transfers if k[0] == rec.conn_id]:
            st = self._out_transfers.pop(key)
            if st.get("view") is not None:
                st["view"] = None
                if st.get("pinned", True):
                    self.store.unpin(st["oid"])
        # fail or retry the running task (reference: worker death →
        # owner retries, task_manager.h:406)
        if rec.current_task is not None:
            tr = self.tasks.get(rec.current_task)
            oom_detail = self._oom_kills.pop(rec.current_task, None)
            if tr is not None and tr.state == "running":
                if not tr.spec.get("_cpu_released"):
                    self._return_resources(tr.spec)
                tr.spec.pop("_cpu_released", None)
                if tr.retries_left > 0:
                    tr.retries_left -= 1
                    tr.state = "pending"
                    if _fr._active is not None:
                        # name the failed attempt + death-detection gap
                        # explicitly so it doesn't pollute the retry's
                        # enqueue interval in the stage histograms
                        _fr._active.stamp(tr.spec, "retry")
                    self._make_runnable(tr.spec)
                elif oom_detail is not None:
                    from ray_tpu.core.client import OutOfMemoryError
                    tr.state = "failed"
                    tr.error = oom_detail
                    tr.finished_at = time.time()
                    self._record_event(tr.spec, "FAILED")
                    for b in tr.spec["return_ids"]:
                        self._seal_error_object(
                            ObjectID(b), OutOfMemoryError(oom_detail))
                else:
                    self._fail_task(tr.spec,
                                    f"Worker died while running task "
                                    f"(pid={rec.pid})")
        conn_actors = [a for a in self.actors.values()
                       if a.conn_id == rec.conn_id and a.state != "dead"]
        for ar in conn_actors:
                self._return_resources(ar.spec)
                ar.conn_id = None
                # In-flight method calls die with the worker: fail them so
                # callers see an actor-death error instead of hanging
                # (reference: actor task fate on actor death,
                # direct_actor_task_submitter.h DisconnectActor).
                for spec in list(ar.running.values()):
                    self._fail_task(spec,
                                    f"Actor died while executing method "
                                    f"'{spec.get('method', '?')}' "
                                    f"(pid={rec.pid})")
                ar.running.clear()
                if ar.restarts_left != 0:
                    if ar.restarts_left > 0:
                        ar.restarts_left -= 1
                    ar.state = "restarting"
                    self._report_actor_state(ar)
                    self._place_actor(ar)
                else:
                    ar.state = "dead"
                    ar.death_cause = f"worker process died (pid={rec.pid})"
                    self._report_actor_state(ar)
                    self._fail_actor_queue(ar, ar.death_cause)
        if (rec.kind == "driver" and self.stop_on_driver_exit
                and rec.conn_id == self._owner_driver):
            # owning driver gone → shut down
            self._stop.set()
        self._schedule()

def main() -> None:
    import argparse
    parser = argparse.ArgumentParser(description="ray_tpu node service")
    parser.add_argument("--port", type=int, default=6379)
    parser.add_argument("--session", default=None)
    parser.add_argument("--session-dir", default=None)
    parser.add_argument("--num-cpus", type=float, default=None)
    parser.add_argument("--num-tpus", type=float, default=None)
    parser.add_argument("--head-address", default=None,
                        help="head service address; omit for standalone")
    parser.add_argument("--label", action="append", default=[],
                        help="k=v node label (repeatable); e.g. the "
                             "autoscaler's provider_node_id")
    args = parser.parse_args()
    labels = dict(kv.split("=", 1) for kv in args.label)
    import uuid
    session = args.session or uuid.uuid4().hex
    session_dir = args.session_dir or os.path.join(
        "/tmp/ray_tpu", f"session_{session[:8]}")
    svc = NodeService(RayTpuConfig(), session, session_dir, port=args.port,
                      num_cpus=args.num_cpus, num_tpus=args.num_tpus,
                      head_address=args.head_address,
                      stop_on_driver_exit=args.head_address is None,
                      labels=labels)
    print(f"ray_tpu node service listening on {svc.address} "
          f"(session {session})", flush=True)
    try:
        svc.run()
    except KeyboardInterrupt:
        svc.stop()


if __name__ == "__main__":
    main()
