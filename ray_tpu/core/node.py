"""Node service: the single-process control plane for one node.

Combines, in one event loop, the capabilities the reference splits between
the GCS server and the raylet:

  * task scheduling + worker pool        (reference: src/ray/raylet/
    node_manager.cc HandleRequestWorkerLease:1822, worker_pool.h,
    local_task_manager.h dispatch loop)
  * object directory + inline store + shm bookkeeping + spilling
    (reference: core_worker memory_store.h, plasma store.h,
    local_object_manager.h)
  * actor directory, creation, restart   (reference: gcs_actor_manager.cc
    HandleRegisterActor:249, SchedulePendingActors:1247)
  * named actors, KV store, pubsub, function store, job table
    (reference: gcs_kv_manager.cc, pubsub/, function_manager.py)
  * placement groups (resource reservation; 2PC collapses to one phase on a
    single node — reference: gcs_placement_group_scheduler.h:104 2PC)
  * task state events for the state API  (reference: gcs_task_manager.cc)

Runs either as a thread inside the driver (default, `ray_tpu.init()`) or as
a standalone head process (`python -m ray_tpu.core.node`).  The scheduler is
two-level-ready: `_schedule()` is the local half; a cluster half can route
specs between multiple NodeService instances (multi-host, later milestone).
"""

from __future__ import annotations

import os
import selectors
import socket
import struct
import subprocess
import sys
import threading
import time
import traceback
import pickle
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Optional

from ray_tpu._config import RayTpuConfig
from ray_tpu.core.ids import ActorID, NodeID, ObjectID, PlacementGroupID
from ray_tpu.core.object_store import (NativeObjectStoreCore,
                                       make_object_store_core)
from ray_tpu.core.protocol import dumps_frame

_HDR = struct.Struct("<Q")

# ---------------------------------------------------------------------------
# records


@dataclass
class ClientRec:
    conn_id: int
    sock: socket.socket
    kind: str = ""               # driver | worker | tpu_executor | observer
    worker_id: str = ""
    pid: int = 0
    tpu: bool = False            # may execute TPU tasks
    state: str = "idle"          # idle | busy | blocked
    current_task: Optional[bytes] = None
    dedicated_actor: Optional[ActorID] = None
    rbuf: bytearray = field(default_factory=bytearray)
    wbuf: bytearray = field(default_factory=bytearray)
    held_pins: list = field(default_factory=list)
    closed: bool = False


@dataclass
class ObjInfo:
    state: str = "pending"       # pending | ready | error
    loc: str = ""                # inline | shm
    data: Optional[bytes] = None  # inline payload (SerializedObject wire bytes)
    size: int = 0
    owner: str = ""
    is_error: bool = False
    wait_waiters: list = field(default_factory=list)


@dataclass
class TaskRec:
    spec: dict
    state: str = "pending"       # pending | running | finished | failed
    worker: Optional[int] = None
    retries_left: int = 0
    submitted_at: float = field(default_factory=time.time)
    started_at: float = 0.0
    finished_at: float = 0.0
    error: str = ""


@dataclass
class ActorRec:
    actor_id: ActorID
    spec: dict                   # creation spec (reusable for restart)
    state: str = "pending"       # pending | alive | restarting | dead
    conn_id: Optional[int] = None
    name: str = ""
    namespace: str = ""
    restarts_left: int = 0
    seq: int = 0
    queue: deque = field(default_factory=deque)   # pending method-call specs
    running: dict = field(default_factory=dict)   # task_id -> in-flight spec
    max_concurrency: int = 1
    death_cause: str = ""

    @property
    def inflight(self) -> int:
        return len(self.running)


@dataclass
class PGRec:
    pg_id: PlacementGroupID
    bundles: list                # list[dict resource->qty]
    strategy: str
    state: str = "created"       # single-node: reserve succeeds or raises


class NodeService:
    def __init__(self, config: RayTpuConfig, session: str,
                 session_dir: str, listen_host: str = "127.0.0.1",
                 port: int = 0, num_cpus: Optional[float] = None,
                 num_tpus: Optional[float] = None,
                 resources: Optional[dict] = None):
        self.config = config
        self.session = session
        self.session_dir = session_dir
        self.node_id = NodeID.from_random()
        os.makedirs(os.path.join(session_dir, "logs"), exist_ok=True)

        ncpu = num_cpus if num_cpus is not None else float(os.cpu_count() or 1)
        self.total_resources: dict[str, float] = {"CPU": ncpu}
        if num_tpus:
            self.total_resources["TPU"] = float(num_tpus)
        if resources:
            self.total_resources.update(resources)
        self.available = dict(self.total_resources)

        spill_dir = config.object_spilling_dir or os.path.join(session_dir, "spill")
        self.store = make_object_store_core(session,
                                            config.object_store_memory,
                                            spill_dir)

        self.sel = selectors.DefaultSelector()
        self.listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.listener.bind((listen_host, port))
        self.listener.listen(512)
        self.listener.setblocking(False)
        self.address = "%s:%d" % self.listener.getsockname()
        self.sel.register(self.listener, selectors.EVENT_READ, None)

        self._next_conn = 0
        self.clients: dict[int, ClientRec] = {}
        self.objects: dict[ObjectID, ObjInfo] = {}
        self.tasks: dict[bytes, TaskRec] = {}
        # Two-queue dispatch (reference: local_task_manager.h waiting →
        # dispatch queues): tasks wait on deps, then join a runnable FIFO
        # per executor class.
        self.runnable_cpu: deque[dict] = deque()
        self.runnable_tpu: deque[dict] = deque()
        self.dep_waiting: dict[ObjectID, list] = {}  # oid -> waiting specs
        self.actors: dict[ActorID, ActorRec] = {}
        self.named_actors: dict[tuple[str, str], ActorID] = {}
        self.kv: dict[tuple[str, bytes], bytes] = {}
        self.functions: dict[str, bytes] = {}
        self.pubsub: dict[str, set[int]] = {}
        self.pgs: dict[PlacementGroupID, PGRec] = {}
        self.pg_available: dict[tuple[bytes, int], dict] = {}  # (pg,bundle)->free
        self.task_events: deque = deque(maxlen=config.task_events_buffer_size)
        self._spawning = 0
        self._worker_procs: list[subprocess.Popen] = []
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._fn_waiters: dict[str, list] = {}
        # Callbacks posted from timers/other threads; drained by the event
        # loop so ALL state mutation happens on the loop thread.
        self._posted: deque = deque()
        self._posted_lock = threading.Lock()
        # Batched-get bookkeeping: (conn_id, reqid) -> {ids, remaining}.
        self._multigets: dict[tuple, dict] = {}
        self._mg_by_oid: dict[ObjectID, set] = {}
        self._last_tick = 0.0

    def post(self, fn) -> None:
        with self._posted_lock:
            self._posted.append(fn)

    def post_later(self, delay: float, fn) -> None:
        t = threading.Timer(delay, lambda: self.post(fn))
        t.daemon = True
        t.start()

    # ------------------------------------------------------------------ run

    def start_thread(self) -> None:
        self._thread = threading.Thread(target=self.run, name="raytpu-node",
                                        daemon=True)
        self._thread.start()

    def run(self) -> None:
        while not self._stop.is_set():
            while True:
                with self._posted_lock:
                    if not self._posted:
                        break
                    fn = self._posted.popleft()
                try:
                    fn()
                except Exception:
                    sys.stderr.write("[node] posted callback failed:\n"
                                     + traceback.format_exc())
            now = time.monotonic()
            if now - self._last_tick > 0.25:
                self._last_tick = now
                # periodic re-dispatch: recovers from missed wakeups and
                # re-evaluates worker-pool health (dead spawns etc.)
                try:
                    self._schedule()
                    self._expire_stale_pins()
                except Exception:
                    sys.stderr.write("[node] periodic schedule error:\n"
                                     + traceback.format_exc())
            try:
                events = self.sel.select(timeout=0.05)
            except OSError:
                continue
            for key, mask in events:
                if key.data is None:
                    self._accept()
                else:
                    rec: ClientRec = key.data
                    try:
                        if mask & selectors.EVENT_READ:
                            self._on_readable(rec)
                        if mask & selectors.EVENT_WRITE:
                            self._on_writable(rec)
                    except Exception:
                        sys.stderr.write("[node] connection handler error:\n"
                                         + traceback.format_exc())
                        try:
                            self._drop_client(rec)
                        except Exception:
                            sys.stderr.write("[node] drop_client error:\n"
                                             + traceback.format_exc())
        self._cleanup()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None and self._thread is not threading.current_thread():
            self._thread.join(timeout=5)

    def _cleanup(self) -> None:
        for rec in list(self.clients.values()):
            try:
                self._push(rec, {"t": "shutdown"})
                self._flush(rec)
            except Exception:
                pass
        deadline = time.time() + 2.0
        for p in self._worker_procs:
            try:
                p.wait(timeout=max(0.0, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
        for rec in list(self.clients.values()):
            try:
                rec.sock.close()
            except OSError:
                pass
        self.listener.close()
        self.sel.close()
        self.store.shutdown()

    # ----------------------------------------------------------------- io

    def _accept(self) -> None:
        try:
            sock, _ = self.listener.accept()
        except OSError:
            return
        sock.setblocking(False)
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._next_conn += 1
        rec = ClientRec(conn_id=self._next_conn, sock=sock)
        self.clients[rec.conn_id] = rec
        self.sel.register(sock, selectors.EVENT_READ, rec)

    def _on_readable(self, rec: ClientRec) -> None:
        try:
            data = rec.sock.recv(1 << 20)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._drop_client(rec)
            return
        if not data:
            self._drop_client(rec)
            return
        rec.rbuf += data
        while True:
            if len(rec.rbuf) < _HDR.size:
                break
            (n,) = _HDR.unpack_from(rec.rbuf)
            if len(rec.rbuf) < _HDR.size + n:
                break
            frame = bytes(rec.rbuf[_HDR.size:_HDR.size + n])
            del rec.rbuf[:_HDR.size + n]
            msg = pickle.loads(frame)
            self._dispatch(rec, msg)

    def _on_writable(self, rec: ClientRec) -> None:
        if rec.wbuf:
            try:
                sent = rec.sock.send(rec.wbuf)
                del rec.wbuf[:sent]
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                self._drop_client(rec)
                return
        if not rec.wbuf:
            self.sel.modify(rec.sock, selectors.EVENT_READ, rec)

    def _push(self, rec: ClientRec, msg: dict) -> None:
        if rec.closed:
            return
        frame = dumps_frame(msg)
        if rec.wbuf:
            rec.wbuf += frame
            return
        try:
            sent = rec.sock.send(frame)
        except (BlockingIOError, InterruptedError):
            sent = 0
        except OSError:
            self._drop_client(rec)
            return
        if sent < len(frame):
            rec.wbuf += frame[sent:]
            try:
                self.sel.modify(rec.sock,
                                selectors.EVENT_READ | selectors.EVENT_WRITE, rec)
            except KeyError:
                pass

    def _flush(self, rec: ClientRec) -> None:
        rec.sock.setblocking(True)
        if rec.wbuf:
            try:
                rec.sock.sendall(bytes(rec.wbuf))
            except OSError:
                pass
            rec.wbuf.clear()

    def _reply(self, rec: ClientRec, reqid: int, **kw) -> None:
        kw["t"] = "reply"
        kw["reqid"] = reqid
        self._push(rec, kw)

    # ------------------------------------------------------------- dispatch

    def _dispatch(self, rec: ClientRec, msg: dict) -> None:
        handler = getattr(self, "_h_" + msg["t"], None)
        if handler is None:
            if "reqid" in msg:
                self._reply(rec, msg["reqid"], error=f"unknown message {msg['t']}")
            return
        try:
            handler(rec, msg)
        except Exception:
            tb = traceback.format_exc()
            sys.stderr.write(f"[node] handler {msg['t']} failed:\n{tb}")
            if "reqid" in msg:
                self._reply(rec, msg["reqid"], error=tb)

    # -- registration

    def _h_register(self, rec, m):
        rec.kind = m["kind"]
        rec.worker_id = m.get("worker_id", "")
        rec.pid = m.get("pid", 0)
        rec.tpu = bool(m.get("tpu", False))
        if rec.kind in ("worker", "tpu_executor"):
            self._spawning = max(0, self._spawning - 1)
        self._reply(rec, m["reqid"], session=self.session,
                    node_id=self.node_id.hex(), address=self.address,
                    config=self.config.to_dict(),
                    native_store=isinstance(self.store,
                                            NativeObjectStoreCore))
        self._schedule()

    # -- objects

    def _h_put_inline(self, rec, m):
        oid = ObjectID(m["object_id"])
        info = self.objects.setdefault(oid, ObjInfo())
        info.state = "error" if m.get("is_error") else "ready"
        info.loc = "inline"
        info.data = m["data"]
        info.size = len(m["data"])
        info.owner = m.get("owner", rec.worker_id)
        info.is_error = bool(m.get("is_error"))
        self._resolve_waiters(oid, info)
        if "reqid" in m:
            self._reply(rec, m["reqid"], ok=True)

    def _h_register_object(self, rec, m):
        oid = ObjectID(m["object_id"])
        info = self.objects.setdefault(oid, ObjInfo())
        info.state = "ready"
        info.loc = "shm"
        info.size = m["size"]
        info.owner = m.get("owner", rec.worker_id)
        self.store.register(oid, m["size"])
        self._resolve_waiters(oid, info)
        if "reqid" in m:
            self._reply(rec, m["reqid"], ok=True)

    def _h_get_objects(self, rec, m):
        """Batched blocking get: reply once ALL requested objects resolve."""
        ids = [ObjectID(b) for b in m["object_ids"]]
        pending = [o for o in ids
                   if self.objects.setdefault(o, ObjInfo()).state == "pending"]
        if not pending:
            self._reply_batch(rec, m["reqid"], ids)
            return
        key = (rec.conn_id, m["reqid"])
        self._multigets[key] = {"ids": ids, "remaining": set(pending)}
        for o in pending:
            self._mg_by_oid.setdefault(o, set()).add(key)
        if rec.state == "busy":
            rec.state = "blocked"
            self._release_task_cpu(rec)
            self._schedule()

    def _reply_batch(self, rec, reqid, ids):
        results = []
        for oid in ids:
            info = self.objects[oid]
            if info.loc == "shm":
                if self.store.is_spilled(oid):
                    self.store.restore(oid)
                self.store.touch(oid)
                # Pin until the client acks mapping (release_pins) so
                # eviction can't unlink the segment mid-get (reference:
                # plasma pins objects for the duration of a Get).
                self.store.pin(oid)
                rec.held_pins.append((oid, time.monotonic()))
                results.append({"loc": "shm", "size": info.size,
                                "is_error": info.is_error})
            else:
                results.append({"loc": "inline", "data": info.data,
                                "is_error": info.is_error})
        self._reply(rec, reqid, results=results)

    def _h_need_space(self, rec, m):
        # A client's arena allocation failed: spill unpinned objects
        # (reference: plasma create_request_queue.h queues client creates
        # until eviction frees memory — here the client blocks on this
        # request and retries).
        freed = self.store.evict_for(int(m["nbytes"]))
        self._reply(rec, m["reqid"], freed=freed)

    def _h_release_pins(self, rec, m):
        ids = {ObjectID(b) for b in m["object_ids"]}
        kept = []
        for oid, ts in rec.held_pins:
            if oid in ids:
                ids.discard(oid)
                self.store.unpin(oid)
            else:
                kept.append((oid, ts))
        rec.held_pins[:] = kept

    def _expire_stale_pins(self) -> None:
        """Get-replies whose ack never arrived (client timeout/death race)
        must not pin objects forever."""
        cutoff = time.monotonic() - 120.0
        for rec in self.clients.values():
            if not rec.held_pins:
                continue
            kept = []
            for oid, ts in rec.held_pins:
                if ts < cutoff:
                    self.store.unpin(oid)
                else:
                    kept.append((oid, ts))
            rec.held_pins[:] = kept

    def _resolve_waiters(self, oid: ObjectID, info: ObjInfo) -> None:
        for key in self._mg_by_oid.pop(oid, ()):
            mg = self._multigets.get(key)
            if mg is None:
                continue
            mg["remaining"].discard(oid)
            if not mg["remaining"]:
                del self._multigets[key]
                w = self.clients.get(key[0])
                if w is not None:
                    if w.state == "blocked":
                        w.state = "busy"
                    self._reply_batch(w, key[1], mg["ids"])
        for conn_id, reqid, ids, num_returns, deadline in list(info.wait_waiters):
            self._try_finish_wait(conn_id, reqid, ids, num_returns, deadline)
        info.wait_waiters.clear()
        # release tasks waiting on this dependency
        for spec in self.dep_waiting.pop(oid, ()):
            spec["_ndeps"] -= 1
            if spec["_ndeps"] == 0:
                self._make_runnable(spec)
        self._schedule()

    def _h_wait(self, rec, m):
        ids = [ObjectID(b) for b in m["object_ids"]]
        self._try_finish_wait(rec.conn_id, m["reqid"], ids, m["num_returns"],
                              time.time() + m["timeout"] if m.get("timeout")
                              is not None else None, first=True)

    def _try_finish_wait(self, conn_id, reqid, ids, num_returns, deadline,
                         first=False):
        rec = self.clients.get(conn_id)
        if rec is None:
            return
        ready = [o for o in ids
                 if self.objects.get(o) is not None
                 and self.objects[o].state != "pending"]
        timed_out = deadline is not None and time.time() >= deadline
        if len(ready) >= num_returns or timed_out:
            if not timed_out:
                ready = ready[:num_returns]
            self._reply(rec, reqid, ready=[o.binary() for o in ready])
            return
        if first:
            for o in ids:
                info = self.objects.setdefault(o, ObjInfo())
                if info.state == "pending":
                    info.wait_waiters.append((conn_id, reqid, ids, num_returns,
                                              deadline))
            if deadline is not None:
                self.post_later(max(0.0, deadline - time.time()),
                                lambda: self._try_finish_wait(
                                    conn_id, reqid, ids, num_returns, deadline))

    def _h_free_objects(self, rec, m):
        for b in m["object_ids"]:
            oid = ObjectID(b)
            info = self.objects.get(oid)
            if info is not None and (info.state == "pending"
                                     or oid in self._mg_by_oid
                                     or info.wait_waiters
                                     or oid in self.dep_waiting):
                # fail anyone blocked on it before it vanishes
                err = pickle.dumps(RuntimeError(
                    f"Object {oid.hex()[:16]} was freed"))
                from ray_tpu.core.serialization import SerializedObject
                info.state = "error"
                info.loc = "inline"
                info.data = SerializedObject(inband=err).to_bytes()
                info.is_error = True
                self._resolve_waiters(oid, info)
            self.objects.pop(oid, None)
            self.store.delete(oid)
        if "reqid" in m:
            self._reply(rec, m["reqid"], ok=True)

    def _h_object_stats(self, rec, m):
        self._reply(rec, m["reqid"], stats=self.store.stats(),
                    num_objects=len(self.objects))

    # -- functions

    def _h_register_function(self, rec, m):
        self.functions[m["function_id"]] = m["pickled"]
        for conn_id, reqid in self._fn_waiters.pop(m["function_id"], []):
            w = self.clients.get(conn_id)
            if w is not None:
                self._reply(w, reqid, pickled=m["pickled"])
        if "reqid" in m:
            self._reply(rec, m["reqid"], ok=True)

    def _h_fetch_function(self, rec, m):
        fid = m["function_id"]
        if fid in self.functions:
            self._reply(rec, m["reqid"], pickled=self.functions[fid])
        else:
            self._fn_waiters.setdefault(fid, []).append((rec.conn_id, m["reqid"]))

    # -- tasks

    def _h_submit_task(self, rec, m):
        spec = m["spec"]
        spec["submitter"] = rec.conn_id
        tr = TaskRec(spec=spec, retries_left=spec.get("max_retries", 0))
        self.tasks[spec["task_id"]] = tr
        for b in spec["return_ids"]:
            self.objects.setdefault(ObjectID(b), ObjInfo())
        self._record_event(spec, "PENDING")
        if "reqid" in m:
            self._reply(rec, m["reqid"], ok=True)
        self._enqueue_task(spec)

    def _enqueue_task(self, spec: dict) -> None:
        if not self._feasible(spec):
            self._fail_task(spec, "Infeasible resource demand: "
                            f"{self._demand(spec)} on {self.total_resources}")
            return
        ndeps = 0
        for b in spec.get("arg_ids", []):
            oid = ObjectID(b)
            info = self.objects.setdefault(oid, ObjInfo())
            if info.state == "pending":
                ndeps += 1
                self.dep_waiting.setdefault(oid, []).append(spec)
        spec["_ndeps"] = ndeps
        if ndeps == 0:
            self._make_runnable(spec)
            self._schedule()

    def _make_runnable(self, spec: dict) -> None:
        if spec.get("num_tpus"):
            self.runnable_tpu.append(spec)
        else:
            self.runnable_cpu.append(spec)

    def _h_task_done(self, rec, m):
        tid = m["task_id"]
        tr = self.tasks.get(tid)
        if tr is not None:
            tr.state = "failed" if m.get("error") else "finished"
            tr.finished_at = time.time()
            tr.error = m.get("error", "")
            self._record_event(tr.spec, "FAILED" if m.get("error") else "FINISHED")
        if rec.dedicated_actor is not None:
            ar = self.actors.get(rec.dedicated_actor)
            if ar is not None:
                ar.running.pop(tid, None)
                self._dispatch_actor_queue(ar)
        else:
            if rec.state in ("busy", "blocked"):
                rec.state = "idle"
            rec.current_task = None
            if tr is not None and not tr.spec.get("_cpu_released"):
                self._return_resources(tr.spec)
        # unpin args
        if tr is not None:
            for b in tr.spec.get("arg_ids", []):
                self.store.unpin(ObjectID(b))
        self._schedule()

    def _release_task_cpu(self, rec: ClientRec) -> None:
        """Worker blocked on get: release its task's resources so the node
        can keep making progress (reference: raylet releases CPU for
        blocked workers)."""
        if rec.current_task is None:
            return
        tr = self.tasks.get(rec.current_task)
        if tr is not None and not tr.spec.get("_cpu_released"):
            tr.spec["_cpu_released"] = True
            self._return_resources(tr.spec)

    def _demand(self, spec) -> dict:
        d = dict(spec.get("resources") or {})
        # Tasks default to 1 CPU; actors hold 0 CPU for their lifetime
        # unless explicitly requested (reference: ray actor default
        # num_cpus=0 after creation, ray_option_utils.py).
        d.setdefault("CPU", 0.0 if spec.get("kind") == "actor_create" else 1.0)
        if spec.get("num_tpus"):
            d["TPU"] = float(spec["num_tpus"])
        return d

    def _try_acquire(self, spec) -> bool:
        demand = self._demand(spec)
        pg = spec.get("placement_group")
        if pg is not None:
            key = (pg[0], pg[1])
            free = self.pg_available.get(key)
            if free is None:
                return False
            if all(free.get(k, 0.0) + 1e-9 >= v for k, v in demand.items()):
                for k, v in demand.items():
                    free[k] = free.get(k, 0.0) - v
                return True
            return False
        if all(self.available.get(k, 0.0) + 1e-9 >= v for k, v in demand.items()):
            for k, v in demand.items():
                self.available[k] = self.available.get(k, 0.0) - v
            return True
        return False

    def _return_resources(self, spec) -> None:
        demand = self._demand(spec)
        pg = spec.get("placement_group")
        if pg is not None:
            free = self.pg_available.get((pg[0], pg[1]))
            if free is not None:
                for k, v in demand.items():
                    free[k] = free.get(k, 0.0) + v
            return
        for k, v in demand.items():
            self.available[k] = self.available.get(k, 0.0) + v

    def _feasible(self, spec) -> bool:
        demand = self._demand(spec)
        if spec.get("placement_group"):
            return True
        return all(self.total_resources.get(k, 0.0) + 1e-9 >= v
                   for k, v in demand.items())

    def _args_ready(self, spec) -> bool:
        for b in spec.get("arg_ids", []):
            info = self.objects.get(ObjectID(b))
            if info is None or info.state == "pending":
                return False
        return True

    def _schedule(self) -> None:
        """FIFO dispatch from the runnable queues (reference:
        LocalTaskManager::DispatchScheduledTasksToWorkers,
        local_task_manager.cc:101).  O(1) amortized per event: stops at the
        first queue head that cannot be placed."""
        for q, tpu in ((self.runnable_cpu, False), (self.runnable_tpu, True)):
            while q:
                spec = q[0]
                w = self._find_idle_worker(tpu=tpu)
                if w is None:
                    if not tpu:
                        self._maybe_spawn_worker()
                    break
                if not self._try_acquire(spec):
                    break
                q.popleft()
                self._dispatch_task(w, spec)

    def _find_idle_worker(self, tpu: bool) -> Optional[ClientRec]:
        for rec in self.clients.values():
            if (rec.kind in ("worker", "tpu_executor") and rec.state == "idle"
                    and rec.dedicated_actor is None and rec.tpu == tpu):
                return rec
        return None

    def _dispatch_task(self, w: ClientRec, spec: dict) -> None:
        tr = self.tasks[spec["task_id"]]
        tr.state = "running"
        tr.worker = w.conn_id
        tr.started_at = time.time()
        w.state = "busy"
        w.current_task = spec["task_id"]
        for b in spec.get("arg_ids", []):
            self.store.pin(ObjectID(b))
        self._record_event(spec, "RUNNING")
        self._push(w, {"t": "execute", "spec": spec})

    def _fail_task(self, spec: dict, error: str) -> None:
        tr = self.tasks.get(spec["task_id"])
        if tr is not None:
            tr.state = "failed"
            tr.error = error
        err = pickle.dumps(RuntimeError(error))
        from ray_tpu.core.serialization import SerializedObject
        data = SerializedObject(inband=err).to_bytes()
        for b in spec["return_ids"]:
            oid = ObjectID(b)
            info = self.objects.setdefault(oid, ObjInfo())
            info.state = "error"
            info.loc = "inline"
            info.data = data
            info.is_error = True
            self._resolve_waiters(oid, info)

    def _maybe_spawn_worker(self, tpu: bool = False) -> None:
        if tpu:
            return  # TPU executors are registered by the driver, not spawned
        # Self-heal the in-flight spawn counter against crashed spawns.
        alive_procs = sum(1 for p in self._worker_procs if p.poll() is None)
        registered = sum(1 for c in self.clients.values()
                         if c.kind == "worker" and not c.tpu)
        self._spawning = max(0, alive_procs - registered)
        # Demand-driven pool growth (reference: worker_pool.h capped startup
        # concurrency :192): one worker per waiting task/actor, capped.
        n_actors_waiting = sum(
            1 for a in self.actors.values()
            if a.state in ("pending", "restarting") and a.conn_id is None
            and not a.spec.get("num_tpus"))
        idle = sum(1 for c in self.clients.values()
                   if c.kind == "worker" and not c.tpu and c.state == "idle"
                   and c.dedicated_actor is None)
        # Tasks can only run while CPU is available, so a pool larger than
        # the free CPUs is waste; placement-group tasks draw on their
        # bundle reservation instead, and actors hold no CPU — both always
        # need a process.  Concurrent startups are capped (reference:
        # worker_pool.h maximum_startup_concurrency :192,717).
        n_pg = sum(1 for s in self.runnable_cpu if s.get("placement_group"))
        cpu_demand = min(len(self.runnable_cpu) - n_pg,
                         max(0, int(self.available.get("CPU", 0.0))))
        demand = cpu_demand + n_pg + n_actors_waiting
        max_concurrent_startup = max(2, os.cpu_count() or 1)
        want = min(demand - idle - self._spawning,
                   self.config.max_workers - registered - self._spawning,
                   max_concurrent_startup - self._spawning)
        for _ in range(max(0, want)):
            self._spawning += 1
            self._spawn_worker_proc()

    def _spawn_worker_proc(self) -> None:
        env = dict(os.environ)
        # Workers must not steal the TPU from the driver: force CPU jax.
        env["JAX_PLATFORMS"] = "cpu"
        env.setdefault("XLA_FLAGS", "")
        env["RAY_TPU_SESSION"] = self.session
        # Propagate the driver's import path so functions/classes pickled
        # by reference (module-level defs in driver-side scripts) resolve
        # in workers — the minimal slice of the reference's runtime-env
        # working_dir propagation (reference:
        # python/ray/_private/runtime_env/working_dir.py capability).
        env["PYTHONPATH"] = os.pathsep.join(
            [p for p in sys.path if p] +
            [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p])
        logdir = os.path.join(self.session_dir, "logs")
        idx = len(self._worker_procs)
        out = open(os.path.join(logdir, f"worker-{idx}.out"), "ab", buffering=0)
        err = open(os.path.join(logdir, f"worker-{idx}.err"), "ab", buffering=0)
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.core.worker",
             "--address", self.address, "--session", self.session],
            env=env, stdout=out, stderr=err, start_new_session=True)
        self._worker_procs.append(proc)

    # -- actors

    def _h_create_actor(self, rec, m):
        spec = m["spec"]
        actor_id = ActorID(spec["actor_id"])
        name = spec.get("name") or ""
        ns = spec.get("namespace") or "default"
        if name:
            key = (ns, name)
            if key in self.named_actors and \
                    self.actors[self.named_actors[key]].state != "dead":
                if spec.get("get_if_exists"):
                    self._reply(rec, m["reqid"],
                                actor_id=self.named_actors[key].binary(),
                                existing=True)
                    return
                self._reply(rec, m["reqid"],
                            error=f"Actor name '{name}' already taken in "
                                  f"namespace '{ns}'")
                return
            self.named_actors[key] = actor_id
        if not self._feasible(spec):
            self.named_actors.pop((ns, name), None) if name else None
            self._reply(rec, m["reqid"],
                        error=f"Infeasible actor resource demand: "
                              f"{self._demand(spec)} on {self.total_resources}")
            return
        ar = ActorRec(actor_id=actor_id, spec=spec, name=name, namespace=ns,
                      restarts_left=spec.get("max_restarts", 0),
                      max_concurrency=spec.get("max_concurrency", 1))
        self.actors[actor_id] = ar
        self._reply(rec, m["reqid"], actor_id=actor_id.binary())
        self._place_actor(ar)

    def _place_actor(self, ar: ActorRec) -> None:
        needs_tpu = bool(ar.spec.get("num_tpus"))
        w = self._find_idle_worker(tpu=needs_tpu)
        if w is None:
            self._maybe_spawn_worker(tpu=needs_tpu)
            self.post_later(0.05, lambda: self._place_actor_if_pending(ar))
            return
        if not self._try_acquire(ar.spec):
            self.post_later(0.05, lambda: self._place_actor_if_pending(ar))
            return
        if not w.tpu:
            # CPU actors get a dedicated worker process (reference: one
            # worker per actor); the in-process TPU executor is shared —
            # it hosts all TPU actors and tasks in the driver.
            w.dedicated_actor = ar.actor_id
            w.state = "busy"
        ar.conn_id = w.conn_id
        self._push(w, {"t": "create_actor_exec", "spec": ar.spec})

    def _place_actor_if_pending(self, ar: ActorRec) -> None:
        if ar.state in ("pending", "restarting") and ar.conn_id is None:
            self._place_actor(ar)

    def _h_actor_created(self, rec, m):
        ar = self.actors.get(ActorID(m["actor_id"]))
        if ar is None:
            return
        if m.get("error"):
            ar.state = "dead"
            ar.death_cause = m["error"]
            self._fail_actor_queue(ar, m["error"])
            if rec.dedicated_actor == ar.actor_id:
                rec.dedicated_actor = None
                rec.state = "idle"
            ar.conn_id = None
            self._return_resources(ar.spec)
        else:
            ar.state = "alive"
            self._publish("actor_state",
                          {"actor_id": ar.actor_id.hex(), "state": "alive"})
            self._dispatch_actor_queue(ar)

    def _h_submit_actor_task(self, rec, m):
        spec = m["spec"]
        actor_id = ActorID(spec["actor_id"])
        ar = self.actors.get(actor_id)
        for b in spec["return_ids"]:
            self.objects.setdefault(ObjectID(b), ObjInfo())
        self.tasks[spec["task_id"]] = TaskRec(spec=spec)
        self._record_event(spec, "PENDING")
        if ar is None or ar.state == "dead":
            cause = ar.death_cause if ar else "actor not found"
            self._fail_task(spec, f"Actor is dead: {cause}")
            return
        ar.queue.append(spec)
        self._dispatch_actor_queue(ar)

    def _dispatch_actor_queue(self, ar: ActorRec) -> None:
        if ar.state != "alive" or ar.conn_id is None:
            return
        w = self.clients.get(ar.conn_id)
        if w is None:
            return
        while ar.queue and ar.inflight < ar.max_concurrency:
            spec = ar.queue.popleft()
            if not self._args_ready(spec):
                # actors preserve submission order: put back and stop
                ar.queue.appendleft(spec)
                self._wait_args_then(spec, lambda: self._dispatch_actor_queue(ar))
                return
            ar.running[spec["task_id"]] = spec
            for b in spec.get("arg_ids", []):
                self.store.pin(ObjectID(b))
            tr = self.tasks.get(spec["task_id"])
            if tr is not None:
                tr.state = "running"
                tr.started_at = time.time()
                tr.worker = w.conn_id
            self._record_event(spec, "RUNNING")
            self._push(w, {"t": "execute_actor", "spec": spec})

    def _wait_args_then(self, spec, cb) -> None:
        remaining = [ObjectID(b) for b in spec.get("arg_ids", [])
                     if self.objects.get(ObjectID(b), ObjInfo()).state == "pending"]
        if not remaining:
            cb()
            return
        # Poll via the event loop until the dependency lands (v1; the
        # reference stages deps through the DependencyManager).
        self.post_later(0.02, lambda: self._wait_args_then(spec, cb))

    def _fail_actor_queue(self, ar: ActorRec, error: str) -> None:
        while ar.queue:
            self._fail_task(ar.queue.popleft(), f"Actor died: {error}")

    def _h_kill_actor(self, rec, m):
        actor_id = ActorID(m["actor_id"])
        ar = self.actors.get(actor_id)
        if ar is None:
            if "reqid" in m:
                self._reply(rec, m["reqid"], ok=False)
            return
        no_restart = m.get("no_restart", True)
        if no_restart:
            ar.restarts_left = 0
        w = self.clients.get(ar.conn_id) if ar.conn_id is not None else None
        if w is not None and not w.tpu:
            self._push(w, {"t": "exit"})
        elif w is not None:
            # shared in-process TPU executor: destroy only this actor's
            # instance, keep the executor alive for other work
            self._push(w, {"t": "destroy_actor",
                           "actor_id": actor_id.binary()})
            self._mark_actor_dead(ar, "killed")
        else:
            self._mark_actor_dead(ar, "killed")
        if "reqid" in m:
            self._reply(rec, m["reqid"], ok=True)

    def _mark_actor_dead(self, ar: ActorRec, cause: str) -> None:
        if ar.state == "dead":
            return
        ar.state = "dead"
        ar.death_cause = cause
        ar.conn_id = None
        for spec in list(ar.running.values()):
            self._fail_task(spec, f"Actor died: {cause}")
        ar.running.clear()
        self._fail_actor_queue(ar, cause)
        self._return_resources(ar.spec)
        self._publish("actor_state", {"actor_id": ar.actor_id.hex(),
                                      "state": "dead"})

    def _h_get_named_actor(self, rec, m):
        key = (m.get("namespace") or "default", m["name"])
        aid = self.named_actors.get(key)
        if aid is None or self.actors[aid].state == "dead":
            self._reply(rec, m["reqid"], error="not found")
        else:
            ar = self.actors[aid]
            self._reply(rec, m["reqid"], actor_id=aid.binary(), spec_meta={
                "methods": ar.spec.get("methods", []),
                "class_name": ar.spec.get("class_name", "")})

    def _h_list_named_actors(self, rec, m):
        out = [{"namespace": ns, "name": n}
               for (ns, n), aid in self.named_actors.items()
               if self.actors[aid].state != "dead"
               and (m.get("all_namespaces") or ns == (m.get("namespace")
                                                      or "default"))]
        self._reply(rec, m["reqid"], actors=out)

    # -- placement groups (single node: reservation only)

    def _h_create_pg(self, rec, m):
        pg_id = PlacementGroupID(m["pg_id"])
        bundles = m["bundles"]
        # single-node prepare+commit in one step
        total: dict[str, float] = {}
        for b in bundles:
            for k, v in b.items():
                total[k] = total.get(k, 0.0) + v
        if not all(self.available.get(k, 0.0) + 1e-9 >= v
                   for k, v in total.items()):
            self._reply(rec, m["reqid"],
                        error=f"Cannot reserve bundles {total}; "
                              f"available {self.available}")
            return
        for k, v in total.items():
            self.available[k] -= v
        self.pgs[pg_id] = PGRec(pg_id=pg_id, bundles=bundles,
                                strategy=m.get("strategy", "PACK"))
        for i, b in enumerate(bundles):
            self.pg_available[(pg_id.binary(), i)] = dict(b)
        self._reply(rec, m["reqid"], ok=True)

    def _h_remove_pg(self, rec, m):
        pg_id = PlacementGroupID(m["pg_id"])
        pg = self.pgs.pop(pg_id, None)
        if pg is not None:
            for i, b in enumerate(pg.bundles):
                self.pg_available.pop((pg_id.binary(), i), None)
                for k, v in b.items():
                    self.available[k] = self.available.get(k, 0.0) + v
        if "reqid" in m:
            self._reply(rec, m["reqid"], ok=True)

    # -- kv / pubsub

    def _h_kv_put(self, rec, m):
        key = (m.get("namespace") or "default", m["key"])
        if m.get("overwrite", True) or key not in self.kv:
            self.kv[key] = m["value"]
            added = True
        else:
            added = False
        if "reqid" in m:
            self._reply(rec, m["reqid"], added=added)

    def _h_kv_get(self, rec, m):
        self._reply(rec, m["reqid"],
                    value=self.kv.get((m.get("namespace") or "default",
                                       m["key"])))

    def _h_kv_del(self, rec, m):
        existed = self.kv.pop((m.get("namespace") or "default", m["key"]),
                              None) is not None
        if "reqid" in m:
            self._reply(rec, m["reqid"], deleted=existed)

    def _h_kv_keys(self, rec, m):
        ns = m.get("namespace") or "default"
        prefix = m.get("prefix", b"")
        self._reply(rec, m["reqid"],
                    keys=[k for (n, k) in self.kv if n == ns
                          and k.startswith(prefix)])

    def _h_subscribe(self, rec, m):
        self.pubsub.setdefault(m["channel"], set()).add(rec.conn_id)
        if "reqid" in m:
            self._reply(rec, m["reqid"], ok=True)

    def _h_publish(self, rec, m):
        self._publish(m["channel"], m["data"])
        if "reqid" in m:
            self._reply(rec, m["reqid"], ok=True)

    def _publish(self, channel: str, data: Any) -> None:
        for conn_id in list(self.pubsub.get(channel, ())):
            w = self.clients.get(conn_id)
            if w is not None:
                self._push(w, {"t": "pub", "channel": channel, "data": data})

    # -- state API

    def _record_event(self, spec: dict, state: str) -> None:
        self.task_events.append({
            "task_id": spec["task_id"].hex() if isinstance(spec["task_id"], bytes)
            else spec["task_id"],
            "name": spec.get("name", ""),
            "state": state,
            "actor_id": spec.get("actor_id", b"").hex()
            if spec.get("actor_id") else None,
            "time": time.time(),
        })

    def _h_state(self, rec, m):
        what = m["what"]
        if what == "tasks":
            out = [{"task_id": tid.hex(), "name": tr.spec.get("name", ""),
                    "state": tr.state, "error": tr.error,
                    "submitted_at": tr.submitted_at,
                    "duration": (tr.finished_at - tr.started_at)
                    if tr.finished_at else None}
                   for tid, tr in self.tasks.items()]
        elif what == "actors":
            out = [{"actor_id": aid.hex(), "state": ar.state,
                    "name": ar.name, "namespace": ar.namespace,
                    "class_name": ar.spec.get("class_name", ""),
                    "pending_calls": len(ar.queue)}
                   for aid, ar in self.actors.items()]
        elif what == "objects":
            out = [{"object_id": oid.hex(), "state": info.state,
                    "loc": info.loc, "size": info.size}
                   for oid, info in self.objects.items()]
        elif what == "workers":
            out = [{"worker_id": c.worker_id, "kind": c.kind, "pid": c.pid,
                    "state": c.state, "tpu": c.tpu}
                   for c in self.clients.values()
                   if c.kind in ("worker", "tpu_executor")]
        elif what == "nodes":
            out = [{"node_id": self.node_id.hex(), "address": self.address,
                    "resources": self.total_resources,
                    "available": self.available, "alive": True}]
        elif what == "task_events":
            out = list(self.task_events)
        elif what == "resources":
            out = {"total": self.total_resources, "available": self.available}
        else:
            out = []
        self._reply(rec, m["reqid"], data=out)

    def _h_ping(self, rec, m):
        self._reply(rec, m["reqid"], ok=True, time=time.time())

    # -- disconnect handling

    def _drop_client(self, rec: ClientRec) -> None:
        if rec.closed:
            return
        rec.closed = True
        try:
            self.sel.unregister(rec.sock)
        except (KeyError, ValueError):
            pass
        try:
            rec.sock.close()
        except OSError:
            pass
        self.clients.pop(rec.conn_id, None)
        for oid, _ts in rec.held_pins:
            self.store.unpin(oid)
        rec.held_pins.clear()
        # fail or retry the running task (reference: worker death →
        # owner retries, task_manager.h:406)
        if rec.current_task is not None:
            tr = self.tasks.get(rec.current_task)
            if tr is not None and tr.state == "running":
                if not tr.spec.get("_cpu_released"):
                    self._return_resources(tr.spec)
                tr.spec.pop("_cpu_released", None)
                if tr.retries_left > 0:
                    tr.retries_left -= 1
                    tr.state = "pending"
                    self._make_runnable(tr.spec)
                else:
                    self._fail_task(tr.spec,
                                    f"Worker died while running task "
                                    f"(pid={rec.pid})")
        conn_actors = [a for a in self.actors.values()
                       if a.conn_id == rec.conn_id and a.state != "dead"]
        for ar in conn_actors:
                self._return_resources(ar.spec)
                ar.conn_id = None
                # In-flight method calls die with the worker: fail them so
                # callers see an actor-death error instead of hanging
                # (reference: actor task fate on actor death,
                # direct_actor_task_submitter.h DisconnectActor).
                for spec in list(ar.running.values()):
                    self._fail_task(spec,
                                    f"Actor died while executing method "
                                    f"'{spec.get('method', '?')}' "
                                    f"(pid={rec.pid})")
                ar.running.clear()
                if ar.restarts_left != 0:
                    if ar.restarts_left > 0:
                        ar.restarts_left -= 1
                    ar.state = "restarting"
                    self._publish("actor_state", {"actor_id": ar.actor_id.hex(),
                                                  "state": "restarting"})
                    self._place_actor(ar)
                else:
                    ar.state = "dead"
                    ar.death_cause = f"worker process died (pid={rec.pid})"
                    self._publish("actor_state", {"actor_id": ar.actor_id.hex(),
                                                  "state": "dead"})
                    self._fail_actor_queue(ar, ar.death_cause)
        if rec.kind == "driver":
            # single-driver node: driver gone → shut down
            self._stop.set()
        self._schedule()


def main() -> None:
    import argparse
    parser = argparse.ArgumentParser(description="ray_tpu head node service")
    parser.add_argument("--port", type=int, default=6379)
    parser.add_argument("--session", default=None)
    parser.add_argument("--session-dir", default=None)
    parser.add_argument("--num-cpus", type=float, default=None)
    parser.add_argument("--num-tpus", type=float, default=None)
    args = parser.parse_args()
    import uuid
    session = args.session or uuid.uuid4().hex
    session_dir = args.session_dir or os.path.join(
        "/tmp/ray_tpu", f"session_{session[:8]}")
    svc = NodeService(RayTpuConfig(), session, session_dir, port=args.port,
                      num_cpus=args.num_cpus, num_tpus=args.num_tpus)
    print(f"ray_tpu node service listening on {svc.address} "
          f"(session {session})", flush=True)
    try:
        svc.run()
    except KeyboardInterrupt:
        svc.stop()


if __name__ == "__main__":
    main()
