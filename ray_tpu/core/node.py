"""Node service: the per-node daemon (raylet analogue).

Local half (reference: src/ray/raylet/node_manager.cc
HandleRequestWorkerLease:1822, worker_pool.h, local_task_manager.h):

  * task scheduling + worker pool
  * object directory + inline store + shm bookkeeping + spilling
    (reference: core_worker memory_store.h, plasma store.h,
    local_object_manager.h)
  * actor execution management, per-actor queues, local restart
  * placement-group bundle reservation (2PC participant)

Cluster half (active when ``head_address`` is set; reference splits this
between the raylet, the object manager, and the GCS client):

  * head channel: register, heartbeat, resource view sync
    (reference: ray_syncer.h:30)
  * task spillover / routing through the head when local resources
    can't satisfy demand (reference: cluster_task_manager.h:33)
  * chunked node-to-node object transfer over lazy peer connections
    (reference: object_manager.h:117 Push/Pull, object_manager.proto:61)
  * actor-task forwarding to the owning node, with head-side location
    lookup + caching (reference: direct_actor_task_submitter.h)
  * proxying of cluster-scope client requests (KV, pubsub, named actors,
    placement groups, functions) so drivers/workers only ever talk to
    their local node
  * node-death recovery: resubmit forwarded tasks whose returns were
    lost, fail in-flight calls to actors on dead nodes

Without a head this service runs standalone exactly as in round 1: the
single-node control plane fused into one loop.  Runs as a thread inside
the driver (default, ``ray_tpu.init()``) or standalone
(``python -m ray_tpu.core.node``).
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
import traceback
import pickle
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Optional

from ray_tpu._config import RayTpuConfig
from ray_tpu.core import fault_injection as _fi
from ray_tpu.core import flight_recorder as _fr
from ray_tpu.core import protocol
from ray_tpu.core.ids import ActorID, NodeID, ObjectID, PlacementGroupID
from ray_tpu.core.resources import bundle_total, covers
from ray_tpu.core.object_store import (NativeObjectStoreCore, ObjectExists,
                                       make_object_store_core)
from ray_tpu.core.service import (ClientRec, ClusterStoreMixin,
                                  EventLoopService)

# ---------------------------------------------------------------------------
# fork-server worker handle


class _ForkedProc:
    """Popen-shaped handle for a worker forked by the prefork template
    (core/prefork.py).  The template reaps exits, so liveness is probed
    with signal 0 rather than waitpid."""

    def __init__(self, pid: int):
        self.pid = pid
        self._rc: Optional[int] = None

    def poll(self) -> Optional[int]:
        if self._rc is None:
            try:
                os.kill(self.pid, 0)
            except (ProcessLookupError, PermissionError):
                self._rc = 0
        return self._rc

    def wait(self, timeout: Optional[float] = None) -> int:
        deadline = None if timeout is None else time.time() + timeout
        while self.poll() is None:
            if deadline is not None and time.time() > deadline:
                raise subprocess.TimeoutExpired("forked-worker", timeout)
            time.sleep(0.02)
        return self._rc

    def _signal(self, sig: int) -> None:
        try:
            os.kill(self.pid, sig)
        except (ProcessLookupError, PermissionError):
            pass

    def terminate(self) -> None:
        self._signal(signal.SIGTERM)

    def kill(self) -> None:
        self._signal(signal.SIGKILL)


class _PendingLaunch:
    """Popen-shaped placeholder guarding a container launch that has
    been SCHEDULED but not yet exec'd (e.g. chaos slow-spawn).  poll()
    reads in-flight until the register window expires, then done —
    re-arming retries for a launch that silently died."""

    def __init__(self, ttl_s: float):
        self._deadline = time.monotonic() + ttl_s
        self.pid = 0

    def poll(self) -> Optional[int]:
        return None if time.monotonic() < self._deadline else 0


# ---------------------------------------------------------------------------
# records


@dataclass
class ObjInfo:
    state: str = "pending"       # pending | ready | error
    loc: str = ""                # inline | shm | device
    data: Optional[bytes] = None  # inline payload (SerializedObject wire bytes)
    size: int = 0
    owner: str = ""
    is_error: bool = False
    # device-resident entries: conn_id of the process holding the HBM
    # buffers (core/device_objects.py); data holds the descriptor
    owner_conn: Optional[int] = None
    loc_reported: bool = False   # location pushed to the head
    nested: tuple = ()           # ids this object's value embeds refs to
    wait_waiters: list = field(default_factory=list)
    # (node_hex, address) of the node that OWNS this object — the
    # submitter's node is the location authority and lineage holder
    # (reference: ownership model, core_worker.h / the owner_address
    # every ObjectReference carries)
    owner_node: tuple = ()


@dataclass
class OwnedRec:
    """Owner-side directory entry for one owned object (reference:
    ownership_based_object_directory.cc — the owner, not the GCS, is
    authoritative for locations of objects it owns)."""
    task_id: bytes = b""                       # producer (b"" for puts)
    locations: dict = field(default_factory=dict)   # node_hex -> address
    watchers: set = field(default_factory=set)      # (node_hex, address)


@dataclass
class TaskRec:
    spec: dict
    state: str = "pending"       # pending | running | forwarded | finished | failed
    worker: Optional[int] = None
    retries_left: int = 0
    submitted_at: float = field(default_factory=time.time)
    started_at: float = 0.0
    finished_at: float = 0.0
    error: str = ""


@dataclass
class ActorRec:
    actor_id: ActorID
    spec: dict                   # creation spec (reusable for restart)
    state: str = "pending"       # pending | alive | restarting | dead
    conn_id: Optional[int] = None
    name: str = ""
    namespace: str = ""
    restarts_left: int = 0
    seq: int = 0
    queue: deque = field(default_factory=deque)   # pending method-call specs
    running: dict = field(default_factory=dict)   # task_id -> in-flight spec
    max_concurrency: int = 1
    death_cause: str = ""

    @property
    def inflight(self) -> int:
        return len(self.running)


@dataclass
class PGRec:
    pg_id: PlacementGroupID
    bundles: list                # list[dict resource->qty]
    strategy: str
    state: str = "created"       # single-node: reserve succeeds or raises


def _wire_spec(spec: dict) -> dict:
    """Spec copy safe to ship to another service (drop node-local keys)."""
    return {k: v for k, v in spec.items()
            if not k.startswith("_") and k != "submitter"}


def _gil_free_copy(dst, src, size: int) -> None:
    """memcpy that RELEASES the GIL (ctypes foreign calls drop it):
    a multi-hundred-MiB memoryview slice-assign holds the GIL and
    stalls every other event loop thread in the process for its whole
    duration — broadcast copies serialized behind each other."""
    import ctypes
    try:
        dst_c = (ctypes.c_char * size).from_buffer(dst)
        src_mv = memoryview(src)
        if src_mv.readonly:
            src_c = bytes(src_mv[:size])    # rare: readonly source
        else:
            src_c = (ctypes.c_char * size).from_buffer(src_mv)
        ctypes.memmove(dst_c, src_c, size)
    except (TypeError, ValueError):
        dst[:size] = src[:size]


# Same-process node registry: virtual clusters (cluster_utils) run many
# NodeServices as threads of one process.  Object pulls between them can
# hand the bytes over with one memcpy instead of a socket stream — the
# same-host semantics the reference gets from one shared plasma store
# per machine (plasma store.h:55; workers on a host never stream to
# each other).  Real multi-host peers are never in this registry.
_LOCAL_NODES_BY_HEX: dict[str, "NodeService"] = {}


class NodeService(ClusterStoreMixin, EventLoopService):
    name = "node"

    def __init__(self, config: RayTpuConfig, session: str,
                 session_dir: str, listen_host: str = "127.0.0.1",
                 port: int = 0, num_cpus: Optional[float] = None,
                 num_tpus: Optional[float] = None,
                 resources: Optional[dict] = None,
                 head_address: Optional[str] = None,
                 stop_on_driver_exit: bool = True,
                 labels: Optional[dict] = None):
        super().__init__(listen_host, port)
        _fi.autoinstall_from_env()   # chaos plane in spawned node daemons
        self.config = config
        self.session = session
        self.session_dir = session_dir
        self.node_id = NodeID.from_random()
        _LOCAL_NODES_BY_HEX[self.node_id.hex()] = self
        self.stop_on_driver_exit = stop_on_driver_exit
        os.makedirs(os.path.join(session_dir, "logs"), exist_ok=True)
        # same-host workers connect over a unix socket (cheaper per
        # message than TCP loopback); falls back to the TCP address
        self.worker_address = self.address
        try:
            port = self.address.rsplit(":", 1)[1]
            self.worker_address = self.add_unix_listener(
                os.path.join(session_dir, f"node-{port}.sock"))
        except OSError:
            pass

        ncpu = num_cpus if num_cpus is not None else float(os.cpu_count() or 1)
        self.total_resources: dict[str, float] = {"CPU": ncpu}
        if num_tpus:
            self.total_resources["TPU"] = float(num_tpus)
            # advertise the generation so accelerator_type constraints
            # can pin placement (util/accelerators.accelerator_resource)
            try:
                from ray_tpu.util.accelerators import (
                    accelerator_resource, detect_tpu_type)
                tpu_type = detect_tpu_type()
                if tpu_type:
                    self.total_resources[
                        accelerator_resource(tpu_type)] = float(num_tpus)
            except Exception:   # noqa: BLE001 - detection is best-effort
                pass
        if resources:
            self.total_resources.update(resources)
        self.available = dict(self.total_resources)

        spill_dir = config.object_spilling_dir or os.path.join(session_dir, "spill")
        self.store = make_object_store_core(session,
                                            config.object_store_memory,
                                            spill_dir,
                                            spill_uri=config.object_spilling_uri)

        self.objects: dict[ObjectID, ObjInfo] = {}
        self.tasks: dict[bytes, TaskRec] = {}
        # Two-queue dispatch (reference: local_task_manager.h waiting →
        # dispatch queues): tasks wait on deps, then join a runnable FIFO
        # per executor class.
        self.runnable_cpu: deque[dict] = deque()
        self.runnable_zero: deque[dict] = deque()   # zero-demand specs
        self.runnable_tpu: deque[dict] = deque()
        # incremental aggregates over the runnable queues: admission and
        # spawn decisions run PER EVENT, so recomputing by iterating a
        # deep queue would be O(backlog) per task -> O(n^2) per burst
        self._queued_demand: dict[str, float] = {}
        self._queued_pg = 0
        self.dep_waiting: dict[ObjectID, list] = {}  # oid -> waiting specs
        self.actors: dict[ActorID, ActorRec] = {}
        self.named_actors: dict[tuple[str, str], ActorID] = {}
        self._actors_wanting_worker: deque = deque()
        self._init_stores()   # kv / pubsub / function store (mixin)
        self.pgs: dict[PlacementGroupID, PGRec] = {}
        self.pg_available: dict[tuple[bytes, int], dict] = {}  # (pg,bundle)->free
        self.task_events: deque = deque(maxlen=config.task_events_buffer_size)
        # bounded retention of finished TaskRecs: the state API wants
        # recent history, but an unbounded dict makes every scan over
        # self.tasks O(everything ever run)
        self._done_order: deque = deque()
        self._spawning = 0
        self._worker_procs: list = []   # Popen | _ForkedProc
        self._worker_log_by_pid: dict[int, tuple] = {}  # pid -> (out, err)
        # fork-server template (reference: worker_pool.h:352
        # PrestartWorkers amortization; here startup cost is paid once
        # in the template and workers fork in ~ms — core/prefork.py)
        self._prefork_proc: Optional[subprocess.Popen] = None
        self._prefork_conn = None       # control socket to the template
        self._prefork_buf = b""
        self._prefork_path = ""
        if config.prefork_workers:
            self._start_prefork_template()
        # containerized-worker spawns in flight: image -> Popen.  One
        # at a time per image (a container cold-start is seconds; a
        # burst would stampede podman), re-armed when the worker
        # registers or its launcher process dies.
        self._container_spawning: dict[str, Any] = {}
        # Batched-get bookkeeping: (conn_id, reqid) -> {ids, remaining}.
        self._multigets: dict[tuple, dict] = {}
        self._mg_by_oid: dict[ObjectID, set] = {}

        # ---- cluster plane state (dormant when head_address is None) ----
        self.head_address = head_address
        self.labels = dict(labels or {})
        self._owner_driver: Optional[int] = None
        self.head_conn: Optional[protocol.Connection] = None
        self.cluster_view: dict[str, dict] = {}
        self._head_seq = 0
        self._head_pending: dict[int, Any] = {}
        self._head_subs: set[str] = set()
        self._hb_inflight = False
        self._peer_conns: dict[str, protocol.Connection] = {}
        self._peer_connecting: dict[str, list] = {}   # node_hex -> [cb]
        # actor_id(bytes) -> ("alive", node_hex, address)
        self.actor_cache: dict[bytes, tuple] = {}
        self._awaiting_actor: dict[bytes, list] = {}   # aid -> queued specs
        # aid -> when its locate was orphaned by a head failover
        self._actor_wait_parked: dict[bytes, float] = {}
        self._pulls: dict[bytes, dict] = {}            # oid bytes -> state
        self._pull_attempts: dict[bytes, int] = {}
        self._out_transfers: dict[tuple, dict] = {}    # (conn_id, oid) -> st
        self._bcast_tail: dict[bytes, tuple] = {}      # ob -> (hex, addr)
        self._watched: set[bytes] = set()              # locate sent for oid
        self._fwd_tasks: dict[bytes, dict] = {}        # task_id -> fwd info
        self._fwd_by_oid: dict[bytes, bytes] = {}      # return oid -> task_id
        self._pg_prepared: dict[tuple, dict] = {}      # (pg,idx) -> bundle
        self._pg_bundles: dict[tuple, dict] = {}       # committed originals
        self._pending_local_pgs: dict[bytes, dict] = {}  # single-node queue
        self._device_pending_pulls: dict[bytes, list] = {}  # ob -> [(conn,m)]
        self._released_wait: set[ObjectID] = set()     # owner-released oids
        self._nested_count: dict[bytes, int] = {}      # id -> container holds
        # ---- ownership + lineage (reference: reference_count.h /
        # object_recovery_manager.h / ownership_based_object_directory.cc)
        self.owned: dict[bytes, OwnedRec] = {}         # oid -> directory rec
        self.lineage: dict[bytes, dict] = {}           # tid -> {spec,cost,live,recons}
        self._lineage_bytes = 0
        self._lineage_order: deque[bytes] = deque()
        self._owner_watch: dict[bytes, str] = {}       # oid -> owner hex asked

        # OOM protection (reference: memory_monitor.h + worker killing
        # policy; N15 MemoryMonitor slice)
        self.memory_monitor = None
        if config.memory_monitor_refresh_ms > 0:
            from ray_tpu.core.memory_monitor import MemoryMonitor
            self.memory_monitor = MemoryMonitor(
                config.memory_usage_threshold,
                config.memory_monitor_refresh_ms)
        self._oom_kills: dict[bytes, str] = {}     # task_id -> detail
        self.oom_kill_count = 0

        # per-iteration coalescing for head/peer channels: handlers emit
        # several small messages per task (location reports, owner
        # pushes, forwards); one batched send per loop pass replaces a
        # send (syscall or lane post + peer wakeup) per message
        self._head_out: list = []
        self._peer_out: dict[int, tuple] = {}   # id(conn) -> (conn, [msgs])

        self._last_hb = 0.0
        self._hb_period = config.heartbeat_period_ms / 1000.0
        # ticks must run at least as often as heartbeats are due
        self.tick_interval = min(self.tick_interval, self._hb_period)

        # flight recorder (core/flight_recorder.py): armed per process
        # by config/env; workers stamp data-driven off the spec instead
        if config.flight_recorder and _fr._active is None:
            _fr.enable()

        self.metrics_exporter = None
        if config.metrics_export_port:
            from ray_tpu.metrics import MetricsExporter, node_metrics_snapshot
            self.metrics_exporter = MetricsExporter(
                lambda: node_metrics_snapshot(self),
                port=config.metrics_export_port)

        if head_address:
            self._connect_head()

    # ------------------------------------------------------------------ run

    def on_tick(self) -> None:
        # periodic re-dispatch: recovers from missed wakeups and
        # re-evaluates worker-pool health (dead spawns etc.)
        self._audit_worker_pool()
        self._schedule()
        self._rebalance()
        self._expire_stale_pins()
        self._sweep_released()
        self._memory_check()
        self._expire_parked_actor_waits()
        self._heartbeat()

    def _expire_parked_actor_waits(self) -> None:
        """Actor-bound tasks parked through a head failover fail once
        the grace window runs out with the head still gone."""
        if not self._actor_wait_parked or self.head_conn is not None:
            return
        grace = self.config.actor_locate_failover_grace_s
        cutoff = time.monotonic() - grace
        for ab, since in list(self._actor_wait_parked.items()):
            if since < cutoff:
                self._actor_wait_parked.pop(ab, None)
                for spec in self._awaiting_actor.pop(ab, []):
                    self._fail_task(
                        spec, "Actor location unknown: head connection "
                              f"lost and not recovered within {grace:.0f}s")

    def _memory_check(self) -> None:
        """OOM protection: when node memory crosses the threshold, kill
        one running worker chosen by the group-by-owner policy; the task
        retries or fails with OutOfMemoryError (reference:
        memory_monitor.h:52, worker_killing_policy_group_by_owner.h:85)."""
        mm = self.memory_monitor
        if mm is None or not mm.due():
            return
        over = mm.over_threshold()
        if over is None:
            return
        used, total = over
        from ray_tpu.core.memory_monitor import pick_victim
        cands = []
        for rec in self.clients.values():
            if (rec.kind != "worker" or rec.dedicated_actor is not None
                    or rec.state != "busy" or rec.current_task is None
                    or not rec.pid):
                continue
            tr = self.tasks.get(rec.current_task)
            if tr is not None and tr.state == "running":
                cands.append((rec, tr))
        victim = pick_victim(cands)
        if victim is None:
            return
        rec, tr = victim
        detail = (f"task used node memory past the threshold "
                  f"({used / (1 << 20):.0f}MiB / {total / (1 << 20):.0f}"
                  f"MiB >= {mm.threshold:.2f}); worker pid={rec.pid} "
                  f"killed to protect the node")
        try:
            os.kill(rec.pid, signal.SIGKILL)
        except OSError:
            return   # already gone: no kill happened, record nothing
        self._oom_kills[rec.current_task] = detail
        self.oom_kill_count += 1
        self._record_event(tr.spec, "OOM_KILLED", worker=rec.conn_id)
        sys.stderr.write(f"[node] OOM: killing worker pid={rec.pid} "
                         f"(task {rec.current_task.hex()[:12]}, "
                         f"{used}/{total} bytes)\n")

    def _rebalance(self) -> None:
        """Queued work meets new capacity: spillover decisions are made
        at enqueue time, so when another node gains availability LATER
        (autoscaler launch, task completion elsewhere), re-route queue
        heads this node can't start now (reference: the cluster
        scheduler re-evaluates pending queues on resource updates,
        cluster_task_manager.cc ScheduleAndDispatchTasks)."""
        if self.head_conn is None:
            return
        moved = 0
        for q in (self.runnable_cpu, self.runnable_tpu):
            while q and moved < 8:
                spec = q[0]
                if spec.get("placement_group"):
                    break   # FIFO: don't reorder past an unmovable head
                demand = self._demand(spec)
                if all(self.available.get(k, 0.0) + 1e-9 >= v
                       for k, v in demand.items()):
                    break   # dispatches here as soon as a worker frees
                if not self._cluster_has_capacity(spec):
                    break
                # _routed (head-parked) specs move too: during a burst
                # the head parks work on saturated nodes; when capacity
                # appears LATER (autoscaler launch, drain elsewhere) the
                # parked backlog must chase it.  No ping-pong: we only
                # re-forward when the view shows another node free NOW,
                # and the head ranks available-now targets first.
                self._queue_pop(q)
                self._forward_task(spec)
                moved += 1

    def _cleanup(self) -> None:
        from ray_tpu.core import local_lane
        local_lane.unregister_service(self)
        _LOCAL_NODES_BY_HEX.pop(self.node_id.hex(), None)
        for rec in list(self.clients.values()):
            try:
                self._push(rec, {"t": "shutdown"})
                self._flush(rec)
            except Exception:
                pass
        # closing the control connection tells the template to exit
        if self._prefork_conn is not None:
            try:
                self._prefork_conn.close()
            except OSError:
                pass
            self._prefork_conn = None
        deadline = time.time() + 2.0
        for p in self._worker_procs:
            try:
                p.wait(timeout=max(0.0, deadline - time.time()))
            except subprocess.TimeoutExpired:
                p.kill()
        if self._prefork_proc is not None:
            try:
                self._prefork_proc.wait(timeout=max(0.0,
                                                    deadline - time.time()))
            except subprocess.TimeoutExpired:
                self._prefork_proc.kill()
        for rec in list(self.clients.values()):
            try:
                rec.sock.close()
            except OSError:
                pass
            if rec.lane is not None:
                rec.lane._mark_closed()
        self.listener.close()
        self._close_extra_listeners()
        self.sel.close()
        for conn in self._peer_conns.values():
            try:
                conn.close()
            except Exception:
                pass
        if self.head_conn is not None:
            try:
                self.head_conn.close()
            except Exception:
                pass
        if self.metrics_exporter is not None:
            self.metrics_exporter.stop()
        self.store.shutdown()

    # ------------------------------------------------------- head channel

    def _connect_head(self) -> None:
        conn = protocol.connect(
            self.head_address, remote=True,
            label=(f"node:{self.node_id.hex()[:8]}", "head"))
        conn.send({"t": "register_node", "reqid": 0,
                   "node_id": self.node_id.hex(), "address": self.address,
                   "resources": self.total_resources,
                   "available": dict(self.available),
                   "labels": self.labels})
        reply = conn.recv(timeout=30.0)
        if reply.get("error"):
            raise RuntimeError(f"head registration failed: {reply['error']}")
        self.cluster_view = reply.get("view", {})
        # the head's session differs from this node's DERIVED session
        # (per-node shm arenas) — replica validation uses the head's
        self.head_session = reply.get("session", "")
        self.head_conn = conn
        self._start_head_recv(conn)

    def _start_head_recv(self, conn) -> None:
        """Route head pushes onto the event loop.  A lane connection
        (same-process head) delivers straight from the head's loop —
        no dedicated recv thread, one wakeup fewer per message."""
        from ray_tpu.core.local_lane import LaneConnection
        if isinstance(conn, LaneConnection):
            conn.on_close = lambda: self.post(self._head_lost)
            conn.set_deliver(
                lambda m: self.post(lambda m=m: self._on_head_msg(m)))
            return
        t = threading.Thread(target=self._head_recv_loop, daemon=True,
                             name="raytpu-node-head")
        t.start()

    def _head_recv_loop(self) -> None:
        while not self._stop.is_set():
            try:
                msg = self.head_conn.recv()
            except protocol.ConnectionClosed:
                self.post(self._head_lost)
                return
            except Exception:
                continue
            self.post(lambda m=msg: self._on_head_msg(m))

    def _head_lost(self) -> None:
        # Head death orphans the cluster plane; keep serving local work
        # (reference: raylets survive transient GCS outages), fail
        # everything mid-flight through the head so callers see errors
        # instead of hanging forever, and keep trying to REJOIN — a
        # persistent head restarting on the same address picks the
        # cluster back up (reference: GCS-FT reconnection,
        # gcs_client reconnection loop).
        if self.head_conn is None:
            return
        sys.stderr.write("[node] lost connection to head service\n")
        self.head_conn = None
        self._hb_inflight = False
        pending = list(self._head_pending.values())
        self._head_pending.clear()
        for cb in pending:
            try:
                cb({"error": "head connection lost"})
            except Exception:
                sys.stderr.write("[node] head-lost callback failed:\n"
                                 + traceback.format_exc())
        # actor-bound tasks whose locate was cut off stay PARKED for the
        # failover grace window (config actor_locate_failover_grace_s):
        # failing them instantly turned every head failover into
        # client-visible actor errors.  _head_rejoined re-issues the
        # locates; on_tick expires the ones the grace ran out on.
        now = time.monotonic()
        for ab in self._awaiting_actor:
            self._actor_wait_parked.setdefault(ab, now)
        self.post_later(1.0, self._try_reconnect_head)

    def _try_reconnect_head(self) -> None:
        if self.head_conn is not None or self._stop.is_set():
            return

        def work():
            try:
                conn = protocol.connect(
                    self.head_address, timeout=3.0, remote=True,
                    label=(f"node:{self.node_id.hex()[:8]}", "head"))
                conn.send({"t": "register_node", "reqid": 0,
                           "node_id": self.node_id.hex(),
                           "address": self.address,
                           "resources": self.total_resources,
                           "available": dict(self.available),
                           "labels": self.labels})
                reply = conn.recv(timeout=10.0)
                if reply.get("error"):
                    raise RuntimeError(reply["error"])
            except Exception:
                self.post_later(2.0, self._try_reconnect_head)
                return
            self.post(lambda: self._head_rejoined(conn, reply))
        threading.Thread(target=work, daemon=True,
                         name="raytpu-head-reconnect").start()

    def _head_rejoined(self, conn: protocol.Connection,
                       reply: dict) -> None:
        if self.head_conn is not None:
            try:
                conn.close()
            except Exception:
                pass
            return
        sys.stderr.write("[node] rejoined head service\n")
        self.head_conn = conn
        self.cluster_view = reply.get("view", {})
        self.head_session = reply.get("session",
                                      getattr(self, "head_session", ""))
        self._start_head_recv(conn)
        try:
            # re-establish cluster-visible state: subscriptions, object
            # locations, actor liveness (a restarted head restored its
            # durable directory but not this live state)
            for ch in self._head_subs:
                conn.send({"t": "subscribe", "channel": ch})
            adds = []
            for oid, info in self.objects.items():
                if info.state in ("ready", "error"):
                    info.loc_reported = True
                    adds.append(oid.binary())
            if adds:
                conn.send({"t": "report_locations", "adds": adds})
            for ar in self.actors.values():
                if ar.state != "dead":
                    self._report_actor_state(ar)
            # re-ask for every actor whose locate the failover orphaned;
            # the parked specs resume the moment the new head answers
            for ab in list(self._awaiting_actor):
                self._head_rpc(
                    {"t": "locate_actor", "actor_id": ab},
                    lambda reply, ab=ab: self._on_actor_located(ab, reply))
        except protocol.ConnectionClosed:
            self._head_lost()

    def _head_send(self, msg: dict) -> None:
        """Queue a head-bound message; the loop flushes the batch once
        per iteration (_flush_corked).  Send failures surface there and
        run the normal head-loss path."""
        if self.head_conn is None:
            return
        self._head_out.append(msg)

    def _conn_send(self, conn, msg: dict) -> None:
        """Queue a peer-bound message for the per-iteration batch
        flush."""
        ent = self._peer_out.get(id(conn))
        if ent is None:
            self._peer_out[id(conn)] = (conn, [msg])
        else:
            ent[1].append(msg)

    def _flush_corked(self) -> None:
        if self._head_out:
            out, self._head_out = self._head_out, []
            conn = self.head_conn
            if conn is not None:
                try:
                    if len(out) == 1:
                        conn.send(out[0])
                    else:
                        conn.send_batch(out)
                except protocol.ConnectionClosed:
                    self._head_lost()
        if self._peer_out:
            batches, self._peer_out = self._peer_out, {}
            for conn, msgs in batches.values():
                try:
                    if len(msgs) == 1:
                        conn.send(msgs[0])
                    else:
                        conn.send_batch(msgs)
                except (protocol.ConnectionClosed, OSError):
                    pass   # peer drop is handled by its recv/on_close path
        super()._flush_corked()

    def _head_rpc(self, msg: dict, cb=None) -> None:
        if self.head_conn is None:
            if cb is not None:
                cb({"error": "no head connection"})
            return
        if cb is not None:
            self._head_seq += 1
            msg["reqid"] = self._head_seq
            self._head_pending[self._head_seq] = cb
        self._head_send(msg)

    def _on_head_msg(self, m: dict) -> None:
        if m.get("t") == "reply":
            cb = self._head_pending.pop(m.get("reqid"), None)
            if cb is not None:
                try:
                    cb(m)
                except Exception:
                    sys.stderr.write("[node] head rpc callback failed:\n"
                                     + traceback.format_exc())
            return
        handler = getattr(self, "_hh_" + m["t"], None)
        if handler is None:
            return
        try:
            handler(m)
        except Exception:
            sys.stderr.write(f"[node] head push {m['t']} failed:\n"
                             + traceback.format_exc())

    def _head_reply(self, reqid: int, **kw) -> None:
        kw["t"] = "reply"
        kw["reqid"] = reqid
        self._head_send(kw)

    def _heartbeat(self) -> None:
        if self.head_conn is None or self._hb_inflight:
            return
        now = time.monotonic()
        if now - self._last_hb < self._hb_period:
            return
        self._last_hb = now
        self._hb_inflight = True

        def cb(reply):
            self._hb_inflight = False
            if not reply.get("error"):
                self.cluster_view = reply.get("view", self.cluster_view)
        queued = {k: v for k, v in self._queued_demand.items()
                  if v > 1e-9}
        self._head_rpc({"t": "heartbeat",
                        "available": self._projected_available(),
                        "total": self.total_resources,
                        "queued": queued}, cb)

    # -------------------------------------------------------- registration

    def _h_register(self, rec, m):
        rec.kind = m["kind"]
        rec.worker_id = m.get("worker_id", "")
        rec.pid = m.get("pid", 0)
        rec.tpu = bool(m.get("tpu", False))
        rec.node_hex = m.get("node_hex", "")
        rec.container_image = m.get("container_image", "")
        if rec.kind == "driver" and self._owner_driver is None:
            # the FIRST driver owns this node's lifetime; later drivers
            # (job entrypoints, attached shells) come and go freely
            self._owner_driver = rec.conn_id
        if rec.kind in ("worker", "tpu_executor"):
            if rec.container_image:
                # container launches track per-image (_container_
                # spawning), never the host _spawning counter — a
                # decrement here would mark an unrelated in-flight host
                # spawn as done
                self._container_spawning.pop(rec.container_image, None)
            else:
                self._spawning = max(0, self._spawning - 1)
        self._reply(rec, m["reqid"], session=self.session,
                    node_id=self.node_id.hex(), address=self.address,
                    config=self.config.to_dict(),
                    native_store=isinstance(self.store,
                                            NativeObjectStoreCore))
        while self._actors_wanting_worker:
            ar = self._actors_wanting_worker.popleft()
            if ar.state in ("pending", "restarting") and ar.conn_id is None:
                self._place_actor(ar)
                break   # one new worker hosts one actor
        self._schedule()

    # -- objects

    def _h_put_inline(self, rec, m):
        oid = ObjectID(m["object_id"])
        info = self.objects.setdefault(oid, ObjInfo())
        info.state = "error" if m.get("is_error") else "ready"
        info.loc = "inline"
        info.data = m["data"]
        info.size = len(m["data"])
        # ownership set at submit time wins (the submitter owns task
        # returns, even when an executor stores them)
        info.owner = info.owner or m.get("owner", rec.worker_id)
        info.is_error = bool(m.get("is_error"))
        if self.head_conn is not None and not info.owner_node:
            # first stored here with no prior claim: this node owns it
            # (ray.put objects — the putter's node is the authority)
            info.owner_node = (self.node_id.hex(), self.address)
        self._track_nested(info, m.get("nested_refs"))
        self._resolve_waiters(oid, info)
        if "reqid" in m:
            self._reply(rec, m["reqid"], ok=True)

    def _h_register_object(self, rec, m):
        oid = ObjectID(m["object_id"])
        info = self.objects.setdefault(oid, ObjInfo())
        info.state = "ready"
        info.loc = "shm"
        info.size = m["size"]
        info.owner = info.owner or m.get("owner", rec.worker_id)
        if self.head_conn is not None and not info.owner_node:
            info.owner_node = (self.node_id.hex(), self.address)
        self._track_nested(info, m.get("nested_refs"))
        self.store.register(oid, m["size"])
        self._resolve_waiters(oid, info)
        if "reqid" in m:
            self._reply(rec, m["reqid"], ok=True)

    def _h_get_objects(self, rec, m):
        """Batched blocking get: reply once ALL requested objects resolve."""
        ids = [ObjectID(b) for b in m["object_ids"]]
        for o in ids:
            info = self.objects.setdefault(o, ObjInfo())
            if (info.loc == "device" and info.state == "ready"
                    and info.owner_conn != rec.conn_id):
                # another process wants a device-resident object: ask the
                # owner to spill it to the host store once (materialize-
                # on-demand), then this get resolves like any other
                self._request_materialize(o, info)
        pending = [o for o in ids
                   if self.objects[o].state == "pending"]
        if not pending:
            self._reply_batch(rec, m["reqid"], ids)
            return
        key = (rec.conn_id, m["reqid"])
        self._multigets[key] = {"ids": ids, "remaining": set(pending)}
        for o in pending:
            self._mg_by_oid.setdefault(o, set()).add(key)
        self._ensure_remote_watch([o for o in pending
                                   if self.objects[o].loc != "device"])
        if rec.state == "busy":
            rec.state = "blocked"
            self._release_task_cpu(rec)
            self._schedule()

    # -- device-resident objects (core/device_objects.py) -------------------

    def _h_put_device(self, rec, m):
        oid = ObjectID(m["object_id"])
        info = self.objects.setdefault(oid, ObjInfo())
        info.state = "ready"
        info.loc = "device"
        info.data = m["descriptor"]
        info.size = m.get("size", 0)
        info.owner = info.owner or m.get("owner", rec.worker_id)
        info.owner_conn = rec.conn_id
        if self.head_conn is not None and not info.owner_node:
            info.owner_node = (self.node_id.hex(), self.address)
        self._track_nested(info, m.get("nested_refs"))
        self._resolve_waiters(oid, info)

    def _h_materialize_failed(self, rec, m):
        oid = ObjectID(m["object_id"])
        info = self.objects.get(oid)
        if (info is not None and info.state == "pending"
                and info.loc == "device"):
            self._seal_error_object(oid, RuntimeError(
                f"device object materialization failed: {m.get('error')}"))

    def _request_materialize(self, oid: ObjectID, info: ObjInfo) -> None:
        owner = self.clients.get(info.owner_conn)
        if owner is None:
            self._device_owner_lost(oid, info)
            return
        info.state = "pending"
        self._push(owner, {"t": "materialize_object",
                           "object_id": oid.binary()})

    def _device_owner_lost(self, oid: ObjectID, info: ObjInfo) -> None:
        """The process holding a device entry's HBM buffers died: the
        value is gone.  Reconstruction via lineage applies exactly as for
        any lost object; without lineage the get errors."""
        info.loc = ""
        info.data = None
        info.owner_conn = None
        info.state = "pending"
        if not self._try_reconstruct_device(oid):
            self._seal_error_object(
                oid, RuntimeError(
                    "owner process of device-resident object died"))

    def _try_reconstruct_device(self, oid: ObjectID) -> bool:
        rec_ = self.owned.get(oid.binary())
        if rec_ is not None and rec_.task_id:
            return self._reconstruct(rec_.task_id)
        return False

    def _reply_batch(self, rec, reqid, ids):
        results = []
        for oid in ids:
            info = self.objects[oid]
            if info.loc == "device":
                # only the owner reaches here with a device loc (others
                # were routed through materialization in _h_get_objects)
                results.append({"loc": "device_local", "data": info.data,
                                "is_error": False})
            elif info.loc == "shm":
                # Pin FIRST, then restore: the pin must already protect
                # the object when a later restore in this same batch (or
                # restore's own capacity-balancing pass) evicts — the
                # reply promises a mapped segment (reference: plasma pins
                # objects for the duration of a Get).
                self.store.pin(oid)
                rec.held_pins.append((oid, time.monotonic()))
                if self.store.is_spilled(oid):
                    self.store.restore(oid)
                self.store.touch(oid)
                results.append({"loc": "shm", "size": info.size,
                                "is_error": info.is_error})
            else:
                results.append({"loc": "inline", "data": info.data,
                                "is_error": info.is_error})
        self._reply(rec, reqid, results=results)

    def _h_need_space(self, rec, m):
        # A client's arena allocation failed: spill unpinned objects
        # (reference: plasma create_request_queue.h queues client creates
        # until eviction frees memory — here the client blocks on this
        # request and retries).
        freed = self.store.evict_for(int(m["nbytes"]))
        self._reply(rec, m["reqid"], freed=freed)

    def _h_release_pins(self, rec, m):
        ids = {ObjectID(b) for b in m["object_ids"]}
        kept = []
        for oid, ts in rec.held_pins:
            if oid in ids:
                ids.discard(oid)
                self.store.unpin(oid)
            else:
                kept.append((oid, ts))
        rec.held_pins[:] = kept

    def _expire_stale_pins(self) -> None:
        """Get-replies whose ack never arrived (client timeout/death race)
        must not pin objects forever."""
        cutoff = time.monotonic() - 120.0
        for rec in self.clients.values():
            if not rec.held_pins:
                continue
            kept = []
            for oid, ts in rec.held_pins:
                if ts < cutoff:
                    self.store.unpin(oid)
                else:
                    kept.append((oid, ts))
            rec.held_pins[:] = kept

    def _object_ready_hook(self, oid: ObjectID, info: ObjInfo) -> None:
        """Cluster bookkeeping when an object becomes ready/error here."""
        ob = oid.binary()
        if info.loc != "device":
            for conn_id, pm in self._device_pending_pulls.pop(ob, []):
                peer = self.clients.get(conn_id)
                if peer is not None:
                    self._h_pull_object(peer, pm)
        self._watched.discard(ob)
        self._pull_attempts.pop(ob, None)
        self._owner_watch.pop(ob, None)
        if self.head_conn is not None and not info.loc_reported:
            info.loc_reported = True
            self._head_send({"t": "report_locations", "adds": [ob]})
        if self.head_conn is not None and info.owner_node:
            # tell the object's OWNER a copy lives here — the owner, not
            # the head, serves location queries for owned objects
            if info.owner_node[0] == self.node_id.hex():
                self._owner_add_location(ob, self.node_id.hex(),
                                         self.address)
            elif info.loc == "inline" and info.data is not None:
                # inline result of forwarded work: ship the VALUE to the
                # owner directly — a location report would cost the owner
                # a locate + pull round trip for ~bytes of payload
                # (reference contrast: small returns ride the
                # PushTaskReply inline, core_worker.cc:2528)
                self._owner_push(
                    info.owner_node[0], info.owner_node[1],
                    {"t": "owner_object_value", "object_id": ob,
                     "data": info.data, "is_error": info.is_error,
                     "node": self.node_id.hex(), "address": self.address})
            else:
                self._owner_push(
                    info.owner_node[0], info.owner_node[1],
                    {"t": "owner_object_at", "object_id": ob,
                     "node": self.node_id.hex(), "address": self.address})
        tid = self._fwd_by_oid.pop(ob, None)
        if tid is not None:
            fw = self._fwd_tasks.get(tid)
            if fw is not None and not any(
                    b in self._fwd_by_oid for b in fw["spec"]["return_ids"]):
                self._fwd_tasks.pop(tid, None)
                tr = self.tasks.get(tid)
                if tr is not None and tr.state == "forwarded":
                    tr.state = "failed" if info.is_error else "finished"
                    tr.finished_at = time.time()
                    self._note_task_finished(tid)
                    self._release_arg_blob(fw["spec"])

    def _resolve_waiters(self, oid: ObjectID, info: ObjInfo) -> None:
        self._object_ready_hook(oid, info)
        for key in self._mg_by_oid.pop(oid, ()):
            mg = self._multigets.get(key)
            if mg is None:
                continue
            mg["remaining"].discard(oid)
            if not mg["remaining"]:
                del self._multigets[key]
                w = self.clients.get(key[0])
                if w is not None:
                    if w.state == "blocked":
                        w.state = "busy"
                    self._reply_batch(w, key[1], mg["ids"])
        for conn_id, reqid, ids, num_returns, deadline in list(info.wait_waiters):
            self._try_finish_wait(conn_id, reqid, ids, num_returns, deadline)
        info.wait_waiters.clear()
        # release tasks waiting on this dependency
        for spec in self.dep_waiting.pop(oid, ()):
            spec["_ndeps"] -= 1
            if spec["_ndeps"] == 0:
                self._make_runnable(spec)
        self._schedule()

    def _h_wait(self, rec, m):
        ids = [ObjectID(b) for b in m["object_ids"]]
        self._ensure_remote_watch(
            [o for o in ids
             if self.objects.setdefault(o, ObjInfo()).state == "pending"])
        self._try_finish_wait(rec.conn_id, m["reqid"], ids, m["num_returns"],
                              time.time() + m["timeout"] if m.get("timeout")
                              is not None else None, first=True)

    def _try_finish_wait(self, conn_id, reqid, ids, num_returns, deadline,
                         first=False):
        rec = self.clients.get(conn_id)
        if rec is None:
            return
        ready = [o for o in ids
                 if self.objects.get(o) is not None
                 and self.objects[o].state != "pending"]
        timed_out = deadline is not None and time.time() >= deadline
        if len(ready) >= num_returns or timed_out:
            if not timed_out:
                ready = ready[:num_returns]
            self._reply(rec, reqid, ready=[o.binary() for o in ready])
            return
        if first:
            for o in ids:
                info = self.objects.setdefault(o, ObjInfo())
                if info.state == "pending":
                    info.wait_waiters.append((conn_id, reqid, ids, num_returns,
                                              deadline))
            if deadline is not None:
                self.post_later(max(0.0, deadline - time.time()),
                                lambda: self._try_finish_wait(
                                    conn_id, reqid, ids, num_returns, deadline))

    def _seal_error_object(self, oid: ObjectID, exc: BaseException) -> None:
        """Make `oid` resolve to an error value and wake its waiters —
        the single encoder of error objects on this node."""
        from ray_tpu.core.serialization import SerializedObject
        info = self.objects.setdefault(oid, ObjInfo())
        info.state = "error"
        info.loc = "inline"
        info.data = SerializedObject(inband=pickle.dumps(exc)).to_bytes()
        info.is_error = True
        self._resolve_waiters(oid, info)

    def _track_nested(self, info: ObjInfo, nested) -> None:
        """Record ids embedded in this object's value so their storage
        outlives the owner's release while the container exists."""
        if not nested or info.nested:
            return   # guard against double-count on a retried put
        info.nested = tuple(nested)
        for nb in info.nested:
            self._nested_count[nb] = self._nested_count.get(nb, 0) + 1

    def _release_owned(self, ob: bytes) -> None:
        """Drop the ownership record and dereference its lineage entry
        (freed objects need no reconstruction path)."""
        orec = self.owned.pop(ob, None)
        if orec is None or not orec.task_id:
            return
        lin = self.lineage.get(orec.task_id)
        if lin is None:
            return
        lin["live"].discard(ob)
        if not lin["live"]:
            if lin["spec"] is not None:
                self._lineage_bytes -= lin["cost"]
            del self.lineage[orec.task_id]
            # compact the eviction queue occasionally: entries for
            # deleted lineage would otherwise accumulate forever
            if len(self._lineage_order) > 256 \
                    and len(self._lineage_order) > 4 * len(self.lineage):
                self._lineage_order = deque(
                    t for t in self._lineage_order if t in self.lineage)

    def _forget_object(self, oid: ObjectID) -> None:
        """Single removal point: drop the entry, its storage, and its
        holds on nested ids."""
        info = self.objects.pop(oid, None)
        self.store.delete(oid)
        ob = oid.binary()
        self._bcast_tail.pop(ob, None)
        if info is not None and info.owner_node \
                and info.owner_node[0] == self.node_id.hex():
            self._release_owned(ob)
        else:
            orec = self.owned.get(ob)
            if orec is not None:
                orec.locations.pop(self.node_id.hex(), None)
        if info is not None and info.nested:
            for nb in info.nested:
                c = self._nested_count.get(nb, 0) - 1
                if c > 0:
                    self._nested_count[nb] = c
                else:
                    self._nested_count.pop(nb, None)

    def _delete_local_object(self, oid: ObjectID) -> None:
        info = self.objects.get(oid)
        # capture BEFORE sealing: _seal_error_object rewrites loc to
        # "inline", which would skip the owner's HBM release below
        was_device = info is not None and info.loc == "device"
        device_owner = info.owner_conn if was_device else None
        if info is not None and (info.state == "pending"
                                 or oid in self._mg_by_oid
                                 or info.wait_waiters
                                 or oid in self.dep_waiting):
            # fail anyone blocked on it before it vanishes
            self._seal_error_object(
                oid, RuntimeError(f"Object {oid.hex()[:16]} was freed"))
        if was_device:
            # tell the owner process to release the HBM buffers
            owner = self.clients.get(device_owner)
            if owner is not None:
                self._push(owner, {"t": "drop_device_object",
                                   "object_id": oid.binary()})
        self._forget_object(oid)

    def _h_free_objects(self, rec, m):
        for b in m["object_ids"]:
            self._delete_local_object(ObjectID(b))
        if self.head_conn is not None:
            self._head_send({"t": "free_objects",
                             "object_ids": list(m["object_ids"])})
        if "reqid" in m:
            self._reply(rec, m["reqid"], ok=True)

    def _h_object_stats(self, rec, m):
        self._reply(rec, m["reqid"], stats=self.store.stats(),
                    num_objects=len(self.objects))

    # -- automatic object lifetime (owner-based release) --------------------

    def _h_release_refs(self, rec, m):
        """The owning process dropped its last local ref to these objects
        — reclaim their storage once nothing on this node still needs
        them (reference: reference_count.h owner-count-zero → delete;
        borrower chains are out of scope, so non-owner releases are
        ignored rather than trusted)."""
        for b in m["object_ids"]:
            oid = ObjectID(b)
            info = self.objects.get(oid)
            if info is None:
                continue
            if info.owner and info.owner != rec.worker_id:
                continue
            self._released_wait.add(oid)
        self._sweep_released()

    def _args_in_flight(self) -> set:
        """Object ids still referenced as args by queued or running work
        on this node — storage for these must survive the owner's
        release until the work completes."""
        s: set = set()
        for q in (self.runnable_cpu, self.runnable_tpu,
                  self.runnable_zero):
            for spec in q:
                s.update(spec.get("arg_ids", ()))
        for specs in self.dep_waiting.values():
            for spec in specs:
                s.update(spec.get("arg_ids", ()))
        for ar in self.actors.values():
            for spec in ar.queue:
                s.update(spec.get("arg_ids", ()))
            for spec in ar.running.values():
                s.update(spec.get("arg_ids", ()))
        # running (non-actor) work hangs off busy workers — iterating
        # clients is O(pool), where iterating self.tasks would be
        # O(task history) per release sweep
        for rec in self.clients.values():
            if rec.current_task is not None:
                tr = self.tasks.get(rec.current_task)
                if tr is not None:
                    s.update(tr.spec.get("arg_ids", ()))
        # forwarded work: the destination node still has to PULL these
        # args from us — our copy must outlive the forward
        for fw in self._fwd_tasks.values():
            s.update(fw["spec"].get("arg_ids", ()))
        for specs in self._awaiting_actor.values():
            for spec in specs:
                s.update(spec.get("arg_ids", ()))
        return s

    def _sweep_released(self) -> None:
        if not self._released_wait:
            return
        in_flight = self._args_in_flight()
        freed: list[bytes] = []
        for oid in list(self._released_wait):
            info = self.objects.get(oid)
            if info is None:
                self._released_wait.discard(oid)
                continue
            if info.state == "pending":
                continue   # producing task still running; re-checked later
            if oid.binary() in in_flight:
                continue
            if oid in self._mg_by_oid or info.wait_waiters:
                continue
            if self._nested_count.get(oid.binary(), 0) > 0:
                continue   # a stored container still embeds this ref
            if info.loc == "shm":
                e = self.store.entries.get(oid)
                if e is not None and e.pin_count > 0:
                    continue   # a get/transfer is mapping it right now
            self._released_wait.discard(oid)
            self._forget_object(oid)
            freed.append(oid.binary())
        if freed and self.head_conn is not None:
            # replicas pulled to other nodes die with the owner's copy
            self._head_send({"t": "free_objects", "object_ids": freed})

    # -- functions

    def _h_register_function(self, rec, m):
        self._store_function(m["function_id"], m["pickled"])
        if self.head_conn is not None:
            # cluster-wide export so any node's workers can fetch it
            self._head_send({"t": "register_function",
                             "function_id": m["function_id"],
                             "pickled": m["pickled"]})
        if "reqid" in m:
            self._reply(rec, m["reqid"], ok=True)

    def _h_fetch_function(self, rec, m):
        fid = m["function_id"]
        if fid in self.functions:
            self._reply(rec, m["reqid"], pickled=self.functions[fid])
            return
        first = fid not in self._fn_waiters
        self._fn_waiters.setdefault(fid, []).append((rec.conn_id, m["reqid"]))
        if first and self.head_conn is not None:
            # the head parks the fetch until some node registers the
            # function (functions are exported once, cluster-wide)
            def cb(reply):
                if reply.get("pickled"):
                    self._store_function(fid, reply["pickled"])
                elif reply.get("error"):
                    # head gone: fail waiters instead of hanging workers
                    for conn_id, reqid in self._fn_waiters.pop(fid, []):
                        w = self.clients.get(conn_id)
                        if w is not None:
                            self._reply(w, reqid,
                                        error="function fetch failed: "
                                              f"{reply['error']}")
            self._head_rpc({"t": "fetch_function", "function_id": fid}, cb)

    # -- tasks

    def _h_submit_task(self, rec, m):
        spec = m["spec"]
        spec["submitter"] = rec.conn_id
        self._admit_task(spec)
        if "reqid" in m:
            self._reply(rec, m["reqid"], ok=True)

    def _admit_task(self, spec: dict) -> None:
        tr = TaskRec(spec=spec, retries_left=spec.get("max_retries", 0))
        self.tasks[spec["task_id"]] = tr
        if _fr._active is not None:
            _fr._active.start_or_stamp(spec, "node_recv")
        if self.head_conn is not None and not spec.get("owner_node"):
            # first admission on the submitter's node: WE own the returns
            spec["owner_node"] = (self.node_id.hex(), self.address)
            if spec.get("max_retries", 0) != 0:
                # retry-disabled tasks are not reconstructable, matching
                # the reference (max_retries=0 -> ObjectLostError)
                self._record_lineage(spec)
        self._absorb_arg_owners(spec)
        onode = tuple(spec.get("owner_node") or ())
        for b in spec["return_ids"]:
            info = self.objects.setdefault(ObjectID(b), ObjInfo())
            info.owner = info.owner or spec.get("owner", "")
            if onode and not info.owner_node:
                info.owner_node = onode
        self._record_event(spec, "PENDING")
        self._enqueue_task(spec)

    # -- ownership + lineage --------------------------------------------------

    def _record_lineage(self, spec: dict) -> None:
        """Retain the producer spec so lost returns can be re-executed
        (reference: task_manager.h lineage pinning bounded by
        max_lineage_bytes)."""
        tid = spec["task_id"]
        live = set(spec["return_ids"])
        for b in live:
            rec = self.owned.get(b)
            if rec is None:
                self.owned[b] = OwnedRec(task_id=tid)
            else:
                rec.task_id = rec.task_id or tid
        if tid in self.lineage or not live:
            return
        wire = _wire_spec(spec)
        # cheap size estimate: serialized args dominate a spec
        cost = len(wire.get("args") or b"") + 256 * (1 + len(live))
        self.lineage[tid] = {"spec": wire, "cost": cost, "live": live,
                             "recons": 0}
        self._lineage_order.append(tid)
        self._lineage_bytes += cost
        cap = self.config.max_lineage_bytes
        while self._lineage_bytes > cap and self._lineage_order:
            old = self._lineage_order.popleft()
            lin = self.lineage.get(old)
            if lin is not None and lin["spec"] is not None:
                lin["spec"] = None
                self._lineage_bytes -= lin["cost"]

    def _absorb_arg_owners(self, spec: dict) -> None:
        """Adopt the forwarding node's owner hints for arg objects so
        location queries go to owners, not the head."""
        for b, onode in (spec.get("arg_owners") or {}).items():
            info = self.objects.setdefault(ObjectID(b), ObjInfo())
            if not info.owner_node:
                info.owner_node = tuple(onode)

    def _attach_arg_owners(self, wire: dict, spec: dict) -> None:
        """Stamp owner addresses onto a spec leaving this node (the
        reference ships owner_address inside every ObjectReference)."""
        owners = {}
        ids = list(spec.get("arg_ids", ()))
        for b in ids:
            info = self.objects.get(ObjectID(b))
            if info is None:
                continue
            if info.owner_node:
                owners[b] = tuple(info.owner_node)
            elif info.state != "pending":
                # no owner recorded but we hold a copy: we can serve it
                owners[b] = (self.node_id.hex(), self.address)
        if owners:
            wire["arg_owners"] = owners

    def _projected_available(self) -> dict:
        """Availability net of demand already sitting in the runnable
        queues: resources are only acquired at dispatch, so raw
        `available` over-promises (the reference's hybrid policy counts
        committed resources the same way,
        hybrid_scheduling_policy.h)."""
        proj = dict(self.available)
        for k, v in self._queued_demand.items():
            proj[k] = proj.get(k, 0.0) - v
        return {k: max(0.0, v) for k, v in proj.items()}

    def _available_covers(self, spec: dict) -> bool:
        proj = self._projected_available()
        return all(proj.get(k, 0.0) + 1e-9 >= v
                   for k, v in self._demand(spec).items())

    def _cluster_has_capacity(self, spec: dict) -> bool:
        demand = self._demand(spec)
        me = self.node_id.hex()
        for h, n in self.cluster_view.items():
            if h == me or not n.get("alive"):
                continue
            if all(n["available"].get(k, 0.0) + 1e-9 >= v
                   for k, v in demand.items()):
                return True
        return False

    def _enqueue_task(self, spec: dict) -> None:
        routed = spec.get("_routed")
        pg = spec.get("placement_group")
        clustered = self.head_conn is not None and not routed
        if pg is not None:
            if (pg[0], pg[1]) not in self.pg_available:
                if clustered:
                    # bundle lives on another node: the head routes it there
                    self._forward_task(spec)
                    return
                if routed:
                    # routed here for a bundle that was removed in the
                    # meantime: fail fast — queueing would head-of-line
                    # block every later task behind an unacquirable spec
                    self._fail_task(
                        spec, "Placement group bundle no longer exists "
                              "on this node (group removed?)")
                    return
        elif not self._feasible(spec):
            if clustered:
                self._forward_task(spec)
                return
            self._fail_task(spec, "Infeasible resource demand: "
                            f"{self._demand(spec)} on {self.total_resources}")
            return
        elif clustered and not self._available_covers(spec):
            # spillover: we can't run it NOW — let the head place it.
            # The head ranks by availability AND parked backlog, so this
            # must not be gated on the view showing free capacity: the
            # view's availability is optimistically debited to zero
            # during any burst, and gating on it made a submitter keep
            # ~95% of a 4000-task burst while seven nodes sat idle
            # (reference: saturated tasks go to the cluster scheduler,
            # cluster_task_manager.h — placement is ITS call, not the
            # submitting raylet's)
            self._forward_task(spec)
            return
        if spec.get("_routed") and not self._feasible(spec):
            # routing race: the head's view was stale
            self._fail_task(spec, "Infeasible resource demand after "
                            f"routing: {self._demand(spec)} on "
                            f"{self.total_resources}")
            return
        ndeps = 0
        for b in spec.get("arg_ids", []):
            oid = ObjectID(b)
            info = self.objects.setdefault(oid, ObjInfo())
            if info.state == "pending":
                ndeps += 1
                self.dep_waiting.setdefault(oid, []).append(spec)
                self._ensure_remote_watch([oid])
        spec["_ndeps"] = ndeps
        if ndeps == 0:
            self._make_runnable(spec)
            self._schedule()

    def _forward_task(self, spec: dict) -> None:
        tid = spec["task_id"]
        if _fr._active is not None:
            # the interval ending at the DESTINATION's node_recv stamp
            # is then the head-route + wire hop
            _fr._active.stamp(spec, "forward")

        def cb(reply):
            if reply.get("error"):
                self._fail_task(spec, reply["error"])
                return
            if reply.get("local"):
                spec["_routed"] = True
                self._enqueue_task(spec)
                return
            dst = reply["node"]
            tr = self.tasks.get(tid)
            if tr is not None:
                tr.state = "forwarded"
            self._fwd_tasks[tid] = {"spec": spec, "dst": dst,
                                    "retries": spec.get("max_retries", 0)}
            for b in spec["return_ids"]:
                self._fwd_by_oid[b] = tid
            self._ensure_remote_watch(
                [ObjectID(b) for b in spec["return_ids"]])
        wire = _wire_spec(spec)
        self._attach_arg_owners(wire, spec)
        self._head_rpc({"t": "cluster_submit", "spec": wire,
                        "src_available": self._projected_available()}, cb)

    def _hh_remote_submit(self, m: dict) -> None:
        spec = m["spec"]
        spec["_routed"] = True
        self._admit_task(spec)

    def _make_runnable(self, spec: dict) -> None:
        if _fr._active is not None:
            _fr._active.stamp(spec, "enqueue")
        if spec.get("num_tpus"):
            self.runnable_tpu.append(spec)
        elif self._is_zero_demand(spec):
            # zero-demand tasks (PlacementGroup.ready() pollers) get
            # their own queue: they can always run, so they must not sit
            # behind a resource-blocked FIFO head — and keeping them out
            # of runnable_cpu keeps _schedule O(1), no per-event scans
            self.runnable_zero.append(spec)
        else:
            self.runnable_cpu.append(spec)
        if spec.get("placement_group"):
            self._queued_pg += 1
        else:
            for k, v in self._demand(spec).items():
                self._queued_demand[k] = self._queued_demand.get(k, 0.0) + v

    def _queue_pop(self, q: deque) -> dict:
        spec = q.popleft()
        if spec.get("placement_group"):
            self._queued_pg = max(0, self._queued_pg - 1)
        else:
            for k, v in self._demand(spec).items():
                self._queued_demand[k] = self._queued_demand.get(k, 0.0) - v
        if (not self.runnable_cpu and not self.runnable_tpu
                and not self.runnable_zero):
            # drain point: clear float drift
            self._queued_demand.clear()
            self._queued_pg = 0
        return spec

    def _h_task_done(self, rec, m):
        tid = m["task_id"]
        # the task outran its SIGKILL: it is not an OOM casualty (and a
        # stale entry must not mislabel a later failure of this task id)
        self._oom_kills.pop(tid, None)
        tr = self.tasks.get(tid)
        if tr is not None:
            tr.state = "failed" if m.get("error") else "finished"
            tr.finished_at = time.time()
            tr.error = m.get("error", "")
            self._note_task_finished(tid)
            self._release_arg_blob(tr.spec)
            if _fr._active is not None:
                self._fr_finish(tr, m)
            self._record_event(tr.spec, "FAILED" if m.get("error") else "FINISHED")
        if rec.dedicated_actor is not None:
            ar = self.actors.get(rec.dedicated_actor)
            if ar is not None:
                ar.running.pop(tid, None)
                self._dispatch_actor_queue(ar)
        else:
            if rec.state in ("busy", "blocked"):
                rec.state = "idle"
            rec.current_task = None
            if tr is not None and not tr.spec.get("_cpu_released"):
                self._return_resources(tr.spec)
        # unpin args
        if tr is not None:
            for b in tr.spec.get("arg_ids", []):
                self.store.unpin(ObjectID(b))
        self._schedule()

    def _release_task_cpu(self, rec: ClientRec) -> None:
        """Worker blocked on get: release its task's resources so the node
        can keep making progress (reference: raylet releases CPU for
        blocked workers)."""
        if rec.current_task is None:
            return
        tr = self.tasks.get(rec.current_task)
        if tr is not None and not tr.spec.get("_cpu_released"):
            tr.spec["_cpu_released"] = True
            self._return_resources(tr.spec)

    def _demand(self, spec) -> dict:
        d = dict(spec.get("resources") or {})
        # Tasks default to 1 CPU; actors hold 0 CPU for their lifetime
        # unless explicitly requested (reference: ray actor default
        # num_cpus=0 after creation, ray_option_utils.py).
        d.setdefault("CPU", 0.0 if spec.get("kind") == "actor_create" else 1.0)
        if spec.get("num_tpus"):
            d["TPU"] = float(spec["num_tpus"])
        return d

    def _try_acquire(self, spec) -> bool:
        demand = self._demand(spec)
        pg = spec.get("placement_group")
        if pg is not None:
            key = (pg[0], pg[1])
            free = self.pg_available.get(key)
            if free is None:
                return False
            if all(free.get(k, 0.0) + 1e-9 >= v for k, v in demand.items()):
                for k, v in demand.items():
                    free[k] = free.get(k, 0.0) - v
                return True
            return False
        if all(self.available.get(k, 0.0) + 1e-9 >= v for k, v in demand.items()):
            for k, v in demand.items():
                self.available[k] = self.available.get(k, 0.0) - v
            return True
        return False

    def _return_resources(self, spec) -> None:
        demand = self._demand(spec)
        pg = spec.get("placement_group")
        if pg is not None:
            free = self.pg_available.get((pg[0], pg[1]))
            if free is not None:
                for k, v in demand.items():
                    free[k] = free.get(k, 0.0) + v
            return
        for k, v in demand.items():
            self.available[k] = self.available.get(k, 0.0) + v
        if self._pending_local_pgs:
            self._try_place_local_pgs()

    def _feasible(self, spec) -> bool:
        demand = self._demand(spec)
        if spec.get("placement_group"):
            return True
        return all(self.total_resources.get(k, 0.0) + 1e-9 >= v
                   for k, v in demand.items())

    def _args_ready(self, spec) -> bool:
        for b in spec.get("arg_ids", []):
            info = self.objects.get(ObjectID(b))
            if info is None or info.state == "pending":
                return False
        return True

    def _schedule(self) -> None:
        """FIFO dispatch from the runnable queues (reference:
        LocalTaskManager::DispatchScheduledTasksToWorkers,
        local_task_manager.cc:101).  O(1) amortized per event: stops at the
        first queue head that cannot be placed."""
        for q, tpu in ((self.runnable_cpu, False), (self.runnable_tpu, True),
                       (self.runnable_zero, False)):
            while q:
                spec = q[0]
                container = (spec.get("runtime_env") or {}).get("container")
                if container and tpu:
                    # the TPU executor lives in the driver process; a
                    # containerized worker can never satisfy it — fail
                    # fast instead of wedging the TPU queue head
                    self._queue_pop(q)
                    self._fail_task(
                        spec, "runtime_env.container is not supported "
                              "for TPU tasks (TPU work runs on the "
                              "driver's in-process executor)")
                    continue
                w = self._find_idle_worker(
                    tpu=tpu, env_hash=spec.get("env_hash"),
                    container_image=(container or {}).get("image", ""))
                if w is None:
                    if container:
                        self._maybe_spawn_container_worker(container)
                    elif not tpu:
                        self._maybe_spawn_worker()
                    break
                if not self._try_acquire(spec):
                    break
                self._queue_pop(q)
                self._dispatch_task(w, spec)

    def _is_zero_demand(self, spec: dict) -> bool:
        """True for specs that take nothing from the pool (e.g.
        PlacementGroup.ready() pollers) — they always deserve a worker
        and ride their own queue, immune to CPU-FIFO head blocking."""
        return (not spec.get("placement_group")
                and not spec.get("num_tpus")
                and all(v <= 0 for v in self._demand(spec).values()))

    def _find_idle_worker(self, tpu: bool,
                          env_hash: Optional[str] = None,
                          container_image: str = ""
                          ) -> Optional[ClientRec]:
        best = None
        for rec in self.clients.values():
            if (rec.kind in ("worker", "tpu_executor") and rec.state == "idle"
                    and rec.dedicated_actor is None and rec.tpu == tpu):
                # container tasks only run inside a matching image;
                # plain tasks never borrow a containerized worker (its
                # filesystem is the image's, not the host's)
                if rec.container_image != container_image:
                    continue
                if not env_hash:
                    return rec
                # prefer a worker that already materialized this env
                # (reference: worker_pool.h:192 runtime-env-hash cache)
                if env_hash in rec.seen_envs:
                    return rec
                if best is None:
                    best = rec
        return best

    def _maybe_spawn_container_worker(self, container: dict) -> None:
        """Launch a worker exec'd inside the requested image
        (runtime_env.container — ROADMAP 5a).  One launch in flight per
        image: container cold-starts are seconds, and every _schedule
        pass would otherwise stampede podman.  A launcher that dies
        before its worker registers re-arms on the next pass."""
        image = container["image"]
        prev = self._container_spawning.get(image)
        if prev is not None and prev.poll() is None:
            return
        # arm the guard BEFORE the spawn call: a chaos-delayed spawn
        # returns without a Popen, and every _schedule pass until the
        # delay elapsed would otherwise queue another launch.  The
        # placeholder expires after the register window so a silently
        # failed launch re-arms; _do_spawn_worker overwrites it with
        # the real proc.
        self._container_spawning[image] = _PendingLaunch(
            self.config.worker_register_timeout_s)
        try:
            self._spawn_worker_proc(container=dict(container))
        except Exception as e:
            self._container_spawning.pop(image, None)
            # no container runtime / unlaunchable image: a spec that can
            # never dispatch must not wedge the queue head forever —
            # fail the demand with the real problem named
            self._fail_container_demand(
                image, f"containerized worker for image '{image}' "
                       f"cannot launch: {e}")

    def _fail_container_demand(self, image: str, error: str) -> None:
        for q in (self.runnable_cpu, self.runnable_tpu,
                  self.runnable_zero):
            doomed = [s for s in q
                      if (((s.get("runtime_env") or {}).get("container")
                           or {}).get("image")) == image]
            for spec in doomed:
                q.remove(spec)
                # mirror _queue_pop's aggregate accounting
                if spec.get("placement_group"):
                    self._queued_pg = max(0, self._queued_pg - 1)
                else:
                    for k, v in self._demand(spec).items():
                        self._queued_demand[k] = \
                            self._queued_demand.get(k, 0.0) - v
                self._fail_task(spec, error)
        if (not self.runnable_cpu and not self.runnable_tpu
                and not self.runnable_zero):
            self._queued_demand.clear()
            self._queued_pg = 0
        for ar in list(self.actors.values()):
            if (ar.state in ("pending", "restarting")
                    and ar.conn_id is None
                    and (((ar.spec.get("runtime_env") or {})
                          .get("container") or {}).get("image")) == image):
                self._mark_actor_dead(ar, error)

    def _dispatch_task(self, w: ClientRec, spec: dict) -> None:
        tr = self.tasks[spec["task_id"]]
        tr.state = "running"
        tr.worker = w.conn_id
        tr.started_at = time.time()
        w.state = "busy"
        w.current_task = spec["task_id"]
        if spec.get("env_hash"):
            w.seen_envs.add(spec["env_hash"])
        for b in spec.get("arg_ids", []):
            self.store.pin(ObjectID(b))
        self._record_event(spec, "RUNNING", worker=w.conn_id)
        if _fr._active is not None:
            _fr._active.stamp(spec, "dispatch")
        self._push(w, {"t": "execute", "spec": spec})
        if _fi._active is not None:
            # chaos plane: "kill the worker that got the K-th dispatch"
            # — the task is in flight, so this exercises the
            # worker-death retry/FAILED path deterministically
            _fi._active.on_dispatch(self, w, spec)

    def _release_arg_blob(self, spec: dict) -> None:
        """Oversized (args, kwargs) tuples ride the store as a blob put
        by the submitter purely to carry them (runtime._prepare_args);
        no ObjectRef ever wraps the blob, so nothing releases it —
        reclaim it on TERMINAL task completion (retries still need it)."""
        b = spec.get("arg_blob")
        if b:
            self._released_wait.add(ObjectID(b))
            self._sweep_released()

    def _note_task_finished(self, tid: bytes) -> None:
        """Bound the finished-task history (the live dict stays O(recent),
        dupes are harmless — eviction re-checks state)."""
        self._done_order.append(tid)
        cap = max(1000, self.config.task_events_buffer_size // 5)
        while len(self._done_order) > cap:
            old = self._done_order.popleft()
            tr = self.tasks.get(old)
            if tr is not None and tr.state in ("finished", "failed"):
                del self.tasks[old]

    def _fail_task(self, spec: dict, error: str) -> None:
        tr = self.tasks.get(spec["task_id"])
        if tr is not None:
            tr.state = "failed"
            tr.error = error
            tr.finished_at = time.time()
            self._note_task_finished(spec["task_id"])
        self._release_arg_blob(spec)
        self._record_event(spec, "FAILED")
        for b in spec["return_ids"]:
            self._seal_error_object(ObjectID(b), RuntimeError(error))

    def _audit_worker_pool(self) -> None:
        """Self-heal the in-flight spawn counter against crashed spawns
        and prune long-dead procs.  Runs on the periodic tick, NOT per
        event: each liveness probe is a waitpid/kill syscall per proc,
        and at thousands of events/s this scan alone was ~45% of the
        node loop (sampled; the 5 ms throttle still admitted it every
        few events)."""
        alive = [p for p in self._worker_procs if p.poll() is None]
        if len(self._worker_procs) - len(alive) > 32:
            self._worker_procs = alive
        registered = sum(1 for c in self.clients.values()
                         if c.kind == "worker" and not c.tpu)
        # on_tick runs _schedule() right after this, so just correct
        # the counter here
        self._spawning = max(0, len(alive) - registered)

    def _maybe_spawn_worker(self, tpu: bool = False) -> None:
        if tpu:
            return  # TPU executors are registered by the driver, not spawned
        # Throttle: this runs on EVERY submit/completion event.  Pool
        # sizing only needs to be right within a few ms; the periodic
        # tick re-audits (and self-heals `_spawning`) regardless.
        now = time.monotonic()
        if now - getattr(self, "_last_spawn_eval", 0.0) < 0.005:
            # re-arm so a lone skipped event still gets its evaluation
            # promptly instead of waiting for the next tick
            if not getattr(self, "_spawn_eval_armed", False):
                self._spawn_eval_armed = True

                def rearm():
                    self._spawn_eval_armed = False
                    self._schedule()
                self.post_later(0.006, rearm)
            return
        self._last_spawn_eval = now
        registered = sum(1 for c in self.clients.values()
                         if c.kind == "worker" and not c.tpu)
        # Demand-driven pool growth (reference: worker_pool.h capped startup
        # concurrency :192): one worker per waiting task/actor, capped.
        n_actors_waiting = sum(
            1 for a in self.actors.values()
            if a.state in ("pending", "restarting") and a.conn_id is None
            and not a.spec.get("num_tpus"))
        # containerized workers don't count as spare capacity here: they
        # can only take matching-image tasks, so an idle one must not
        # mask the need for a host worker
        idle = sum(1 for c in self.clients.values()
                   if c.kind == "worker" and not c.tpu and c.state == "idle"
                   and c.dedicated_actor is None and not c.container_image)
        # Tasks can only run while CPU is available, so a pool larger than
        # the free CPUs is waste; placement-group tasks draw on their
        # bundle reservation, zero-cpu tasks (e.g. PlacementGroup.ready()
        # pollers) run regardless of CPU pressure, and actors hold no CPU
        # — all three always need a process.  Concurrent startups are
        # capped (reference: worker_pool.h maximum_startup_concurrency
        # :192,717).
        n_pg = min(self._queued_pg, len(self.runnable_cpu))
        n_zero = len(self.runnable_zero)
        cpu_demand = min(len(self.runnable_cpu) - n_pg,
                         max(0, int(self.available.get("CPU", 0.0))))
        demand = cpu_demand + n_pg + n_zero + n_actors_waiting
        # cold spawns compete for CPU, so their concurrency is capped at
        # roughly core count; forks from the warm template cost ~ms and
        # can ramp much harder (reference: worker_pool.h:192,717)
        if self._prefork_conn is not None or self._prefork_ready():
            max_concurrent_startup = 16
        else:
            max_concurrent_startup = max(2, os.cpu_count() or 1)
        want = min(demand - idle - self._spawning,
                   self.config.max_workers - registered - self._spawning,
                   max_concurrent_startup - self._spawning)
        for _ in range(max(0, want)):
            self._spawning += 1
            self._spawn_worker_proc()

    def _spawn_worker_proc(self, container: Optional[dict] = None) -> None:
        if _fi._active is not None:
            # chaos plane: slow-spawn (the fork lands late) or a spawn
            # that silently dies; _audit_worker_pool self-heals the
            # in-flight counter either way, exactly as for a real
            # crashed spawn
            v = _fi._active.spawn_verdict(self)
            if v == "fail":
                return
            if type(v) is tuple:
                self.post_later(
                    v[1], lambda: self._do_spawn_worker(container))
                return
        self._do_spawn_worker(container)

    def _do_spawn_worker(self, container: Optional[dict] = None) -> None:
        logdir = os.path.join(self.session_dir, "logs")
        # monotone counter, NOT len(): pruning dead procs shrinks the
        # list and len() would hand a live worker's log index to a new
        # one (interleaved logs, wrong dashboard attribution)
        self._worker_seq = getattr(self, "_worker_seq", 0) + 1
        idx = self._worker_seq
        outp = os.path.join(logdir, f"worker-{idx}.out")
        errp = os.path.join(logdir, f"worker-{idx}.err")
        # containerized workers (runtime_env.container) always bypass
        # the prefork template: the child must be exec'd INSIDE the
        # image, and a fork of this host's pre-imported interpreter is
        # by definition not that (reference:
        # _private/runtime_env/container.py worker command wrapping)
        proc = None if container else self._fork_worker(outp, errp)
        if proc is None:
            env = self._worker_env()
            worker_cmd = [sys.executable, "-m", "ray_tpu.core.worker",
                          "--address", self.worker_address,
                          "--session", self.session]
            if container:
                from ray_tpu.runtime_env import container_command
                worker_cmd = container_command(container, worker_cmd,
                                               self.session_dir)
            out = open(outp, "ab", buffering=0)
            err = open(errp, "ab", buffering=0)
            proc = subprocess.Popen(
                worker_cmd,
                env=env, stdout=out, stderr=err, start_new_session=True)
            if container:
                self._container_spawning[container["image"]] = proc
        self._worker_procs.append(proc)
        # stack dumps / the dashboard log view need pid -> log mapping
        self._worker_log_by_pid[proc.pid] = (outp, errp)

    def _worker_env(self) -> dict:
        env = dict(os.environ)
        # Workers must not steal the TPU from the driver: force CPU jax —
        # and skip ambient TPU-plugin registration entirely (site hooks
        # keyed on this env cost ~2.4 s of pure import time per process
        # and risk contending for the chip the driver owns).
        env.pop("PALLAS_AXON_POOL_IPS", None)
        env["JAX_PLATFORMS"] = "cpu"
        env.setdefault("XLA_FLAGS", "")
        env["RAY_TPU_SESSION"] = self.session
        # Propagate the driver's import path so functions/classes pickled
        # by reference (module-level defs in driver-side scripts) resolve
        # in workers — the minimal slice of the reference's runtime-env
        # working_dir propagation (reference:
        # python/ray/_private/runtime_env/working_dir.py capability).
        env["PYTHONPATH"] = os.pathsep.join(
            [p for p in sys.path if p] +
            [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p])
        return env

    # -- fork-server template (core/prefork.py)

    def _start_prefork_template(self) -> None:
        """Spawn the pre-imported worker template.  Non-blocking: the
        template warms up (~0.5 s) while the node finishes starting;
        until its socket accepts, spawns fall back to cold Popen."""
        logdir = os.path.join(self.session_dir, "logs")
        os.makedirs(logdir, exist_ok=True)
        self._prefork_path = os.path.join(self.session_dir, "prefork.sock")
        out = open(os.path.join(logdir, "prefork.out"), "ab", buffering=0)
        err = open(os.path.join(logdir, "prefork.err"), "ab", buffering=0)
        self._prefork_proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu.core.prefork",
             "--socket", self._prefork_path],
            env=self._worker_env(), stdout=out, stderr=err,
            start_new_session=True)

    def _prefork_ready(self) -> bool:
        if self._prefork_conn is not None:
            return True
        if (self._prefork_proc is None
                or self._prefork_proc.poll() is not None):
            return False
        import socket as _socket
        s = _socket.socket(_socket.AF_UNIX)
        s.settimeout(0.05)
        try:
            s.connect(self._prefork_path)
        except OSError:
            s.close()
            return False
        # short bound: this socket is read on the EVENT-LOOP thread, so
        # a wedged template must not stall scheduling for long — on
        # timeout we drop the template and cold-spawn instead
        s.settimeout(2.0)
        self._prefork_conn = s
        self._prefork_buf = b""
        return True

    def _fork_worker(self, outp: str, errp: str):
        """Request a forked worker from the template; None -> caller
        should cold-spawn instead."""
        if not self.config.prefork_workers or not self._prefork_ready():
            return None
        import json as _json
        try:
            req = {"address": self.worker_address,
                   "stdout": outp, "stderr": errp,
                   "env": {"RAY_TPU_SESSION": self.session}}
            self._prefork_conn.sendall(_json.dumps(req).encode() + b"\n")
            while b"\n" not in self._prefork_buf:
                chunk = self._prefork_conn.recv(4096)
                if not chunk:
                    raise OSError("prefork template closed")
                self._prefork_buf += chunk
            line, self._prefork_buf = self._prefork_buf.split(b"\n", 1)
            return _ForkedProc(_json.loads(line)["pid"])
        except (OSError, ValueError):
            try:
                self._prefork_conn.close()
            except OSError:
                pass
            self._prefork_conn = None
            return None

    # -- actors

    def _h_create_actor(self, rec, m):
        spec = m["spec"]
        if self.head_conn is not None:
            # head owns names, placement, and the cluster directory
            reqid = m["reqid"]

            def cb(reply):
                w = self.clients.get(rec.conn_id)
                if w is None:
                    return
                if reply.get("error"):
                    self._reply(w, reqid, error=reply["error"])
                else:
                    self._reply(w, reqid, actor_id=reply["actor_id"],
                                existing=reply.get("existing", False))
            self._head_rpc({"t": "cluster_create_actor",
                            "spec": _wire_spec(spec)}, cb)
            return
        actor_id = ActorID(spec["actor_id"])
        name = spec.get("name") or ""
        ns = spec.get("namespace") or "default"
        if name:
            key = (ns, name)
            if key in self.named_actors and \
                    self.actors[self.named_actors[key]].state != "dead":
                if spec.get("get_if_exists"):
                    self._reply(rec, m["reqid"],
                                actor_id=self.named_actors[key].binary(),
                                existing=True)
                    return
                self._reply(rec, m["reqid"],
                            error=f"Actor name '{name}' already taken in "
                                  f"namespace '{ns}'")
                return
            self.named_actors[key] = actor_id
        if not self._feasible(spec):
            self.named_actors.pop((ns, name), None) if name else None
            self._reply(rec, m["reqid"],
                        error=f"Infeasible actor resource demand: "
                              f"{self._demand(spec)} on {self.total_resources}")
            return
        self._reply(rec, m["reqid"], actor_id=actor_id.binary())
        self._admit_actor(spec)

    def _admit_actor(self, spec: dict) -> ActorRec:
        actor_id = ActorID(spec["actor_id"])
        # named concurrency groups add their own in-flight budget on top
        # of the default group's (reference: concurrency_group_manager.cc
        # — per-group executors; the executor enforces per-group limits,
        # the node only caps the total it pushes)
        mc = spec.get("max_concurrency", 1) + \
            sum((spec.get("concurrency_groups") or {}).values())
        ar = ActorRec(actor_id=actor_id, spec=spec,
                      name=spec.get("name") or "",
                      namespace=spec.get("namespace") or "default",
                      restarts_left=spec.get("max_restarts", 0),
                      max_concurrency=mc)
        self.actors[actor_id] = ar
        self._place_actor(ar)
        return ar

    def _hh_place_actor(self, m: dict) -> None:
        """Head chose this node to host the actor (fresh or node-death
        re-place: the constructor re-runs; reference:
        gcs_actor_manager.cc RestartActor)."""
        spec = m["spec"]
        old = self.actors.get(ActorID(spec["actor_id"]))
        if old is not None and old.state not in ("dead",):
            return  # duplicate placement push
        self._admit_actor(spec)

    def _place_actor(self, ar: ActorRec) -> None:
        needs_tpu = bool(ar.spec.get("num_tpus"))
        container = (ar.spec.get("runtime_env") or {}).get("container")
        if container and needs_tpu:
            self._mark_actor_dead(
                ar, "runtime_env.container is not supported for TPU "
                    "actors (TPU work runs on the driver's in-process "
                    "executor)")
            return
        w = self._find_idle_worker(
            tpu=needs_tpu,
            container_image=(container or {}).get("image", ""))
        if w is None:
            if container:
                self._maybe_spawn_container_worker(container)
            else:
                self._maybe_spawn_worker(tpu=needs_tpu)
            # event-driven retry on the next worker registration (the
            # 50 ms poll alone serialized bursts of actor creations)
            self._actors_wanting_worker.append(ar)
            self.post_later(0.05, lambda: self._place_actor_if_pending(ar))
            return
        if not self._try_acquire(ar.spec):
            self.post_later(0.05, lambda: self._place_actor_if_pending(ar))
            return
        if not w.tpu:
            # CPU actors get a dedicated worker process (reference: one
            # worker per actor); the in-process TPU executor is shared —
            # it hosts all TPU actors and tasks in the driver.
            w.dedicated_actor = ar.actor_id
            w.state = "busy"
        ar.conn_id = w.conn_id
        self._push(w, {"t": "create_actor_exec", "spec": ar.spec})

    def _place_actor_if_pending(self, ar: ActorRec) -> None:
        if ar.state in ("pending", "restarting") and ar.conn_id is None:
            self._place_actor(ar)

    def _report_actor_state(self, ar: ActorRec) -> None:
        """State fan-out: via the head in cluster mode (it publishes and
        resolves watchers), locally otherwise."""
        if self.head_conn is not None:
            self._head_send({"t": "actor_state_report",
                             "actor_id": ar.actor_id.binary(),
                             "state": ar.state,
                             "death_cause": ar.death_cause})
        else:
            self._publish_local("actor_state",
                                {"actor_id": ar.actor_id.hex(),
                                 "state": ar.state})

    def _h_actor_created(self, rec, m):
        ar = self.actors.get(ActorID(m["actor_id"]))
        if ar is None:
            return
        if m.get("error"):
            ar.state = "dead"
            ar.death_cause = m["error"]
            self._fail_actor_queue(ar, m["error"])
            if rec.dedicated_actor == ar.actor_id:
                rec.dedicated_actor = None
                rec.state = "idle"
            ar.conn_id = None
            self._return_resources(ar.spec)
            self._report_actor_state(ar)
        else:
            ar.state = "alive"
            self._report_actor_state(ar)
            self._dispatch_actor_queue(ar)

    def _h_submit_actor_task(self, rec, m):
        spec = m["spec"]
        actor_id = ActorID(spec["actor_id"])
        ar = self.actors.get(actor_id)
        if self.head_conn is not None and not spec.get("owner_node"):
            # actor-task returns get the ownership directory but NOT
            # lineage: re-running actor methods is not loss-transparent
            # (reference: actor results -> ObjectLostError by default)
            spec["owner_node"] = (self.node_id.hex(), self.address)
        onode = tuple(spec.get("owner_node") or ())
        for b in spec["return_ids"]:
            info = self.objects.setdefault(ObjectID(b), ObjInfo())
            info.owner = info.owner or spec.get("owner", "")
            if onode and not info.owner_node:
                info.owner_node = onode
        self.tasks[spec["task_id"]] = TaskRec(spec=spec)
        if _fr._active is not None:
            _fr._active.start_or_stamp(spec, "node_recv")
        self._record_event(spec, "PENDING")
        if ar is not None:
            if ar.state == "dead":
                self._fail_task(spec, f"Actor is dead: {ar.death_cause}")
                return
            ar.queue.append(spec)
            self._dispatch_actor_queue(ar)
            return
        if self.head_conn is None:
            self._fail_task(spec, "Actor is dead: actor not found")
            return
        self._route_actor_task(spec)

    # ---- cluster actor-task routing

    def _route_actor_task(self, spec: dict) -> None:
        ab = spec["actor_id"]
        cached = self.actor_cache.get(ab)
        if cached is not None:
            # on forward failure: invalidate the cache and re-route via a
            # fresh head lookup (the actor may have moved)
            self._forward_actor_task(
                spec, cached[0], cached[1],
                on_fail=lambda: (self.actor_cache.pop(ab, None),
                                 self._queue_actor_locate(spec)))
            return
        self._queue_actor_locate(spec)

    def _queue_actor_locate(self, spec: dict) -> None:
        ab = spec["actor_id"]
        waiting = self._awaiting_actor.setdefault(ab, [])
        waiting.append(spec)
        if len(waiting) == 1:
            self._head_rpc({"t": "locate_actor", "actor_id": ab},
                           lambda reply: self._on_actor_located(ab, reply))

    def _on_actor_located(self, ab: bytes, reply: dict) -> None:
        state = reply.get("state")
        if reply.get("error") and self.head_conn is None:
            # transient: the head died mid-locate.  Keep the specs
            # parked through the failover grace window — the rejoin
            # path re-asks, on_tick expires the window.
            self._actor_wait_parked.setdefault(ab, time.monotonic())
            return
        self._actor_wait_parked.pop(ab, None)   # the head answered
        if reply.get("error") or state in ("dead", "unknown"):
            cause = reply.get("death_cause") or reply.get("error") \
                or "actor not found"
            for spec in self._awaiting_actor.pop(ab, []):
                self._fail_task(spec, f"Actor is dead: {cause}")
            return
        if state == "alive":
            self.actor_cache[ab] = (reply["node"], reply["address"])
            for spec in self._awaiting_actor.pop(ab, []):
                self._forward_actor_task(
                    spec, reply["node"], reply["address"],
                    on_fail=lambda s=spec: self._fail_task(
                        s, "Actor's node is unreachable"))
            return
        # pending/restarting: the head registered us as a watcher and will
        # push actor_at when it settles — keep the specs queued

    def _hh_actor_at(self, m: dict) -> None:
        self._on_actor_located(m["actor_id"], m)

    def _forward_actor_task(self, spec: dict, node_hex: str,
                            address: str, on_fail) -> None:
        def go(conn):
            if conn is None:
                on_fail()
                return
            wire = _wire_spec(spec)
            wire["_routed"] = True
            self._attach_arg_owners(wire, spec)
            try:
                conn.send({"t": "remote_actor_task", "spec": wire})
            except protocol.ConnectionClosed:
                self._drop_peer(node_hex)
                on_fail()
                return
            tid = spec["task_id"]
            tr = self.tasks.get(tid)
            if tr is not None:
                tr.state = "forwarded"
            self._fwd_tasks[tid] = {"spec": spec, "dst": node_hex,
                                    "retries": 0, "actor": True}
            for b in spec["return_ids"]:
                self._fwd_by_oid[b] = tid
            self._ensure_remote_watch(
                [ObjectID(b) for b in spec["return_ids"]])
        self._peer_conn_async(node_hex, address, go)

    def _h_remote_actor_task(self, rec, m):
        """A peer node forwarded a method call for an actor hosted here."""
        spec = m["spec"]
        spec["_routed"] = True
        actor_id = ActorID(spec["actor_id"])
        self._absorb_arg_owners(spec)
        onode = tuple(spec.get("owner_node") or ())
        for b in spec["return_ids"]:
            info = self.objects.setdefault(ObjectID(b), ObjInfo())
            info.owner = info.owner or spec.get("owner", "")
            if onode and not info.owner_node:
                info.owner_node = onode
        self.tasks[spec["task_id"]] = TaskRec(spec=spec)
        self._record_event(spec, "PENDING")
        ar = self.actors.get(actor_id)
        if ar is None or ar.state == "dead":
            cause = ar.death_cause if ar else "actor not on this node"
            self._fail_task(spec, f"Actor is dead: {cause}")
            return
        ar.queue.append(spec)
        self._dispatch_actor_queue(ar)

    def _dispatch_actor_queue(self, ar: ActorRec) -> None:
        if ar.state != "alive" or ar.conn_id is None:
            return
        w = self.clients.get(ar.conn_id)
        if w is None:
            return
        while ar.queue and ar.inflight < ar.max_concurrency:
            spec = ar.queue.popleft()
            if not self._args_ready(spec):
                # actors preserve submission order: put back and stop
                ar.queue.appendleft(spec)
                self._ensure_remote_watch(
                    [ObjectID(b) for b in spec.get("arg_ids", [])
                     if self.objects.setdefault(ObjectID(b),
                                                ObjInfo()).state == "pending"])
                self._wait_args_then(spec, lambda: self._dispatch_actor_queue(ar))
                return
            ar.running[spec["task_id"]] = spec
            for b in spec.get("arg_ids", []):
                self.store.pin(ObjectID(b))
            tr = self.tasks.get(spec["task_id"])
            if tr is not None:
                tr.state = "running"
                tr.started_at = time.time()
                tr.worker = w.conn_id
            self._record_event(spec, "RUNNING", worker=w.conn_id)
            if _fr._active is not None:
                _fr._active.stamp(spec, "dispatch")
            self._push(w, {"t": "execute_actor", "spec": spec})

    def _wait_args_then(self, spec, cb) -> None:
        remaining = [ObjectID(b) for b in spec.get("arg_ids", [])
                     if self.objects.get(ObjectID(b), ObjInfo()).state == "pending"]
        if not remaining:
            cb()
            return
        # Poll via the event loop until the dependency lands (v1; the
        # reference stages deps through the DependencyManager).
        self.post_later(0.02, lambda: self._wait_args_then(spec, cb))

    def _fail_actor_queue(self, ar: ActorRec, error: str) -> None:
        while ar.queue:
            self._fail_task(ar.queue.popleft(), f"Actor died: {error}")

    def _h_kill_actor(self, rec, m):
        actor_id = ActorID(m["actor_id"])
        ar = self.actors.get(actor_id)
        if ar is None and self.head_conn is not None:
            # actor lives elsewhere: the head routes the kill
            reqid = m.get("reqid")

            def cb(reply):
                w = self.clients.get(rec.conn_id)
                if reqid is not None and w is not None:
                    self._reply(w, reqid, ok=bool(reply.get("ok")))
            self._head_rpc({"t": "kill_actor", "actor_id": m["actor_id"],
                            "no_restart": m.get("no_restart", True)}, cb)
            return
        if ar is None:
            if "reqid" in m:
                self._reply(rec, m["reqid"], ok=False)
            return
        self._kill_local_actor(ar, m.get("no_restart", True))
        if "reqid" in m:
            self._reply(rec, m["reqid"], ok=True)

    def _kill_local_actor(self, ar: ActorRec, no_restart: bool) -> None:
        if no_restart:
            ar.restarts_left = 0
        w = self.clients.get(ar.conn_id) if ar.conn_id is not None else None
        if w is not None and not w.tpu:
            self._push(w, {"t": "exit"})
        elif w is not None:
            # shared in-process TPU executor: destroy only this actor's
            # instance, keep the executor alive for other work
            self._push(w, {"t": "destroy_actor",
                           "actor_id": ar.actor_id.binary()})
            self._mark_actor_dead(ar, "killed")
        else:
            self._mark_actor_dead(ar, "killed")

    def _hh_kill_local_actor(self, m: dict) -> None:
        ar = self.actors.get(ActorID(m["actor_id"]))
        if ar is not None:
            self._kill_local_actor(ar, m.get("no_restart", True))

    def _mark_actor_dead(self, ar: ActorRec, cause: str) -> None:
        if ar.state == "dead":
            return
        ar.state = "dead"
        ar.death_cause = cause
        ar.conn_id = None
        for spec in list(ar.running.values()):
            self._fail_task(spec, f"Actor died: {cause}")
        ar.running.clear()
        self._fail_actor_queue(ar, cause)
        self._return_resources(ar.spec)
        self._report_actor_state(ar)

    def _h_get_named_actor(self, rec, m):
        if self._cluster_scope(rec, m):
            return
        key = (m.get("namespace") or "default", m["name"])
        aid = self.named_actors.get(key)
        if aid is None or self.actors[aid].state == "dead":
            self._reply(rec, m["reqid"], error="not found")
        else:
            ar = self.actors[aid]
            self._reply(rec, m["reqid"], actor_id=aid.binary(), spec_meta={
                "methods": ar.spec.get("methods", []),
                "class_name": ar.spec.get("class_name", "")})

    def _h_list_named_actors(self, rec, m):
        if self._cluster_scope(rec, m):
            return
        out = [{"namespace": ns, "name": n}
               for (ns, n), aid in self.named_actors.items()
               if self.actors[aid].state != "dead"
               and (m.get("all_namespaces") or ns == (m.get("namespace")
                                                      or "default"))]
        self._reply(rec, m["reqid"], actors=out)

    # -- head proxying ------------------------------------------------------

    def _cluster_scope(self, rec: ClientRec, m: dict) -> bool:
        """Route a cluster-scope client request.  True = handled here
        (proxied to the head, or failed transiently); False = this node
        is STANDALONE and should serve it from its local stores.

        The distinction matters during a head failover: a cluster
        node with its head temporarily gone must NOT silently fall back
        to its (empty) local store — that's a split-brain read.  It
        answers with a transient, RetryPolicy-retryable error instead,
        so clients ride out the failover and then read the truth."""
        if self.head_address is None:
            return False
        if self.head_conn is None:
            if "reqid" in m:
                self._reply(rec, m["reqid"],
                            error="head connection lost (failover in "
                                  "progress)")
            return True
        self._proxy_to_head(rec, m)
        return True

    def _proxy_to_head(self, rec: ClientRec, m: dict) -> None:
        """Forward a cluster-scope client request to the head verbatim and
        relay the reply (errors included)."""
        reqid = m.get("reqid")
        fwd = {k: v for k, v in m.items() if k != "reqid"}
        if reqid is None:
            self._head_send(fwd)
            return

        def cb(reply):
            w = self.clients.get(rec.conn_id)
            if w is None:
                return
            out = {k: v for k, v in reply.items() if k not in ("t", "reqid")}
            self._reply(w, reqid, **out)
        self._head_rpc(fwd, cb)

    # -- placement groups

    def _h_create_pg(self, rec, m):
        if self._cluster_scope(rec, m):
            return   # head (or failover error) ran the cross-node 2PC
        bundles = m["bundles"]
        total = bundle_total(bundles)
        if not covers(self.total_resources, total):
            # can NEVER fit on this node — fail creation synchronously
            self._reply(rec, m["reqid"],
                        error=f"Infeasible placement group {total}; "
                              f"node total {self.total_resources}")
            return
        # creation is async: reply now, reserve when resources allow;
        # PlacementGroup.ready() gates on pg_state == "created"
        self._reply(rec, m["reqid"], ok=True, state="pending")
        self._pending_local_pgs[m["pg_id"]] = {
            "bundles": bundles, "strategy": m.get("strategy", "PACK")}
        self._try_place_local_pgs()

    def _try_place_local_pgs(self) -> None:
        """Reserve queued single-node PGs once resources free up."""
        for pgb, info in list(self._pending_local_pgs.items()):
            total = bundle_total(info["bundles"])
            if not covers(self.available, total):
                continue
            for k, v in total.items():
                self.available[k] -= v
            pg_id = PlacementGroupID(pgb)
            self.pgs[pg_id] = PGRec(pg_id=pg_id, bundles=info["bundles"],
                                    strategy=info["strategy"])
            for i, b in enumerate(info["bundles"]):
                self.pg_available[(pgb, i)] = dict(b)
            del self._pending_local_pgs[pgb]
            self._schedule()

    def _h_pg_state(self, rec, m):
        if self._cluster_scope(rec, m):
            return
        pg_id = PlacementGroupID(m["pg_id"])
        if pg_id in self.pgs:
            st = "created"
        elif m["pg_id"] in self._pending_local_pgs:
            st = "pending"
        else:
            st = "removed"
        self._reply(rec, m["reqid"], ok=True, state=st)

    def _h_remove_pg(self, rec, m):
        if self._cluster_scope(rec, m):
            return
        pg_id = PlacementGroupID(m["pg_id"])
        self._pending_local_pgs.pop(m["pg_id"], None)
        pg = self.pgs.pop(pg_id, None)
        if pg is not None:
            for i, b in enumerate(pg.bundles):
                self.pg_available.pop((pg_id.binary(), i), None)
                for k, v in b.items():
                    self.available[k] = self.available.get(k, 0.0) + v
            self._try_place_local_pgs()
        if "reqid" in m:
            self._reply(rec, m["reqid"], ok=True)

    # 2PC participant handlers (pushed by the head over the head channel;
    # reference: gcs_placement_group_scheduler.h Prepare/Commit on raylets)

    def _hh_head_snapshot(self, m: dict) -> None:
        """Persist the head's replicated snapshot (the cluster-as-the-
        database head-FT store — see head.py _fan_out_replicas)."""
        if m.get("session") not in (None, getattr(self, "head_session",
                                                  "")):
            return   # a different cluster's state must never land here
        # seq fence per head incarnation: a slow async snapshot can fan
        # out AFTER a newer snapshot_now one — applying it would undo
        # the barrier's guarantee (and lose whatever the newer snapshot
        # captured on a later head-machine recovery)
        boot = m.get("boot")
        if boot != getattr(self, "_head_replica_boot", None):
            self._head_replica_boot = boot
            self._head_replica_seq = 0
        if m.get("seq", 0) < getattr(self, "_head_replica_seq", 0):
            return   # stale replica from an older snapshot
        path = os.path.join(self.session_dir, "head_replica.state")
        tmp = path + ".tmp"
        try:
            with open(tmp, "wb") as f:
                f.write(m["data"])
            os.replace(tmp, path)
            self._head_replica_seq = m.get("seq", 0)
        except OSError:
            pass  # a missed replica is refreshed by the next snapshot

    def _h_fetch_head_snapshot(self, rec, m):
        """A replacement head bootstraps from this node's replica; the
        reply carries this node's session so a head recovering against
        the wrong cluster rejects it."""
        path = os.path.join(self.session_dir, "head_replica.state")
        try:
            with open(path, "rb") as f:
                data = f.read()
        except OSError:
            self._reply(rec, m["reqid"],
                        session=getattr(self, "head_session", ""),
                        error="no head snapshot replica on this node")
            return
        self._reply(rec, m["reqid"], ok=True, data=data,
                    session=getattr(self, "head_session", ""),
                    seq=getattr(self, "_head_replica_seq", 0))

    def _hh_pg_prepare(self, m: dict) -> None:
        bundle = m["bundle"]
        ok = all(self.available.get(k, 0.0) + 1e-9 >= v
                 for k, v in bundle.items())
        if ok:
            for k, v in bundle.items():
                self.available[k] -= v
            self._pg_prepared[(m["pg_id"], m["bundle_idx"])] = dict(bundle)
        self._head_reply(m["reqid"], ok=ok)

    def _hh_pg_commit(self, m: dict) -> None:
        key = (m["pg_id"], m["bundle_idx"])
        bundle = self._pg_prepared.pop(key, None)
        if bundle is not None:
            self.pg_available[key] = dict(bundle)
            self._pg_bundles[key] = dict(bundle)   # original reservation

    def _hh_pg_rollback(self, m: dict) -> None:
        bundle = self._pg_prepared.pop((m["pg_id"], m["bundle_idx"]), None)
        if bundle is not None:
            for k, v in bundle.items():
                self.available[k] = self.available.get(k, 0.0) + v

    def _hh_pg_remove_local(self, m: dict) -> None:
        key = (m["pg_id"], m["bundle_idx"])
        free = self.pg_available.pop(key, None)
        # hand the ORIGINAL bundle reservation back to the node; tasks
        # still drawing on the bundle release into the void afterwards,
        # same as the reference's bundle-return semantics
        orig = self._pg_bundles.pop(key, None)
        if orig is None and free is None:
            return
        for k, v in (orig or free).items():
            self.available[k] = self.available.get(k, 0.0) + v

    # -- kv / pubsub

    def _h_kv_put(self, rec, m):
        if self._cluster_scope(rec, m):
            return
        super()._h_kv_put(rec, m)

    def _h_kv_get(self, rec, m):
        if self._cluster_scope(rec, m):
            return
        super()._h_kv_get(rec, m)

    def _h_kv_del(self, rec, m):
        if self._cluster_scope(rec, m):
            return
        super()._h_kv_del(rec, m)

    def _h_kv_keys(self, rec, m):
        if self._cluster_scope(rec, m):
            return
        super()._h_kv_keys(rec, m)

    def _h_subscribe(self, rec, m):
        ch = m["channel"]
        if self.head_conn is not None and ch not in self._head_subs:
            # subscribe this NODE at the head once per channel; local
            # clients fan out from the node (reference: pubsub long-poll
            # through the raylet)
            self._head_subs.add(ch)
            self._head_send({"t": "subscribe", "channel": ch})
        super()._h_subscribe(rec, m)

    def _publish(self, channel: str, data: Any) -> None:
        if self.head_conn is not None:
            # cluster-wide: the head fans out to subscribed nodes
            # (including this one), which deliver locally on _hh_pub
            self._head_send({"t": "publish", "channel": channel,
                             "data": data})
            return
        self._publish_local(channel, data)

    def _hh_pub(self, m: dict) -> None:
        self._publish_local(m["channel"], m["data"])

    def _hh_view_update(self, m: dict) -> None:
        self.cluster_view = m["view"]

    # -- node-to-node object transfer ---------------------------------------

    def _peer_conn_async(self, node_hex: str, address: str, cb) -> None:
        """Hand `cb` a Connection to the peer (or None).  The TCP connect
        runs on a helper thread — a blackholed peer must never stall the
        event loop (heartbeats ride it, and a stalled loop gets this
        healthy node declared dead)."""
        conn = self._peer_conns.get(node_hex)
        if conn is not None:
            cb(conn)
            return
        waiters = self._peer_connecting.setdefault(node_hex, [])
        waiters.append(cb)
        if len(waiters) > 1:
            return   # a connect is already in flight

        def work():
            c = None
            try:
                c = protocol.connect(
                    address, timeout=5.0, remote=True,
                    label=(f"node:{self.node_id.hex()[:8]}",
                           f"node:{node_hex[:8]}"))
                c.send({"t": "register", "kind": "peer", "reqid": 0,
                        "node_hex": self.node_id.hex(),
                        "worker_id": f"peer-{self.node_id.hex()[:12]}"})
            except (OSError, protocol.ConnectionClosed):
                if c is not None:
                    try:
                        c.close()
                    except Exception:
                        pass
                c = None
            self.post(lambda: self._peer_connected(node_hex, c))
        threading.Thread(target=work, daemon=True,
                         name=f"raytpu-connect-{node_hex[:8]}").start()

    def _peer_connected(self, node_hex: str,
                        conn: Optional[protocol.Connection]) -> None:
        cbs = self._peer_connecting.pop(node_hex, [])
        if conn is not None:
            self._peer_conns[node_hex] = conn
            from ray_tpu.core.local_lane import LaneConnection
            if isinstance(conn, LaneConnection):
                # same-process peer: deliver from its loop, no recv thread
                conn.on_close = \
                    lambda: self.post(lambda: self._drop_peer(node_hex))
                conn.set_deliver(
                    lambda m: self.post(
                        lambda m=m: self._on_peer_msg(node_hex, m)))
            else:
                t = threading.Thread(target=self._peer_recv_loop,
                                     args=(node_hex, conn), daemon=True,
                                     name=f"raytpu-peer-{node_hex[:8]}")
                t.start()
        for cb in cbs:
            try:
                cb(conn)
            except Exception:
                sys.stderr.write("[node] peer-connect callback failed:\n"
                                 + traceback.format_exc())

    def _peer_recv_loop(self, node_hex: str,
                        conn: protocol.Connection) -> None:
        while not self._stop.is_set():
            try:
                msg = conn.recv()
            except protocol.ConnectionClosed:
                self.post(lambda: self._drop_peer(node_hex))
                return
            except Exception:
                continue
            self.post(lambda m=msg: self._on_peer_msg(node_hex, m))

    def _drop_peer(self, node_hex: str) -> None:
        conn = self._peer_conns.pop(node_hex, None)
        if conn is not None:
            try:
                conn.close()
            except Exception:
                pass
        # pulls in flight from that peer: retry through the head (it may
        # know another location, or the producer will resubmit)
        for ob, st in list(self._pulls.items()):
            if st["src"] == node_hex:
                self._pulls.pop(ob, None)
                self._watched.discard(ob)
                self.post_later(
                    0.1, lambda o=ObjectID(ob): self._ensure_remote_watch([o]))

    def _ensure_remote_watch(self, oids: list) -> None:
        """Route pending objects to their location authority: the OWNER
        node when known (reference: ownership_based_object_directory.cc),
        the head only as fallback for objects with no owner hint.  Safe
        to call repeatedly — each object is watched at most once."""
        if self.head_conn is None:
            return
        me = self.node_id.hex()
        head_want = []
        by_owner: dict[tuple, list] = {}
        for o in oids:
            ob = o.binary()
            if ob in self._watched or ob in self._pulls:
                continue
            info = self.objects.get(o)
            if info is not None and info.state != "pending":
                continue
            onode = tuple(info.owner_node) if info is not None \
                and info.owner_node else ()
            if onode and onode[0] == me:
                # owner-side resolution is idempotent and cheap — don't
                # latch _watched, so demand arriving later re-resolves
                self._owner_self_resolve(ob)
            elif onode:
                self._watched.add(ob)
                by_owner.setdefault(onode, []).append(ob)
            else:
                self._watched.add(ob)
                head_want.append(ob)
        for onode, obs in by_owner.items():
            self._owner_locate_send(onode, obs)
        if head_want:
            self._head_locate(head_want)

    def _head_locate(self, obs: list, fatal_missing: bool = False) -> None:
        """Fallback directory lookup through the head."""

        def cb(reply):
            if reply.get("error"):
                return
            locs = reply.get("locs", {})
            for ob, (node_hex, addr) in locs.items():
                self._request_pull(ObjectID(ob), node_hex, addr)
            if fatal_missing:
                from ray_tpu.core.client import ObjectLostError
                for ob in obs:
                    if ob in locs:
                        continue
                    oid = ObjectID(ob)
                    info = self.objects.get(oid)
                    if info is not None and info.state == "pending":
                        self._seal_error_object(oid, ObjectLostError(
                            f"Object {oid.hex()[:16]} was lost: its "
                            "owner node died and no copy is known"))
        self._head_rpc({"t": "locate_object", "object_ids": list(obs)}, cb)

    # -- ownership directory protocol ----------------------------------------

    def _owner_locate_send(self, onode: tuple, obs: list) -> None:
        """Ask the owner node where these objects live; it replies with
        object_at pushes (or owner_object_lost) and registers us as a
        watcher until then."""
        hexn, addr = onode

        def go(conn):
            if conn is None:
                self._owner_unreachable(hexn, obs)
                return
            try:
                conn.send({"t": "owner_locate", "object_ids": list(obs),
                           "from_hex": self.node_id.hex(),
                           "from_addr": self.address})
                for ob in obs:
                    self._owner_watch[ob] = hexn
            except protocol.ConnectionClosed:
                self._drop_peer(hexn)
                self._owner_unreachable(hexn, obs)
        self._peer_conn_async(hexn, addr, go)

    def _owner_unreachable(self, owner_hex: str, obs: list) -> None:
        """Owner node gone: fall back to the head directory; if it knows
        no copy either, the object is lost for good."""
        retry = []
        for ob in obs:
            self._owner_watch.pop(ob, None)
            info = self.objects.get(ObjectID(ob))
            if info is not None and info.state == "pending":
                info.owner_node = ()
                retry.append(ob)
        if retry:
            self._head_locate(retry, fatal_missing=True)

    def _owner_push(self, node_hex: str, address: str, msg: dict) -> None:
        def go(conn):
            if conn is None:
                return
            # corked: one owner push per finished task — the batch flush
            # turns a per-task send into one send per loop pass (a dead
            # peer is noticed by its recv/on_close path)
            self._conn_send(conn, msg)
        self._peer_conn_async(node_hex, address, go)

    def _owner_add_location(self, ob: bytes, node_hex: str,
                            address: str) -> None:
        """Owner-side: record that a copy of an owned object exists on
        `node_hex`, notify watchers, feed our own pending consumers."""
        orec = self.owned.get(ob)
        if orec is None:
            orec = self.owned[ob] = OwnedRec()
        orec.locations[node_hex] = address
        # a remote location report IS the completion signal for a task we
        # forwarded — settle its record so node-death recovery treats the
        # object as lost-but-reconstructable, not in-flight
        tid = self._fwd_by_oid.pop(ob, None)
        if tid is not None:
            fw = self._fwd_tasks.get(tid)
            if fw is not None and not any(b in self._fwd_by_oid
                                          for b in fw["spec"]["return_ids"]):
                self._fwd_tasks.pop(tid, None)
                tr = self.tasks.get(tid)
                if tr is not None and tr.state == "forwarded":
                    tr.state = "finished"
                    tr.finished_at = time.time()
                    self._note_task_finished(tid)
                    self._release_arg_blob(fw["spec"])
        if orec.watchers:
            watchers, orec.watchers = orec.watchers, set()
            for whex, waddr in watchers:
                if whex == node_hex:
                    continue
                self._owner_push(whex, waddr,
                                 {"t": "object_at", "object_id": ob,
                                  "node": node_hex, "address": address})
        # demand-driven: pull our own copy only if something local waits
        # on it (a get, a wait, or a queued task's dependency)
        oid = ObjectID(ob)
        info = self.objects.get(oid)
        if info is not None and info.state == "pending" \
                and node_hex != self.node_id.hex() \
                and (oid in self._mg_by_oid or oid in self.dep_waiting
                     or info.wait_waiters):
            self._request_pull(oid, node_hex, address)

    def _h_owner_object_at(self, rec, m):
        """A node stored a copy of an object WE own."""
        self._owner_add_location(m["object_id"], m["node"], m["address"])

    def _h_owner_locate(self, rec, m):
        """A consumer asks us (the owner) where our objects live."""
        me = self.node_id.hex()
        watcher = (m.get("from_hex", ""), m.get("from_addr", ""))
        for ob in m["object_ids"]:
            oid = ObjectID(ob)
            info = self.objects.get(oid)
            if info is not None and info.state != "pending":
                self._push(rec, {"t": "object_at", "object_id": ob,
                                 "node": me, "address": self.address})
                continue
            orec = self.owned.get(ob)
            if orec is not None:
                self._prune_dead_locations(orec)
                loc = next(((h, a) for h, a in orec.locations.items()
                            if h != me), None)
                if loc is not None:
                    self._push(rec, {"t": "object_at", "object_id": ob,
                                     "node": loc[0], "address": loc[1]})
                    continue
            tid = (orec.task_id if orec is not None and orec.task_id
                   else oid.task_id().binary())
            if self._producer_in_flight(tid) or self._reconstruct(tid):
                # result will arrive: register the asker for the
                # object_at push that follows
                if watcher[0]:
                    orec = self.owned.get(ob)
                    if orec is None:
                        orec = self.owned[ob] = OwnedRec(task_id=tid)
                    orec.watchers.add(watcher)
                continue
            self._push(rec, {"t": "owner_object_lost", "object_id": ob,
                             "cause": "owner holds no copy and no lineage"})

    def _h_object_at(self, rec, m):
        """Location push from an owner node (same shape as the head's)."""
        self._on_owner_object_at_push(m)

    def _h_owner_object_value(self, rec, m):
        """Inline VALUE pushed by the node that executed forwarded work
        we own — seal it locally, skipping locate/pull round trips."""
        ob = m["object_id"]
        self._owner_watch.pop(ob, None)
        self._watched.discard(ob)
        oid = ObjectID(ob)
        info = self.objects.setdefault(oid, ObjInfo())
        if info.state != "pending":
            return
        info.state = "error" if m.get("is_error") else "ready"
        info.loc = "inline"
        info.data = m["data"]
        info.is_error = bool(m.get("is_error"))
        info.size = len(m["data"] or b"")
        # the executing node still holds a replica — track it like an
        # owner_object_at so release sweeps can reach it
        self._owner_add_location(ob, m["node"], m["address"])
        self._resolve_waiters(oid, info)

    def _on_owner_object_at_push(self, m: dict) -> None:
        self._owner_watch.pop(m["object_id"], None)
        self._hh_object_at(m)

    def _h_owner_object_lost(self, rec, m):
        self._on_owner_object_lost_push(m)

    def _on_owner_object_lost_push(self, m: dict) -> None:
        ob = m["object_id"]
        self._owner_watch.pop(ob, None)
        oid = ObjectID(ob)
        info = self.objects.get(oid)
        if info is None or info.state != "pending":
            return
        from ray_tpu.core.client import ObjectLostError
        self._seal_error_object(oid, ObjectLostError(
            f"Object {oid.hex()[:16]} was lost: {m.get('cause', '')}"))

    def _prune_dead_locations(self, orec: OwnedRec) -> None:
        me = self.node_id.hex()
        for h in list(orec.locations):
            if h != me and h not in self.cluster_view:
                orec.locations.pop(h)

    def _producer_in_flight(self, tid: bytes) -> bool:
        if tid in self._fwd_tasks:
            return True
        tr = self.tasks.get(tid)
        return tr is not None and tr.state in ("pending", "running",
                                               "forwarded")

    def _owner_self_resolve(self, ob: bytes) -> None:
        """We own this pending object: pull a known copy, wait on the
        in-flight producer, or re-execute it from lineage (reference:
        object_recovery_manager.h:41)."""
        oid = ObjectID(ob)
        info = self.objects.get(oid)
        if info is None or info.state != "pending":
            return
        me = self.node_id.hex()
        orec = self.owned.get(ob)
        if orec is not None:
            self._prune_dead_locations(orec)
            loc = next(((h, a) for h, a in orec.locations.items()
                        if h != me), None)
            if loc is not None:
                self._request_pull(oid, loc[0], loc[1])
                return
        # no live copy: wait on an in-flight producer (the owned rec may
        # not exist yet — lineage-less tasks only get one when a
        # location is first reported), reconstruct, or declare the loss
        tid = (orec.task_id if orec is not None and orec.task_id
               else oid.task_id().binary())
        if self._producer_in_flight(tid):
            return
        if self._reconstruct(tid):
            return
        from ray_tpu.core.client import ObjectLostError
        self._seal_error_object(oid, ObjectLostError(
            f"Object {oid.hex()[:16]} was lost and cannot be "
            "reconstructed (no live copy, no retained lineage)"))

    def _reconstruct(self, tid: bytes) -> bool:
        """Re-execute the producer of lost owned objects.  Deterministic
        return ids mean the re-run recreates exactly the lost objects
        (reference: object_recovery_manager.h ReconstructObject)."""
        lin = self.lineage.get(tid)
        if lin is None or lin.get("spec") is None:
            return False
        if lin["recons"] >= self.config.max_object_reconstructions:
            return False
        lin["recons"] += 1
        spec = dict(lin["spec"])
        # fresh flight-recorder record: the captured wire spec shares
        # the original attempt's stamp list, and stamping into it would
        # misattribute the whole loss-detection gap to node_recv
        spec.pop("fr", None)
        spec.pop("fr_w0", None)
        spec.pop("fr_done", None)
        sys.stderr.write(f"[node] reconstructing task "
                         f"{tid.hex()[:12]} (attempt {lin['recons']})\n")
        self._admit_task(spec)
        return True

    def _hh_object_at(self, m: dict) -> None:
        oid = ObjectID(m["object_id"])
        info = self.objects.get(oid)
        if info is not None and info.state == "pending":
            self._request_pull(oid, m["node"], m["address"])

    def _hh_object_lost(self, m: dict) -> None:
        ob = m["object_id"]
        if ob in self._fwd_by_oid:
            return  # our own forwarded task will be resubmitted on node_dead
        oid = ObjectID(ob)
        info = self.objects.get(oid)
        if info is None or info.state != "pending":
            return
        if info.owner_node:
            # the owner, not the head, decides whether this is fatal —
            # it may hold another copy or reconstruct from lineage
            if info.owner_node[0] == self.node_id.hex():
                self._owner_self_resolve(ob)
            elif ob not in self._owner_watch:
                self._owner_locate_send(tuple(info.owner_node), [ob])
            return
        from ray_tpu.core.client import ObjectLostError
        self._seal_error_object(oid, ObjectLostError(
            f"Object {oid.hex()[:16]} was lost: "
            f"{m.get('cause', 'node died')}"))

    def _request_pull(self, oid: ObjectID, node_hex: str,
                      address: str) -> None:
        ob = oid.binary()
        if ob in self._pulls:
            return
        info = self.objects.get(oid)
        if info is None or info.state != "pending":
            return
        if self._try_local_pull(oid, ob, node_hex):
            return
        # reserve the pull slot BEFORE the async connect so concurrent
        # object_at notifications don't start duplicate transfers
        self._pulls[ob] = {"src": node_hex, "view": None, "size": None,
                           "received": 0, "is_error": False}

        def go(conn):
            st = self._pulls.get(ob)
            if st is None or st["src"] != node_hex:
                return   # resolved or re-routed while connecting
            if conn is None:
                self._pulls.pop(ob, None)
                self._watched.discard(ob)
                self.post_later(0.2,
                                lambda: self._ensure_remote_watch([oid]))
                return
            try:
                conn.send({"t": "pull_object", "object_id": ob,
                           # after any failed attempt, insist on a direct
                           # stream — never bounce through a relay again
                           "no_redirect":
                               self._pull_attempts.get(ob, 0) > 0})
            except protocol.ConnectionClosed:
                self._pulls.pop(ob, None)
                self._watched.discard(ob)
                self._drop_peer(node_hex)
                self.post_later(0.2,
                                lambda: self._ensure_remote_watch([oid]))
        self._peer_conn_async(node_hex, address, go)

    # same-process fast path -------------------------------------------------

    def _try_local_pull(self, oid: ObjectID, ob: bytes,
                        node_hex: str) -> bool:
        """Peer lives in THIS process (virtual cluster): hand the bytes
        over with one memcpy.  Thread discipline: the source's loop pins
        + maps, our loop copies into our arena, the source's loop
        unpins.  Falls back to the socket path on any miss."""
        if not self.config.same_host_object_fastpath:
            return False
        src = _LOCAL_NODES_BY_HEX.get(node_hex)
        if src is None or src is self or src._stop.is_set():
            return False
        self._pulls[ob] = {"src": node_hex, "view": None, "size": None,
                           "received": 0, "is_error": False, "local": True}

        def replay_pulls(queued):
            # socket peers that asked for the object mid-memcpy: serve
            # them now (object present -> stream; absent -> pull_failed
            # so they re-route)
            for cid, pm in queued:
                peer = self.clients.get(cid)
                if peer is not None:
                    self._h_pull_object(peer, pm)

        def fallback():
            st = self._pulls.get(ob)
            if st is not None and st.get("local"):
                self._pulls.pop(ob, None)
                self._watched.discard(ob)
                replay_pulls(st.get("replay_pulls", []))
                self.post_later(0.1,
                                lambda: self._ensure_remote_watch([oid]))

        def on_src():
            info = src.objects.get(oid)
            if (info is None or info.state != "ready"
                    or info.loc not in ("shm", "inline")):
                self.post(fallback)
                return
            if info.loc == "inline":
                data, is_err = info.data, info.is_error
                self.post(lambda: self._local_pull_inline(
                    oid, ob, data, is_err))
                return
            if src.store.is_spilled(oid):
                src.store.restore(oid)
            src.store.pin(oid)
            try:
                view = src.store._shm.map(oid)
            except Exception:
                src.store.unpin(oid)
                self.post(fallback)
                return
            size = src.objects[oid].size

            def on_dst():
                try:
                    try:
                        buf = self.store._shm.create(oid, size)
                        _gil_free_copy(buf, view, size)
                        del buf
                        self.store._shm.seal(oid)
                    except ObjectExists:
                        pass
                    st = self._pulls.pop(ob, None)
                    if st is None:
                        return   # resolved another way meanwhile
                    self.store.register(oid, size)
                    info2 = self.objects.setdefault(oid, ObjInfo())
                    info2.state = "ready"
                    info2.loc = "shm"
                    info2.size = size
                    self._resolve_waiters(oid, info2)
                    replay_pulls(st.get("replay_pulls", []))
                except Exception:
                    fallback()
                finally:
                    src.post(lambda: src.store.unpin(oid))
            self.post(on_dst)

        src.post(on_src)
        # safety net: a wedged source loop must not hang the pull
        self.post_later(10.0, fallback)
        return True

    def _local_pull_inline(self, oid: ObjectID, ob: bytes, data,
                           is_err: bool) -> None:
        st = self._pulls.pop(ob, None)
        if st is None:
            return
        info = self.objects.setdefault(oid, ObjInfo())
        if info.state != "pending":
            return
        info.state = "error" if is_err else "ready"
        info.loc = "inline"
        info.data = data
        info.size = len(data or b"")
        info.is_error = is_err
        self._resolve_waiters(oid, info)
        for cid, pm in st.get("replay_pulls", []):
            peer = self.clients.get(cid)
            if peer is not None:
                self._h_pull_object(peer, pm)

    # sender side -----------------------------------------------------------

    def _h_pull_object(self, rec, m):
        """A peer wants an object stored here: inline goes in one frame,
        shm goes in windowed chunks (reference: object_manager.proto:61
        Push with chunked ObjectChunk stream).

        Broadcast shaping (reference: push_manager.h rate-limited
        parallel pushes; here a relay CHAIN): if this node is itself
        still RECEIVING the object, it serves the request as a relay —
        forwarding chunks as they arrive — and if this node is the
        source already streaming to someone, later requesters are
        redirected to the most recent receiver, so an N-node broadcast
        pipelines through the receivers instead of serializing N full
        streams at the source."""
        ob = m["object_id"]
        oid = ObjectID(ob)
        pst = self._pulls.get(ob)
        if pst is not None:
            if pst.get("local"):
                # same-process fast path in flight: chunk relay state
                # never materializes — replay this request when the
                # memcpy lands (or fails) instead of parking it forever
                pst.setdefault("replay_pulls", []).append(
                    (rec.conn_id, dict(m)))
                return
            # mid-pull here: relay chunks to this requester as they land
            self._relay_register(rec, ob, pst)
            return
        if not m.get("no_redirect"):
            tail = self._bcast_tail.get(ob)
            if tail is not None and tail[0] != rec.node_hex \
                    and (rec.conn_id, ob) not in self._out_transfers:
                active = any(o == ob for (_c, o) in self._out_transfers)
                if active:
                    # chain: newest requester fetches from the previous
                    # one; we keep streaming only the first copy
                    self._push(rec, {"t": "pull_redirect", "object_id": ob,
                                     "node": tail[0], "address": tail[1]})
                    self._note_bcast_tail(ob, rec)
                    return
        info = self.objects.get(oid)
        if info is not None and info.loc == "device":
            # device-resident: spill to host first, then serve the pull
            # (the queued request replays when materialization lands)
            self._device_pending_pulls.setdefault(ob, []).append(
                (rec.conn_id, dict(m)))
            if info.state == "ready":
                self._request_materialize(oid, info)
            return
        if info is None or info.state == "pending":
            self._push(rec, {"t": "pull_failed", "object_id": ob,
                             "error": "object not found on this node"})
            return
        if info.loc == "inline":
            self._push(rec, {"t": "obj_inline", "object_id": ob,
                             "data": info.data, "is_error": info.is_error})
            return
        if self.store.is_spilled(oid):
            self.store.restore(oid)
        self.store.touch(oid)
        self.store.pin(oid)
        try:
            view = self.store._shm.map(oid)
        except Exception:
            self.store.unpin(oid)
            self._push(rec, {"t": "pull_failed", "object_id": ob,
                             "error": "object vanished mid-pull"})
            return
        st = {"oid": oid, "view": view, "size": info.size, "next_off": 0,
              "pinned": True}
        self._out_transfers[(rec.conn_id, ob)] = st
        self._note_bcast_tail(ob, rec)
        for _ in range(self.config.object_transfer_window):
            if not self._send_next_chunk(rec, st):
                break

    def _note_bcast_tail(self, ob: bytes, rec: ClientRec) -> None:
        """Remember the most recent receiver as the chain tail for later
        requesters (only peers with a known node identity qualify)."""
        if rec.node_hex and rec.node_hex in self.cluster_view:
            addr = self.cluster_view[rec.node_hex].get("address")
            if addr:
                self._bcast_tail[ob] = (rec.node_hex, addr)

    def _send_next_chunk(self, rec: ClientRec, st: dict) -> bool:
        off = st["next_off"]
        limit = st["size"] if st.get("available") is None \
            else min(st["size"], st["available"])
        if off >= limit or st["view"] is None:
            return False
        n = min(self.config.object_transfer_chunk_size, limit - off)
        st["next_off"] = off + n
        # blob frame: the chunk bytes ride out-of-band of the pickle —
        # one copy into the socket buffer instead of slice+pickle+buffer
        self._push_blob(rec, {"t": "obj_chunk",
                              "object_id": st["oid"].binary(),
                              "offset": off, "total_size": st["size"]},
                        st["view"][off:off + n])
        if st["next_off"] >= st["size"]:
            # final chunk queued: release our references now; remaining
            # acks for this transfer are ignored
            st["view"] = None
            if st.get("pinned"):
                self.store.unpin(st["oid"])
            self._out_transfers.pop((rec.conn_id, st["oid"].binary()), None)
        return True

    def _h_obj_chunk_ack(self, rec, m):
        st = self._out_transfers.get((rec.conn_id, m["object_id"]))
        if st is not None:
            st["outstanding"] = max(0, st.get("outstanding", 1) - 1)
            if self._send_next_chunk(rec, st):
                st["outstanding"] = st.get("outstanding", 0) + 1

    # relay (chain broadcast) ------------------------------------------------

    def _relay_register(self, rec, ob: bytes, pst: dict) -> None:
        """Serve a pull for an object we are still receiving: forward
        already-received bytes now, the rest as chunks arrive."""
        oid = ObjectID(ob)
        if pst.get("size") is None:
            # no chunk yet: start the relay when the first one lands
            pst.setdefault("relay_waiting", []).append(rec.conn_id)
            return
        st = {"oid": oid, "view": pst["view"], "size": pst["size"],
              "next_off": 0, "available": pst["received"],
              "outstanding": 0, "pinned": False, "relay": True}
        self._out_transfers[(rec.conn_id, ob)] = st
        pst.setdefault("relay_conns", []).append(rec.conn_id)
        self._note_bcast_tail(ob, rec)
        self._relay_advance(rec, st)

    def _relay_advance(self, rec, st: dict) -> None:
        window = self.config.object_transfer_window
        while st.get("outstanding", 0) < window:
            if not self._send_next_chunk(rec, st):
                break
            st["outstanding"] = st.get("outstanding", 0) + 1

    def _relay_on_upstream_chunk(self, ob: bytes, pst: dict) -> None:
        """Upstream bytes advanced: wake pending relays and push more."""
        for cid in pst.pop("relay_waiting", []):
            peer = self.clients.get(cid)
            if peer is not None:
                self._relay_register(peer, ob, pst)
        for cid in list(pst.get("relay_conns", [])):
            st = self._out_transfers.get((cid, ob))
            peer = self.clients.get(cid)
            if st is None or peer is None:
                pst["relay_conns"].remove(cid)
                continue
            st["available"] = pst["received"]
            self._relay_advance(peer, st)

    def _relay_on_pull_done(self, oid: ObjectID, pst: dict) -> None:
        """Our pull finished and the buffer was sealed: re-map (pinned)
        for relays that still have bytes to send."""
        ob = oid.binary()
        for cid in pst.get("relay_conns", []):
            st = self._out_transfers.get((cid, ob))
            if st is None:
                continue
            st["available"] = st["size"]
            try:
                st["view"] = self.store._shm.map(oid)
                self.store.pin(oid)
                st["pinned"] = True
            except Exception:
                self._out_transfers.pop((cid, ob), None)
                peer = self.clients.get(cid)
                if peer is not None:
                    self._push(peer, {"t": "pull_failed", "object_id": ob,
                                      "error": "relay source lost the "
                                               "object mid-stream"})
                continue
            peer = self.clients.get(cid)
            if peer is not None:
                self._relay_advance(peer, st)

    # receiver side ----------------------------------------------------------

    def _on_peer_msg(self, node_hex: str, m: dict) -> None:
        t = m.get("t")
        try:
            if t == "obj_chunk":
                self._on_obj_chunk(node_hex, m)
            elif t == "obj_inline":
                self._on_obj_inline(m)
            elif t == "pull_redirect":
                self._on_pull_redirect(m)
            elif t == "pull_failed":
                self._on_pull_failed(m)
            elif t == "object_at":
                # owner's reply to our owner_locate rides this conn
                self._on_owner_object_at_push(m)
            elif t == "owner_object_lost":
                self._on_owner_object_lost_push(m)
            elif t == "owner_object_at":
                # a holder may report on a conn WE opened to it earlier
                self._owner_add_location(m["object_id"], m["node"],
                                         m["address"])
            elif t == "shutdown":
                self._drop_peer(node_hex)
            # replies (e.g. to our peer register) are ignored
        except Exception:
            sys.stderr.write(f"[node] peer message {t} failed:\n"
                             + traceback.format_exc())

    def _on_obj_chunk(self, node_hex: str, m: dict) -> None:
        ob = m["object_id"]
        st = self._pulls.get(ob)
        if st is None:
            return  # stale transfer (object resolved another way)
        oid = ObjectID(ob)
        if st["view"] is None:
            st["size"] = m["total_size"]
            try:
                st["view"] = self.store._shm.create(oid, st["size"])
            except Exception as e:
                # arena full beyond eviction (or segment clash): fail pull
                self._pulls.pop(ob, None)
                self._fail_pull(oid, f"store create failed during "
                                     f"transfer: {type(e).__name__}: {e}")
                return
        data = m["data"]
        off = m["offset"]
        st["view"][off:off + len(data)] = data
        st["received"] += len(data)
        conn = self._peer_conns.get(node_hex)
        if conn is not None:
            try:
                conn.send({"t": "obj_chunk_ack", "object_id": ob})
            except protocol.ConnectionClosed:
                pass
        if st.get("relay_waiting") or st.get("relay_conns"):
            # chain broadcast: forward the new bytes downstream
            self._relay_on_upstream_chunk(ob, st)
        if st["received"] >= st["size"]:
            st["view"] = None   # release buffer before seal/register
            self.store._shm.seal(oid)
            self._pulls.pop(ob, None)
            self.store.register(oid, st["size"])
            info = self.objects.setdefault(oid, ObjInfo())
            info.state = "ready"
            info.loc = "shm"
            info.size = st["size"]
            if st.get("relay_conns"):
                self._relay_on_pull_done(oid, st)
            self._resolve_waiters(oid, info)

    def _on_pull_redirect(self, m: dict) -> None:
        """The source is busy broadcasting: fetch from the chain tail it
        named instead.  Ignored once bytes started flowing; a failed
        relay fetch falls back through the normal re-watch path (which
        sets no_redirect, so the source then serves directly)."""
        ob = m["object_id"]
        st = self._pulls.get(ob)
        if st is None or st.get("size") is not None:
            return
        self._pulls.pop(ob, None)
        self._watched.discard(ob)
        # a redirect counts as an attempt: if the relay fetch fails, the
        # re-watch retries the source with no_redirect set (direct serve)
        self._pull_attempts[ob] = self._pull_attempts.get(ob, 0) + 1
        self._request_pull(ObjectID(ob), m["node"], m["address"])

    def _on_obj_inline(self, m: dict) -> None:
        ob = m["object_id"]
        self._pulls.pop(ob, None)
        oid = ObjectID(ob)
        info = self.objects.setdefault(oid, ObjInfo())
        if info.state != "pending":
            return
        info.state = "error" if m.get("is_error") else "ready"
        info.loc = "inline"
        info.data = m["data"]
        info.size = len(m["data"])
        info.is_error = bool(m.get("is_error"))
        self._resolve_waiters(oid, info)

    def _on_pull_failed(self, m: dict) -> None:
        ob = m["object_id"]
        st = self._pulls.pop(ob, None)
        src = st["src"] if st else None
        self._watched.discard(ob)
        oid = ObjectID(ob)
        # a failed source is no longer a valid location for objects we own
        orec = self.owned.get(ob)
        if orec is not None and src:
            orec.locations.pop(src, None)
        attempts = self._pull_attempts.get(ob, 0) + 1
        self._pull_attempts[ob] = attempts
        if attempts <= 5:
            # the location may be stale (freed/evicted+deleted); re-locate
            self.post_later(0.2, lambda: self._ensure_remote_watch([oid]))
        else:
            self._fail_pull(oid, m.get("error", "pull failed"), src=src)

    def _fail_pull(self, oid: ObjectID, cause: str,
                   src: Optional[str] = None) -> None:
        info = self.objects.get(oid)
        if info is None or info.state != "pending":
            return
        ob = oid.binary()
        if info.owner_node and info.owner_node[0] == self.node_id.hex():
            orec = self.owned.get(ob)
            if orec is not None and src:
                orec.locations.pop(src, None)
            self._pull_attempts.pop(ob, None)
            # may pull another copy, wait on the producer, reconstruct,
            # or seal the loss itself
            self._owner_self_resolve(ob)
            return
        from ray_tpu.core.client import ObjectLostError
        self._seal_error_object(oid, ObjectLostError(
            f"Object {oid.hex()[:16]} could not be fetched: {cause}"))

    def _hh_delete_object(self, m: dict) -> None:
        self._delete_local_object(ObjectID(m["object_id"]))

    # -- node death recovery -------------------------------------------------

    def _hh_node_dead(self, m: dict) -> None:
        node_hex = m["node"]
        self._drop_peer(node_hex)
        self.actor_cache = {k: v for k, v in self.actor_cache.items()
                            if v[0] != node_hex}
        # owned objects whose only copies died: re-resolve (pull another
        # copy / reconstruct) for any object someone is waiting on
        me = self.node_id.hex()
        for ob, orec in list(self.owned.items()):
            if orec.locations.pop(node_hex, None) is None:
                continue
            if orec.locations and any(h == me or h in self.cluster_view
                                      for h in orec.locations):
                continue
            oid = ObjectID(ob)
            info = self.objects.get(oid)
            needed = (orec.watchers
                      or oid in self._mg_by_oid
                      or oid in self.dep_waiting
                      or (info is not None and info.wait_waiters))
            if needed and info is not None and info.state == "pending":
                self._watched.discard(ob)
                self._owner_self_resolve(ob)
        # consumers whose owner-directory authority died: fall back to
        # the head for anything we were watching through that owner
        stale = [ob for ob, h in self._owner_watch.items()
                 if h == node_hex]
        if stale:
            self._owner_unreachable(node_hex, stale)
            for ob in stale:
                self._watched.discard(ob)
        for tid, fw in list(self._fwd_tasks.items()):
            if fw["dst"] != node_hex:
                continue
            self._fwd_tasks.pop(tid, None)
            spec = fw["spec"]
            for b in spec["return_ids"]:
                self._fwd_by_oid.pop(b, None)
            if fw.get("actor"):
                # the actor may restart elsewhere, but this call's
                # execution state died with the node
                self._fail_task(spec, f"Actor's node {node_hex[:8]} died "
                                      "while the method was in flight")
            elif fw["retries"] > 0:
                # lineage-lite: deterministic return ids mean a re-run
                # re-creates exactly the lost objects (reference:
                # object_recovery_manager.h reconstruction)
                spec = dict(spec)
                spec["max_retries"] = fw["retries"] - 1
                if _fr._active is not None:
                    _fr._active.stamp(spec, "retry")
                self._forward_task(spec)
            else:
                self._fail_task(spec, f"Node {node_hex[:8]} died while "
                                      "running forwarded task")

    # -- state API

    def _fr_finish(self, tr: TaskRec, m: dict) -> None:
        """Fold a completed task's lifecycle stamps into the flight
        recorder.  The worker ships its stamps back inside task_done
        (socket workers executed a COPY of the spec); lane executors
        appended to the shared list, in which case both sides are the
        same object and the merge is a no-op."""
        spec = tr.spec
        if spec.get("fr_done"):
            # already folded: a duplicated task_done (chaos dup) must
            # not re-install the message's stamps and count twice
            return
        wfr = m.get("fr")
        nfr = spec.get("fr")
        if wfr is not None and wfr is not nfr \
                and (nfr is None or len(wfr) >= len(nfr)):
            spec["fr"] = wfr
        if spec.get("fr") is not None:
            rec = _fr._active
            if rec is not None:
                rec.stamp(spec, "done")
                rec.finish(spec, worker=tr.worker)
            spec["fr"] = None
            spec["fr_done"] = True

    def _h_flight_recorder(self, rec, m):
        """Observer query: completed lifecycle records + chaos events +
        the per-stage summary (the `ray_tpu timeline` source)."""
        fr = _fr._active
        if fr is None:
            self._reply(rec, m["reqid"], enabled=False, records=[],
                        faults=[], stages={})
            return
        self._reply(rec, m["reqid"], enabled=True,
                    records=fr.export_records(
                        limit=int(m.get("limit", 2000))),
                    faults=fr.export_faults(),
                    stages=fr.stage_summary())

    def _record_event(self, spec: dict, state: str,
                      worker: Optional[int] = None) -> None:
        self.task_events.append({
            "task_id": spec["task_id"].hex() if isinstance(spec["task_id"], bytes)
            else spec["task_id"],
            "name": spec.get("name", ""),
            "state": state,
            "actor_id": spec.get("actor_id", b"").hex()
            if spec.get("actor_id") else None,
            "worker": worker,
            "time": time.time(),
        })

    def _h_state(self, rec, m):
        what = m["what"]
        if what in ("nodes", "resources", "cluster_actors") \
                and self.head_conn is not None:
            # cluster-scope views come from the head (ray.nodes() /
            # ray.cluster_resources() are cluster-wide in the reference)
            fwd = dict(m)
            fwd["what"] = {"cluster_actors": "actors"}.get(what, what)
            self._proxy_to_head(rec, fwd)
            return
        if what == "tasks":
            out = [{"task_id": tid.hex(), "name": tr.spec.get("name", ""),
                    "state": tr.state, "error": tr.error,
                    "submitted_at": tr.submitted_at,
                    "duration": (tr.finished_at - tr.started_at)
                    if tr.finished_at else None}
                   for tid, tr in self.tasks.items()]
        elif what == "actors":
            out = [{"actor_id": aid.hex(), "state": ar.state,
                    "name": ar.name, "namespace": ar.namespace,
                    "class_name": ar.spec.get("class_name", ""),
                    "pending_calls": len(ar.queue)}
                   for aid, ar in self.actors.items()]
        elif what == "objects":
            out = [{"object_id": oid.hex(), "state": info.state,
                    "loc": info.loc, "size": info.size}
                   for oid, info in self.objects.items()]
        elif what == "workers":
            out = [{"worker_id": c.worker_id, "kind": c.kind, "pid": c.pid,
                    "state": c.state, "tpu": c.tpu,
                    "log": os.path.basename(
                        self._worker_log_by_pid.get(c.pid, ("", ""))[0])
                    or None}
                   for c in self.clients.values()
                   if c.kind in ("worker", "tpu_executor")]
        elif what == "nodes":
            out = [{"node_id": self.node_id.hex(), "address": self.address,
                    "resources": self.total_resources,
                    "available": self.available, "alive": True}]
        elif what == "task_events":
            out = list(self.task_events)
        elif what == "resources":
            out = {"total": self.total_resources, "available": self.available}
        else:
            out = []
        self._reply(rec, m["reqid"], data=out)

    def _h_worker_logs(self, rec, m):
        """List this node's worker log files, or tail one (reference:
        the dashboard's per-worker log viewer, dashboard/modules/log/)."""
        logdir = os.path.join(self.session_dir, "logs")
        name = m.get("name")
        if not name:
            files = []
            try:
                for f in sorted(os.listdir(logdir)):
                    full = os.path.join(logdir, f)
                    files.append({"name": f,
                                  "size": os.path.getsize(full)})
            except OSError:
                pass
            self._reply(rec, m["reqid"], files=files)
            return
        # basename only — no path escape out of the log dir
        path = os.path.join(logdir, os.path.basename(str(name)))
        nbytes = int(m.get("nbytes", 64 * 1024))
        try:
            with open(path, "rb") as f:
                f.seek(0, os.SEEK_END)
                size = f.tell()
                f.seek(max(0, size - nbytes))
                data = f.read()
            self._reply(rec, m["reqid"],
                        data=data.decode("utf-8", "replace"), size=size)
        except OSError as e:
            self._reply(rec, m["reqid"], error=str(e))

    def _h_profile_worker(self, rec, m):
        """Sampling-profile a live worker (reference: dashboard
        profile_manager.py py-spy wrapper): route the request to the
        worker's executor, which samples its own interpreter and pushes
        folded stacks back."""
        pid = int(m["pid"])
        target = next((c for c in self.clients.values()
                       if c.kind in ("worker", "tpu_executor")
                       and c.pid == pid), None)
        if target is None:
            self._reply(rec, m["reqid"],
                        error=f"no live worker with pid {pid}")
            return
        self._profile_seq = getattr(self, "_profile_seq", 0) + 1
        prof_id = self._profile_seq
        self._profile_pending = getattr(self, "_profile_pending", {})
        self._profile_pending[prof_id] = (rec.conn_id, m["reqid"])
        duration = float(m.get("duration", 2.0))
        self._push(target, {"t": "profile", "prof_id": prof_id,
                            "duration": duration,
                            "hz": float(m.get("hz", 99.0))})

        def expire():
            pend = self._profile_pending.pop(prof_id, None)
            if pend is not None:
                w = self.clients.get(pend[0])
                if w is not None:
                    self._reply(w, pend[1],
                                error="profile timed out (worker busy "
                                      "outside its message loop?)")
        self.post_later(duration + 30.0, expire)

    def _h_profile_result(self, rec, m):
        pend = getattr(self, "_profile_pending", {}).pop(
            m.get("prof_id"), None)
        if pend is None:
            return
        w = self.clients.get(pend[0])
        if w is None:
            return
        if m.get("error"):
            self._reply(w, pend[1], error=m["error"])
        else:
            self._reply(w, pend[1], folded=m.get("folded", ""))

    def _h_stack_dump(self, rec, m):
        """Dump a live worker's thread stacks (reference: `ray stack`,
        scripts.py:1767 / profile_manager.py): SIGUSR1 triggers the
        worker's faulthandler into its .err log; reply with the fresh
        tail."""
        pid = int(m["pid"])
        target = next((c for c in self.clients.values()
                       if c.kind == "worker" and c.pid == pid), None)
        logs = self._worker_log_by_pid.get(pid)
        if target is None or logs is None:
            self._reply(rec, m["reqid"],
                        error=f"no live spawned worker with pid {pid}")
            return
        err_path = logs[1]
        try:
            start = os.path.getsize(err_path)
        except OSError:
            start = 0
        try:
            os.kill(pid, signal.SIGUSR1)
        except OSError as e:
            self._reply(rec, m["reqid"], error=str(e))
            return

        def collect(attempt: int = 0, last: int = -1):
            # The dump is async — poll THIS worker's own .err for growth
            # (other workers' stderr chatter must not be misattributed),
            # then wait until it QUIESCES: faulthandler writes the
            # threads one at a time with the CURRENT thread (the one
            # executing the task) LAST, so replying on first growth
            # captured a partial dump missing exactly the frames the
            # caller wants (`ray stack` showed only the recv thread).
            try:
                size = os.path.getsize(err_path)
            except OSError:
                size = start
            if attempt < 40 and (size <= start or size != last):
                self.post_later(0.05, lambda: collect(attempt + 1, size))
                return
            if size <= start:
                self._reply(rec, m["reqid"],
                            error="worker produced no stack dump "
                                  "(faulthandler unavailable?)")
                return
            with open(err_path, "rb") as f:
                f.seek(start)
                data = f.read()
            self._reply(rec, m["reqid"], pid=pid,
                        data=data.decode("utf-8", "replace"),
                        log=os.path.basename(err_path))
        collect()

    def _h_ping(self, rec, m):
        self._reply(rec, m["reqid"], ok=True, time=time.time())

    def _h_head_flush(self, rec, m):
        """Replication barrier: force the head to snapshot + fan out
        replicas, reply once THIS node's replica has landed (the
        head_snapshot push precedes the head's reply on this channel)."""
        if self.head_conn is None:
            self._reply(rec, m["reqid"], ok=True, replicated=False)
            return
        reqid = m["reqid"]

        def cb(reply):
            w = self.clients.get(rec.conn_id)
            if w is None:
                return
            if reply.get("error"):
                self._reply(w, reqid, error=reply["error"])
            else:
                self._reply(w, reqid, ok=True,
                            replicated=bool(reply.get("replicated")))
        self._head_rpc({"t": "snapshot_now"}, cb)

    def _h_stop_node(self, rec, m):
        """Hard-stop this node on request — the chaos-testing kill switch
        (reference: the NodeKiller in _private/test_utils.py:1337 and
        `ray kill-random-node`).  Workers die with the node; the head
        notices through the dropped connection / missed heartbeats."""
        if "reqid" in m:
            self._reply(rec, m["reqid"], ok=True)
        for p in self._worker_procs:
            if p.poll() is None:
                try:
                    p.kill()
                except OSError:
                    pass
        if self._prefork_proc is not None and self._prefork_proc.poll() is None:
            try:
                self._prefork_proc.kill()
            except OSError:
                pass
        self._stop.set()

    # -- disconnect handling

    def on_client_drop(self, rec: ClientRec) -> None:
        for oid, _ts in rec.held_pins:
            self.store.unpin(oid)
        rec.held_pins.clear()
        # device-resident entries die with their owner process
        for oid, info in list(self.objects.items()):
            if info.loc == "device" and info.owner_conn == rec.conn_id:
                self._device_owner_lost(oid, info)
        # drop any outbound transfers to this peer
        for key in [k for k in self._out_transfers if k[0] == rec.conn_id]:
            st = self._out_transfers.pop(key)
            if st.get("view") is not None:
                st["view"] = None
                if st.get("pinned", True):
                    self.store.unpin(st["oid"])
        # fail or retry the running task (reference: worker death →
        # owner retries, task_manager.h:406)
        if rec.current_task is not None:
            tr = self.tasks.get(rec.current_task)
            oom_detail = self._oom_kills.pop(rec.current_task, None)
            if tr is not None and tr.state == "running":
                if not tr.spec.get("_cpu_released"):
                    self._return_resources(tr.spec)
                tr.spec.pop("_cpu_released", None)
                if tr.retries_left > 0:
                    tr.retries_left -= 1
                    tr.state = "pending"
                    if _fr._active is not None:
                        # name the failed attempt + death-detection gap
                        # explicitly so it doesn't pollute the retry's
                        # enqueue interval in the stage histograms
                        _fr._active.stamp(tr.spec, "retry")
                    self._make_runnable(tr.spec)
                elif oom_detail is not None:
                    from ray_tpu.core.client import OutOfMemoryError
                    tr.state = "failed"
                    tr.error = oom_detail
                    tr.finished_at = time.time()
                    self._record_event(tr.spec, "FAILED")
                    for b in tr.spec["return_ids"]:
                        self._seal_error_object(
                            ObjectID(b), OutOfMemoryError(oom_detail))
                else:
                    self._fail_task(tr.spec,
                                    f"Worker died while running task "
                                    f"(pid={rec.pid})")
        conn_actors = [a for a in self.actors.values()
                       if a.conn_id == rec.conn_id and a.state != "dead"]
        for ar in conn_actors:
                self._return_resources(ar.spec)
                ar.conn_id = None
                # In-flight method calls die with the worker: fail them so
                # callers see an actor-death error instead of hanging
                # (reference: actor task fate on actor death,
                # direct_actor_task_submitter.h DisconnectActor).
                for spec in list(ar.running.values()):
                    self._fail_task(spec,
                                    f"Actor died while executing method "
                                    f"'{spec.get('method', '?')}' "
                                    f"(pid={rec.pid})")
                ar.running.clear()
                if ar.restarts_left != 0:
                    if ar.restarts_left > 0:
                        ar.restarts_left -= 1
                    ar.state = "restarting"
                    self._report_actor_state(ar)
                    self._place_actor(ar)
                else:
                    ar.state = "dead"
                    ar.death_cause = f"worker process died (pid={rec.pid})"
                    self._report_actor_state(ar)
                    self._fail_actor_queue(ar, ar.death_cause)
        if (rec.kind == "driver" and self.stop_on_driver_exit
                and rec.conn_id == self._owner_driver):
            # owning driver gone → shut down
            self._stop.set()
        self._schedule()


def main() -> None:
    import argparse
    parser = argparse.ArgumentParser(description="ray_tpu node service")
    parser.add_argument("--port", type=int, default=6379)
    parser.add_argument("--session", default=None)
    parser.add_argument("--session-dir", default=None)
    parser.add_argument("--num-cpus", type=float, default=None)
    parser.add_argument("--num-tpus", type=float, default=None)
    parser.add_argument("--head-address", default=None,
                        help="head service address; omit for standalone")
    parser.add_argument("--label", action="append", default=[],
                        help="k=v node label (repeatable); e.g. the "
                             "autoscaler's provider_node_id")
    args = parser.parse_args()
    labels = dict(kv.split("=", 1) for kv in args.label)
    import uuid
    session = args.session or uuid.uuid4().hex
    session_dir = args.session_dir or os.path.join(
        "/tmp/ray_tpu", f"session_{session[:8]}")
    svc = NodeService(RayTpuConfig(), session, session_dir, port=args.port,
                      num_cpus=args.num_cpus, num_tpus=args.num_tpus,
                      head_address=args.head_address,
                      stop_on_driver_exit=args.head_address is None,
                      labels=labels)
    print(f"ray_tpu node service listening on {svc.address} "
          f"(session {session})", flush=True)
    try:
        svc.run()
    except KeyboardInterrupt:
        svc.stop()


if __name__ == "__main__":
    main()
