"""Native dispatch-frame codec: arming surface + pure-Python reference.

The control-plane hot loop frames small dict messages thousands of
times per second (submit → execute → put_inline → task_done).  The
pickle path is already C-speed, but every frame still pays Python-level
envelope assembly: ``encode_payload`` + header pack + bytes concat per
hop, and a ``time.monotonic()`` + tuple + list-append per
flight-recorder stamp.  This module moves the whole frame — length
prefix, tag, body, and the stamp fold — into one C call
(``native/src/rt_frames.cc``, loaded via ctypes like the shm store),
with THIS file as the byte-identical pure-Python reference
implementation and fallback decoder.

Wire format (frame payload tag 0x03, after the 8-byte LE length
prefix shared with every other encoding in ``core/protocol.py``)::

    payload := 0x03 value           # top-level value must be a map
    value   := 'N' | 'T' | 'F'                    # None / True / False
             | 'I' i64-LE                         # int
             | 'D' f64-LE                         # float
             | 'B' u32-LE len bytes               # bytes
             | 'S' u32-LE len utf8                # str
             | 'L' u32-LE count value*            # list
             | 'U' u32-LE count value*            # tuple
             | 'M' u32-LE count (key value)*      # dict; key is 'S'|'B'

Only exact builtin types are eligible (``type(v) is dict`` — a dict
subclass must survive a round trip as its own type, which only pickle
can do).  Anything else makes the whole message fall back to pickle;
frames are self-describing so mixed encodings coexist on one socket.

Stamp fold: ``encode(msg, stamp="dispatch")`` appends one
``(stage, t_monotonic)`` tuple to the FIRST ``"fr"`` list found in
pre-order traversal while writing it — the flight-recorder timestamp
lands in the encoded frame without mutating the caller's dict and
without a Python-level ``time.monotonic()`` call on the native path.

Arming contract (same discipline as ``fault_injection`` /
``flight_recorder``, verified by ``ray_tpu lint``'s hotpath pass):
``_active`` is the armed native codec or None; hot call sites may only
load ``_rtf._active`` and branch on ``is None``.  With no ``.so`` (or
``RAY_TPU_NATIVE_FRAMES=0``) the codec stays disarmed and every caller
takes the identical pre-existing pickle path.
"""

from __future__ import annotations

import os
import struct
import time
from typing import Any, Optional

TAG = b"\x03"
_HDR = struct.Struct("<Q")
_U32 = struct.Struct("<I")
_I64 = struct.Struct("<q")
_F64 = struct.Struct("<d")

MAX_DEPTH = 32

# The armed native codec (ray_tpu.native.frames.NativeFrameCodec) or
# None.  Hot paths read this module attribute directly.
_active: Optional[Any] = None


def enable() -> bool:
    """Arm the native codec in this process (idempotent).  Returns
    False — leaving the pickle path untouched — when the shared library
    is absent or unloadable."""
    global _active
    if _active is not None:
        return True
    try:
        from ray_tpu.native.frames import NativeFrameCodec
        _active = NativeFrameCodec()
        return True
    except Exception:
        return False


def disable() -> None:
    global _active
    _active = None


def enabled() -> bool:
    return _active is not None


def autoenable_from_env() -> None:
    """Default-on: arm unless RAY_TPU_NATIVE_FRAMES disables it.  A
    missing .so leaves the codec disarmed with identical behavior."""
    if os.environ.get("RAY_TPU_NATIVE_FRAMES", "1").lower() \
            not in ("0", "false", "no"):
        enable()


# ---------------------------------------------------------------------------
# pure-Python reference codec (must stay byte-identical to rt_frames.cc;
# tests/test_rt_frames.py fuzzes the parity)


class _Ineligible(Exception):
    """Internal: a value outside the wire universe — fall back to pickle."""


def _py_encode_value(out: list, v, depth: int,
                     stamp: Optional[tuple]) -> None:
    if v is None:
        out.append(b"N")
        return
    t = type(v)
    if t is bool:
        out.append(b"T" if v else b"F")
        return
    if t is int:
        try:
            out.append(b"I" + _I64.pack(v))
        except struct.error:
            raise _Ineligible from None
        return
    if t is float:
        out.append(b"D" + _F64.pack(v))
        return
    if t is bytes:
        if len(v) > 0xFFFFFFFF:
            raise _Ineligible
        out.append(b"B" + _U32.pack(len(v)))
        out.append(v)
        return
    if t is str:
        try:
            b = v.encode("utf-8")
        except UnicodeEncodeError:
            raise _Ineligible from None
        if len(b) > 0xFFFFFFFF:
            raise _Ineligible
        out.append(b"S" + _U32.pack(len(b)))
        out.append(b)
        return
    if depth >= MAX_DEPTH:
        raise _Ineligible
    if t is list or t is tuple:
        out.append((b"L" if t is list else b"U") + _U32.pack(len(v)))
        for item in v:
            _py_encode_value(out, item, depth + 1, stamp)
        return
    if t is dict:
        entries = list(v.items())
        out.append(b"M" + _U32.pack(len(entries)))
        for k, val in entries:
            kt = type(k)
            if kt is not str and kt is not bytes:
                raise _Ineligible
            _py_encode_value(out, k, depth + 1, None)
            if (stamp is not None and not stamp[2] and k == "fr"
                    and type(val) is list):
                # fold the stage stamp into the encoded list (first
                # "fr" in pre-order only, matching the C encoder)
                stamp[2] = True
                out.append(b"L" + _U32.pack(len(val) + 1))
                for item in val:
                    _py_encode_value(out, item, depth + 2, None)
                _py_encode_value(out, (stamp[0], stamp[1]), depth + 2,
                                 None)
            else:
                _py_encode_value(out, val, depth + 1, stamp)
        return
    raise _Ineligible


def py_encode_payload(msg: dict, stamp: Optional[str] = None,
                      now: Optional[float] = None) -> Optional[bytes]:
    """dict → tagged frame payload, or None when any value falls
    outside the wire universe (caller then pickles as before)."""
    if type(msg) is not dict:
        return None
    st = None
    if stamp is not None:
        st = [stamp, time.monotonic() if now is None else now, False]
    out = [TAG]
    try:
        _py_encode_value(out, msg, 0, st)
    except _Ineligible:
        return None
    return b"".join(out)


def py_encode_frame(msg: dict, stamp: Optional[str] = None,
                    now: Optional[float] = None) -> Optional[bytes]:
    """Complete wire frame: 8-byte length prefix + tagged payload."""
    payload = py_encode_payload(msg, stamp, now)
    if payload is None:
        return None
    return _HDR.pack(len(payload)) + payload


class FrameError(ValueError):
    """Malformed 0x03 frame (truncated, bad tag, bad nesting)."""


def _py_decode_value(mv: memoryview, pos: int, depth: int):
    if pos >= len(mv):
        raise FrameError("truncated frame")
    tag = mv[pos]
    pos += 1
    if tag == 0x4E:          # 'N'
        return None, pos
    if tag == 0x54:          # 'T'
        return True, pos
    if tag == 0x46:          # 'F'
        return False, pos
    if tag == 0x49:          # 'I'
        if pos + 8 > len(mv):
            raise FrameError("truncated int")
        return _I64.unpack_from(mv, pos)[0], pos + 8
    if tag == 0x44:          # 'D'
        if pos + 8 > len(mv):
            raise FrameError("truncated float")
        return _F64.unpack_from(mv, pos)[0], pos + 8
    if tag in (0x42, 0x53):  # 'B' / 'S'
        if pos + 4 > len(mv):
            raise FrameError("truncated length")
        (n,) = _U32.unpack_from(mv, pos)
        pos += 4
        if pos + n > len(mv):
            raise FrameError("truncated body")
        raw = bytes(mv[pos:pos + n])
        pos += n
        if tag == 0x53:
            try:
                return raw.decode("utf-8"), pos
            except UnicodeDecodeError as e:
                raise FrameError(f"bad utf-8: {e}") from None
        return raw, pos
    if depth >= MAX_DEPTH:
        raise FrameError("frame nests too deep")
    if tag in (0x4C, 0x55):  # 'L' / 'U'
        if pos + 4 > len(mv):
            raise FrameError("truncated count")
        (n,) = _U32.unpack_from(mv, pos)
        pos += 4
        items = []
        for _ in range(n):
            item, pos = _py_decode_value(mv, pos, depth + 1)
            items.append(item)
        return (items if tag == 0x4C else tuple(items)), pos
    if tag == 0x4D:          # 'M'
        if pos + 4 > len(mv):
            raise FrameError("truncated count")
        (n,) = _U32.unpack_from(mv, pos)
        pos += 4
        d = {}
        for _ in range(n):
            k, pos = _py_decode_value(mv, pos, depth + 1)
            if type(k) is not str and type(k) is not bytes:
                raise FrameError("map key must be str or bytes")
            d[k], pos = _py_decode_value(mv, pos, depth + 1)
        return d, pos
    raise FrameError(f"unknown value tag {tag:#x}")


def py_decode_payload(data) -> dict:
    """Tagged frame payload (0x03 byte included) → dict.  Always
    available: a peer with the native codec armed must interoperate
    with a process running the pure-Python fallback."""
    mv = memoryview(data)
    if len(mv) < 1 or mv[0] != 0x03:
        raise FrameError("not an rt-frames payload")
    obj, pos = _py_decode_value(mv, 1, 0)
    if pos != len(mv):
        raise FrameError(f"{len(mv) - pos} trailing bytes")
    if type(obj) is not dict:
        raise FrameError("top-level value must be a map")
    return obj


def _stamp_walk(v, entry: tuple, depth: int) -> bool:
    """EXACT mirror of the encoders' stamp-fold traversal: pre-order
    over dict entries in insertion order, descending into dict/list/
    tuple VALUES before later keys, stamping the first str-keyed
    ``"fr"`` whose value is an exact list."""
    if depth >= MAX_DEPTH:
        return False
    t = type(v)
    if t is dict:
        for k, val in v.items():
            if k == "fr" and type(k) is str and type(val) is list:
                val.append(entry)
                return True
            if _stamp_walk(val, entry, depth + 1):
                return True
        return False
    if t is list or t is tuple:
        return any(_stamp_walk(item, entry, depth + 1) for item in v)
    return False


def py_stamp(msg: dict, stage: str, now: Optional[float] = None) -> None:
    """Python-side mirror of the encoder's stamp fold: append
    ``(stage, t)`` to the same ``"fr"`` list the native/py encoders
    would have stamped (first match in their pre-order walk).  Used
    when a stamped encode falls back to pickle so the stamp is neither
    lost nor lands on a different list than the native path's."""
    if type(msg) is dict:
        _stamp_walk(msg, (stage, time.monotonic() if now is None else now),
                    0)


autoenable_from_env()
