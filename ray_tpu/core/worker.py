"""Worker process entry point.

The analogue of the reference's default_worker.py (reference:
python/ray/_private/workers/default_worker.py + worker.py main_loop:764):
connect to the node service, register, and block in the execution loop.
Spawned by the node service's worker pool (JAX forced to CPU so the driver
keeps TPU ownership — see node.py _spawn_worker_proc).
"""

from __future__ import annotations

import argparse
import sys


def _install_jax_cpu_pin() -> None:
    """Meta-path hook: pin jax to the CPU platform as soon as it finishes
    importing, no matter what platform plugins do with JAX_PLATFORMS."""
    import importlib.util
    import types

    class _JaxCpuPin:
        _busy = False

        def find_spec(self, name, path=None, target=None):
            if name != "jax" or _JaxCpuPin._busy:
                return None
            _JaxCpuPin._busy = True
            try:
                spec = importlib.util.find_spec(name)
            finally:
                _JaxCpuPin._busy = False
            if spec is None or spec.loader is None:
                return None
            orig = spec.loader

            def exec_module(module):
                orig.exec_module(module)
                try:
                    module.config.update("jax_platforms", "cpu")
                except Exception:
                    pass

            spec.loader = types.SimpleNamespace(
                create_module=orig.create_module, exec_module=exec_module)
            return spec

    sys.meta_path.insert(0, _JaxCpuPin())


def run_worker(address: str) -> None:
    """Connect to the node service and block in the execution loop.
    Shared by the cold-spawn path (main below) and the fork-server
    children (core/prefork.py)."""
    # Workers must not touch the TPU (the driver owns it).  The spawner
    # sets JAX_PLATFORMS=cpu, but ambient platform plugins can override
    # the env var, so pin via jax.config too: immediately if jax is
    # already imported (sitecustomize pre-import), else via a post-import
    # hook the moment user code imports it.  Avoid importing jax
    # ourselves: it adds ~1-2s spawn latency for pure-CPU workloads.
    if "jax" in sys.modules:
        try:
            sys.modules["jax"].config.update("jax_platforms", "cpu")
        except Exception:
            pass
    else:
        _install_jax_cpu_pin()

    # on-demand stack dumps (reference: `ray stack` /
    # dashboard/modules/reporter/profile_manager.py): SIGUSR1 makes the
    # worker write every thread's stack to its .err log, even mid-task
    import faulthandler
    import signal
    try:
        faulthandler.register(signal.SIGUSR1, file=sys.stderr,
                              all_threads=True)
    except (AttributeError, ValueError):
        pass   # non-POSIX or non-main-thread: dumps unavailable

    from ray_tpu.core import fault_injection, flight_recorder
    from ray_tpu.core.client import NodeClient
    from ray_tpu.core.executor import (Executor, make_message_queue,
                                       queue_push_handler)
    from ray_tpu.core import runtime as rt

    fault_injection.autoinstall_from_env()   # chaos plane in workers
    flight_recorder.autoenable_from_env()    # lifecycle stamps in workers

    inbox = make_message_queue()
    cell: dict = {}
    client = NodeClient(address, kind="worker",
                        push_handler=queue_push_handler(inbox, cell))
    cell["client"] = client
    executor = Executor(client, msg_queue=inbox, threaded_actors=True)

    # Make the public API (ray_tpu.get/put/remote/...) work inside tasks.
    rt.attach_worker_runtime(client, executor)

    try:
        executor.run_loop()
    except KeyboardInterrupt:
        pass
    finally:
        client.close()


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--address", required=True)
    parser.add_argument("--session", required=True)
    args = parser.parse_args()
    run_worker(args.address)
    sys.exit(0)


if __name__ == "__main__":
    main()
