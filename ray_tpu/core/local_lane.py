"""In-process loopback transport ("lane") for the control plane.

When both endpoints of a control-plane link live in one process — the
driver talking to the node thread it started (``ray_tpu.init()``), the
in-process TPU executor, or every node/head of a virtual cluster
(``cluster_utils``) — the socket stack is pure overhead: each message
pays encode + sendall + select wakeup + recv + decode, and on the
client side an extra receive-thread hop, for bytes that never leave the
process.  A lane hands the message OBJECT across threads instead: sends
post straight onto the service's event loop, and service→client pushes
run a deliver callback (or land in a queue) with no serialization and
no syscalls.  This is the loopback analogue of the reference's
same-process direct-call fast path (reference: core_worker.cc submits
to local raylet over a unix socket; in-process work skips the RPC
stack entirely).

Lane endpoints keep the ``protocol.Connection`` surface (``send`` /
``send_batch`` / ``send_blob`` / ``recv`` / ``close``), so callers are
transport-agnostic: ``protocol.connect`` returns a lane whenever the
target address is a service registered in THIS process.

Isolation: inter-service links (node↔head, node↔node; ``copy=True``)
pickle-roundtrip each message because both sides mutate and retain
specs — exactly the isolation a socket gave them, minus the syscalls
and wakeups.  Client links (driver/TPU-executor ↔ node) share the
objects directly; the client never mutates a message after send.
"""

from __future__ import annotations

import os
import pickle
import queue
import sys
import threading
import traceback
from typing import Callable, Optional

from ray_tpu.core import fault_injection as _fi

# address -> EventLoopService living in this process.  Services register
# at startup and unregister at cleanup; a hit proves the peer is local.
_services: dict = {}
_lock = threading.Lock()


def register_service(svc) -> None:
    with _lock:
        _services[svc.address] = svc


def unregister_service(svc) -> None:
    with _lock:
        if _services.get(svc.address) is svc:
            del _services[svc.address]


def lookup(address: str):
    with _lock:
        return _services.get(address)


def enabled() -> bool:
    return os.environ.get("RAY_TPU_LOCAL_LANE", "1").lower() \
        not in ("0", "false", "no")


class _LaneSock:
    """Socket stand-in for lane ClientRecs — the event loop never
    selects on it, but generic cleanup paths call these."""

    def close(self) -> None:
        pass

    def setblocking(self, flag: bool) -> None:
        pass

    def sendall(self, data) -> None:
        pass


_CLOSED = object()


class LaneConnection:
    """Client-side endpoint of an in-process lane to one service."""

    encoding = "pickle"   # Connection-surface parity; never used to encode

    def __init__(self, svc, copy: bool = False,
                 label: Optional[tuple] = None):
        self._svc = svc
        self._copy = copy
        # chaos-plane link label (core/fault_injection.py); lanes carry
        # the same label surface as socket Connections so partitions
        # and message rules apply to in-process links too
        self.fi_label = label or ("lane", getattr(svc, "name", "?"))
        self._rx: queue.SimpleQueue = queue.SimpleQueue()
        # service→client fast path: when set, pushes are delivered by
        # calling this on the SERVICE LOOP THREAD (must be quick and
        # never block) instead of landing in the recv queue
        self.deliver: Optional[Callable[[dict], None]] = None
        self.on_close: Optional[Callable[[], None]] = None
        self._closed = threading.Event()
        self.rec = None
        svc._attach_lane(self)   # populates self.rec (waits on the loop)

    @property
    def sock(self):   # Connection-surface parity (never selected on)
        return None

    # ------------------------------------------------- client -> service

    def _iso(self, msg: dict) -> dict:
        if self._copy:
            return pickle.loads(pickle.dumps(msg, protocol=5))
        return msg

    def send(self, msg: dict) -> None:
        self._post([self._iso(msg)])

    def send_batch(self, msgs: list) -> None:
        self._post([self._iso(m) for m in msgs])

    def send_blob(self, meta: dict, data) -> None:
        m = dict(meta)
        m["data"] = bytes(data) if self._copy else data
        self._post([m])

    def _post(self, msgs: list) -> None:
        from ray_tpu.core.protocol import ConnectionClosed
        if self._closed.is_set():
            raise ConnectionClosed("lane closed")
        if _fi._active is not None:
            from ray_tpu.core.protocol import _chaos_filter
            msgs = _chaos_filter(self.fi_label, msgs)
            if not msgs:
                return
        svc, rec = self._svc, self.rec

        def run():
            if rec.closed or svc.clients.get(rec.conn_id) is not rec:
                return
            for m in msgs:
                svc._dispatch(rec, m)
        svc.post(run)

    # ------------------------------------------------- service -> client

    def _deliver(self, msg: dict) -> None:
        """Runs on the service loop thread (from _push)."""
        if _fi._active is not None:
            v = _fi._active.message_verdict("deliver", self.fi_label, msg)
            if v == "drop":
                return
            if v == "dup":
                self._deliver_one(msg)
            elif type(v) is tuple:
                # stalls the SERVICE loop: a slow consumer backpressures
                # its server exactly like a wedged socket peer would
                _fi.apply_delay(v[1])
        self._deliver_one(msg)

    def _deliver_one(self, msg: dict) -> None:
        if self._copy:
            # inter-service links isolate BOTH directions: a pushed view
            # or spec may reference the sender's live mutable state
            # (e.g. the head's per-node availability dicts), and the
            # receiver mutates specs it admits
            msg = pickle.loads(pickle.dumps(msg, protocol=5))
        cb = self.deliver
        if cb is not None:
            try:
                cb(msg)
            except Exception:
                sys.stderr.write("[lane] deliver callback failed:\n"
                                 + traceback.format_exc())
        else:
            self._rx.put(msg)

    def set_deliver(self, cb: Callable[[dict], None]) -> None:
        """Switch to direct delivery AFTER some recv() use (e.g. a
        bootstrap handshake).  The swap runs on the service loop thread
        — the only thread that delivers — so queued messages drain to
        `cb` strictly before any later direct delivery."""
        def swap():
            while True:
                try:
                    msg = self._rx.get_nowait()
                except queue.Empty:
                    break
                if msg is _CLOSED:
                    self._rx.put(_CLOSED)
                    break
                try:
                    cb(msg)
                except Exception:
                    sys.stderr.write("[lane] deliver callback failed:\n"
                                     + traceback.format_exc())
            self.deliver = cb
        self._svc.post(swap)

    def recv(self, timeout: Optional[float] = None) -> dict:
        import socket as _socket
        try:
            msg = self._rx.get(timeout=timeout)
        except queue.Empty:
            raise _socket.timeout("lane recv timed out") from None
        if msg is _CLOSED:
            from ray_tpu.core.protocol import ConnectionClosed
            self._rx.put(_CLOSED)   # keep the sentinel for other waiters
            raise ConnectionClosed("lane closed")
        return msg

    # ------------------------------------------------------------ close

    def _mark_closed(self) -> None:
        """Either side closed: wake recv()ers and tell the owner."""
        if self._closed.is_set():
            return
        self._closed.set()
        self._rx.put(_CLOSED)
        cb = self.on_close
        if cb is not None:
            try:
                cb()
            except Exception:
                sys.stderr.write("[lane] on_close callback failed:\n"
                                 + traceback.format_exc())

    def close(self) -> None:
        if self._closed.is_set():
            return
        self._mark_closed()
        svc, rec = self._svc, self.rec
        if rec is not None:
            svc.post(lambda: svc._drop_client(rec))
