"""Task-lifecycle flight recorder: per-stage timestamps → histograms.

The control plane can tell you *that* a task took 5 ms round-trip but
not *where* the milliseconds went — client serialization? the head's
routing hop?  queueing behind a saturated worker pool?  This module is
the Dapper-style answer scoped to one framework: every task carries a
list of ``(stage, t_monotonic)`` stamps through its whole journey

    submit → encode → node_recv → [forward → head_route → node_recv]
    → enqueue → dispatch → worker_recv → exec_start → exec_end
    → result_store → done

and the node that sees ``task_done`` folds the per-stage deltas into
log-bucketed latency histograms (exported as real Prometheus
``histogram`` metrics via ``ray_tpu.metrics``) plus a bounded ring of
completed lifecycle records for the ``ray_tpu timeline`` Perfetto
export.  The reference ships the same capability split across
``ray.timeline()`` and the per-stage metrics agent
(python/ray/_private/metrics_agent.py).

Zero-overhead contract (same ``is None`` discipline as
``core/fault_injection.py``): when no recorder is armed — the default,
production state — every control-plane hook is a single module-global
``is None`` check and nothing else executes on the hot path.  Worker-
side hooks are *data-driven* instead: they stamp only when the spec
already carries a record (one ``dict.get`` per execution), so pooled
workers spawned before the recorder was armed still participate.

Clocks: stamps are ``time.monotonic()``.  On Linux CLOCK_MONOTONIC is
system-wide, so same-host stamps from different processes (driver,
node, workers) are directly comparable — exactly the committed-artifact
use case.  Each record also carries one wall-clock anchor (``fr_w0``)
taken at the first stamp so timelines can be exported in epoch time.
"""

from __future__ import annotations

import os
import threading
import time
from bisect import bisect_left
from collections import deque
from typing import Any, Dict, List, Optional

# The armed recorder.  Hot paths read this module attribute directly
# (``_active is not None``) so the disabled path costs one global load.
_active: Optional["FlightRecorder"] = None


def active() -> Optional["FlightRecorder"]:
    return _active


def enable(**kw) -> "FlightRecorder":
    """Arm a recorder in this process (idempotent) and mark the env so
    processes spawned from here arm themselves too."""
    global _active
    if _active is None:
        _active = FlightRecorder(**kw)
    os.environ["RAY_TPU_FLIGHT_RECORDER"] = "1"
    return _active


def disable() -> None:
    global _active
    _active = None
    os.environ.pop("RAY_TPU_FLIGHT_RECORDER", None)


def autoenable_from_env() -> None:
    """Arm at process startup when the ``flight_recorder`` config flag
    (env: RAY_TPU_FLIGHT_RECORDER) says so — the worker/node leg of the
    cross-process story (mirrors fault_injection.autoinstall_from_env)."""
    if _active is not None:
        return
    raw = os.environ.get("RAY_TPU_FLIGHT_RECORDER", "")
    if raw.lower() in ("1", "true", "yes", "on"):
        enable()


# Log-bucketed bounds: 1 µs doubling up to ~67 s.  Latency spans six
# orders of magnitude between a lane hand-off and a cold container
# spawn; exponential buckets keep resolution proportional everywhere.
BUCKET_BOUNDS: tuple = tuple(1e-6 * (2.0 ** k) for k in range(27))


class Histogram:
    """One log-bucketed latency histogram (Prometheus ``histogram``
    semantics: cumulative ``le`` buckets + sum + count)."""

    __slots__ = ("counts", "sum", "count")

    def __init__(self):
        self.counts = [0] * (len(BUCKET_BOUNDS) + 1)
        self.sum = 0.0
        self.count = 0

    def observe(self, v: float) -> None:
        self.counts[bisect_left(BUCKET_BOUNDS, v)] += 1
        self.sum += v
        self.count += 1

    def snapshot(self) -> dict:
        """Cumulative exposition form for metrics.render_prometheus."""
        cum = 0
        buckets: List[tuple] = []
        for bound, c in zip(BUCKET_BOUNDS, self.counts):
            cum += c
            buckets.append((bound, cum))
        buckets.append((float("inf"), cum + self.counts[-1]))
        return {"buckets": buckets, "sum": self.sum, "count": self.count}


def _quantile(sorted_vals: list, q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, int(q * (len(sorted_vals) - 1) + 0.5))
    return sorted_vals[idx]


class FlightRecorder:
    """Per-process aggregation point: histograms + p50/p99 samples per
    stage, a ring of completed lifecycle records, and chaos (fault-
    injection) events for the merged timeline."""

    def __init__(self, keep_records: int = 4096,
                 keep_samples: int = 20_000,
                 keep_faults: int = 4096,
                 keep_ingress: int = 8192):
        self.anchor_wall = time.time()
        self.anchor_mono = time.monotonic()
        self._lock = threading.Lock()
        self.hist: Dict[str, Histogram] = {}
        self.samples: Dict[str, deque] = {}
        self.records: deque = deque(maxlen=keep_records)
        self.faults: deque = deque(maxlen=keep_faults)
        self.ingress: deque = deque(maxlen=keep_ingress)
        self._keep_samples = keep_samples

    # ------------------------------------------------------------ stamping
    #
    # start() runs on the submitting client; stamp() everywhere else.
    # Both are called ONLY behind the module-global gate (or, worker
    # side, only when the spec already carries a record), so they can
    # afford the list append + monotonic call.

    def start(self, spec: dict, stage: str = "submit") -> None:
        spec["fr"] = [(stage, time.monotonic())]
        spec["fr_w0"] = time.time()

    @staticmethod
    def stamp(spec: dict, stage: str) -> None:
        fr = spec.get("fr")
        if fr is not None:
            fr.append((stage, time.monotonic()))

    def start_or_stamp(self, spec: dict, stage: str) -> None:
        """Continue the submitter's record, or open one at this stage
        when the submitter had no recorder armed (remote drivers)."""
        if spec.get("fr") is None:
            self.start(spec, stage)
        else:
            spec["fr"].append((stage, time.monotonic()))

    # --------------------------------------------------------- aggregation

    def observe(self, stage: str, seconds: float) -> None:
        with self._lock:
            h = self.hist.get(stage)
            if h is None:
                h = self.hist[stage] = Histogram()
                self.samples[stage] = deque(maxlen=self._keep_samples)
            h.observe(seconds)
            self.samples[stage].append(seconds)

    def finish(self, spec: dict, worker: Any = None) -> None:
        """Fold one completed lifecycle into the aggregates.  Interval
        names follow the LATER stamp: ``dispatch`` = time from enqueue
        (or whatever preceded) until the dispatch stamp."""
        fr = spec.get("fr")
        if not fr or len(fr) < 2:
            return
        w0 = spec.get("fr_w0") or self.anchor_wall
        record = {
            "task_id": spec["task_id"].hex()
            if isinstance(spec.get("task_id"), bytes)
            else str(spec.get("task_id")),
            "name": spec.get("name", ""),
            "worker": worker,
            "start_ts": w0,
            # wall-clock stage stamps: first stamp anchors at w0
            "stages": [(s, w0 + (t - fr[0][1])) for s, t in fr],
        }
        with self._lock:
            self.records.append(record)
        prev_t = fr[0][1]
        for stage, t in fr[1:]:
            self.observe(stage, max(0.0, t - prev_t))
            prev_t = t
        self.observe("total", max(0.0, fr[-1][1] - fr[0][1]))

    def note_fault(self, point: str, action: str, detail: Any) -> None:
        """Chaos-plane event (core/fault_injection.py) for the merged
        timeline — injected faults show up attributed, not as mystery
        latency."""
        with self._lock:
            self.faults.append({"t": time.time(), "point": point,
                                "action": action, "detail": repr(detail)})

    def note_ingress(self, event: dict) -> None:
        """Serve-fleet ingress event (admit/shed/route/resume/scale —
        serve/fleet/ingress.py) for the merged timeline, so admission
        decisions show up next to the task stages and chaos events they
        interleave with."""
        with self._lock:
            self.ingress.append(dict(event))

    def reset(self) -> None:
        """Drop aggregates (between benchmark phases)."""
        with self._lock:
            self.hist.clear()
            self.samples.clear()
            self.records.clear()
            self.faults.clear()
            self.ingress.clear()

    # ------------------------------------------------------------- reading

    def stage_summary(self) -> dict:
        """{stage: {n, p50_us, p99_us, mean_us}} from the bounded raw
        samples — the committed-artifact table."""
        with self._lock:
            snap = {k: list(v) for k, v in self.samples.items()}
        out = {}
        for stage, vals in sorted(snap.items()):
            vals.sort()   # outside the lock: hot-path observes proceed
            if not vals:
                continue
            out[stage] = {
                "n": len(vals),
                "p50_us": round(_quantile(vals, 0.50) * 1e6, 1),
                "p99_us": round(_quantile(vals, 0.99) * 1e6, 1),
                "mean_us": round(sum(vals) / len(vals) * 1e6, 1),
            }
        return out

    def export_records(self, limit: int = 2000) -> list:
        with self._lock:
            recs = list(self.records)
        return recs[-limit:]

    def export_faults(self) -> list:
        with self._lock:   # note_fault appends from other threads
            return list(self.faults)

    def export_ingress(self) -> list:
        with self._lock:   # note_ingress appends from serving threads
            return list(self.ingress)

    def metrics_snapshot(self) -> Dict[tuple, dict]:
        """{((label_key, label_val),): histogram_snapshot} for the
        Prometheus exporter (metrics.render_prometheus histogram kind).
        Snapshots are taken under the lock so a mid-scrape observe()
        can't make the exported _count disagree with the +Inf bucket."""
        with self._lock:
            return {(("stage", stage),): h.snapshot()
                    for stage, h in self.hist.items()}
