"""Device-resident object store entries (HBM objects).

TPU design delta (SURVEY.md §7 delta 5 / hard part 2) — and a capability
the reference does NOT have: plasma is host-only
(src/ray/object_manager/plasma/store.h:55), so every torch-tensor put
crosses to host RAM.  Here ``put()`` of a value containing jax.Arrays
keeps the device buffers exactly where they are:

  * the pickle stream captures each jax.Array leaf as a PLACEHOLDER and
    the leaves stay in this process's DeviceObjectTable — no device→host
    transfer, no host copy;
  * the node records a ``device`` entry (descriptor bytes + owning
    client connection);
  * ``get()`` in the owning process splices the SAME array objects back
    into a fresh container — zero-copy, HBM never touched;
  * ``get()`` from another process triggers materialize-on-demand: the
    node asks the owner to serialize the value to the host store once,
    after which it is an ordinary shm/inline object;
  * a per-process HBM budget (``RAY_TPU_DEVICE_OBJECT_BUDGET_MB``)
    spills the oldest entries to host ONLY under pressure;
  * the owner process dying turns its entries into lost objects, which
    flow through the existing owner-based reconstruction path.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Optional

_splice = threading.local()


def try_jax_array_types():
    """(jax.Array, Tracer) when jax is importable, else None."""
    try:
        import jax
        return jax.Array, jax.core.Tracer
    except Exception:  # pragma: no cover - jax is baked into this image
        return None


def _device_leaf(i: int):
    """Unpickle hook for a captured leaf: splice from the thread-local
    leaf list installed by deserialize_with_leaves."""
    leaves = getattr(_splice, "leaves", None)
    if leaves is None:
        raise RuntimeError(
            "device-resident object deserialized outside its owner "
            "process without materialization")
    return leaves[i]


def set_splice_leaves(leaves: Optional[list]) -> None:
    _splice.leaves = leaves


class DeviceObjectTable:
    """Per-process table of device-resident entries.

    entry = {"leaves": [jax.Array...], "descriptor": bytes, "nbytes": int}
    Ordered oldest-first so budget spills evict LRU-by-insertion.
    """

    def __init__(self, budget_bytes: Optional[int] = None):
        self._entries: "OrderedDict[bytes, dict]" = OrderedDict()
        self._lock = threading.RLock()
        self.budget_bytes = budget_bytes  # None = unlimited
        self.nbytes = 0

    def put(self, oid_bin: bytes, leaves: list, descriptor: bytes) -> list:
        """Insert; returns oid_bins that must be spilled to honor the
        budget (caller materializes them — the table can't, it has no
        client)."""
        nb = sum(int(getattr(a, "nbytes", 0) or 0) for a in leaves)
        with self._lock:
            old = self._entries.pop(oid_bin, None)
            if old is not None:
                self.nbytes -= old["nbytes"]
            self._entries[oid_bin] = {"leaves": leaves,
                                      "descriptor": descriptor,
                                      "nbytes": nb}
            self.nbytes += nb
            to_spill = []
            if self.budget_bytes is not None:
                for ob, e in self._entries.items():
                    if self.nbytes <= self.budget_bytes or ob == oid_bin:
                        break
                    to_spill.append(ob)
                    self.nbytes -= e["nbytes"]  # accounted as gone now
                # re-add the bytes; pop happens when the spill completes
                for ob in to_spill:
                    self.nbytes += self._entries[ob]["nbytes"]
            return to_spill

    def leaves(self, oid_bin: bytes) -> Optional[list]:
        with self._lock:
            e = self._entries.get(oid_bin)
            return None if e is None else e["leaves"]

    def descriptor(self, oid_bin: bytes) -> Optional[bytes]:
        with self._lock:
            e = self._entries.get(oid_bin)
            return None if e is None else e["descriptor"]

    def pop(self, oid_bin: bytes) -> None:
        with self._lock:
            e = self._entries.pop(oid_bin, None)
            if e is not None:
                self.nbytes -= e["nbytes"]

    def __contains__(self, oid_bin: bytes) -> bool:
        with self._lock:
            return oid_bin in self._entries

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)
