"""Typed wire schema: dict messages ⇄ protobuf.

Reference capability: src/ray/protobuf/ (N20 — 22 .proto files typing
every RPC). The contract lives in native/protos/ray_tpu.proto
(compiled into ray_tpu/core/generated/); this module converts the
live control-plane dict messages to and from those protos.

The transport (core/protocol.py) still frames pickled dicts — the
conversion layer is exercised in CI on real traffic shapes so the
encoding can flip to protobuf (or the surface be served over gRPC)
without touching callers. Messages without a dedicated proto ride the
`Raw` envelope (typed tag + pickled body), the same pattern the
reference uses for pickled task payloads inside typed protos.
"""

from __future__ import annotations

import os
import pickle
import sys
from typing import Any, Dict, Optional

_GEN = os.path.join(os.path.dirname(__file__), "generated")
if _GEN not in sys.path:
    sys.path.insert(0, _GEN)

import ray_tpu_pb2 as pb  # noqa: E402


def _dumps(v) -> bytes:
    import cloudpickle
    return cloudpickle.dumps(v)


def _loads(b: bytes):
    return pickle.loads(b)


# -- TaskSpec ------------------------------------------------------------

def spec_to_proto(spec: Dict[str, Any]) -> "pb.TaskSpec":
    p = pb.TaskSpec()
    p.task_id = spec.get("task_id", b"")
    p.kind = spec.get("kind", "task")
    p.name = spec.get("name", "")
    p.function_id = spec.get("function_id", "") or ""
    nr = spec.get("num_returns", 1)
    if nr == "dynamic":
        p.dynamic_returns = True
        p.num_returns = 1
    else:
        p.num_returns = int(nr)
    p.return_ids.extend(spec.get("return_ids", []))
    for k, v in (spec.get("resources") or {}).items():
        p.resources[k] = float(v)
    p.num_tpus = float(spec.get("num_tpus", 0))
    p.max_retries = int(spec.get("max_retries", 0))
    p.owner = spec.get("owner", "") or ""
    p.args_data = spec.get("args", b"") or b""
    p.arg_ids.extend(spec.get("arg_ids", []))
    if spec.get("arg_blob"):
        p.arg_blob = spec["arg_blob"]
    pg = spec.get("placement_group")
    if pg:
        p.placement_group_id = pg[0]
        p.placement_group_bundle = int(pg[1])
    if spec.get("runtime_env"):
        p.runtime_env_payload = _dumps(spec["runtime_env"])
    p.actor_id = spec.get("actor_id", b"")
    p.class_name = spec.get("class_name", "") or ""
    p.methods.extend(spec.get("methods", []))
    p.method = spec.get("method", "") or ""
    p.seq = int(spec.get("seq", 0))
    p.max_restarts = int(spec.get("max_restarts", 0))
    p.max_concurrency = int(spec.get("max_concurrency", 1))
    for k, v in (spec.get("concurrency_groups") or {}).items():
        p.concurrency_groups[k] = int(v)
    p.concurrency_group = spec.get("concurrency_group", "") or ""
    p.namespace = spec.get("namespace", "") or ""
    p.get_if_exists = bool(spec.get("get_if_exists", False))
    tctx = spec.get("trace_ctx") or {}
    p.trace_id = tctx.get("trace_id", "")
    p.span_id = tctx.get("span_id", "")
    if spec.get("owner_node"):
        p.owner_node.extend(spec["owner_node"])
    p.env_hash = spec.get("env_hash", "") or ""
    for b, onode in (spec.get("arg_owners") or {}).items():
        p.arg_owner_ids.append(b)
        p.arg_owner_locs.extend([onode[0], onode[1]])
    return p


def spec_from_proto(p: "pb.TaskSpec") -> Dict[str, Any]:
    spec: Dict[str, Any] = {
        "task_id": p.task_id,
        "kind": p.kind,
        "name": p.name,
        "function_id": p.function_id,
        "num_returns": "dynamic" if p.dynamic_returns else p.num_returns,
        "return_ids": list(p.return_ids),
        "resources": dict(p.resources),
        "num_tpus": p.num_tpus,
        "max_retries": p.max_retries,
        "owner": p.owner,
        "args": p.args_data,
        "arg_ids": list(p.arg_ids),
    }
    if p.arg_blob:
        spec["arg_blob"] = p.arg_blob
    if p.placement_group_id:
        spec["placement_group"] = (p.placement_group_id,
                                   p.placement_group_bundle)
    if p.runtime_env_payload:
        spec["runtime_env"] = _loads(p.runtime_env_payload)
    if p.kind in ("actor_create", "actor_task"):
        spec["actor_id"] = p.actor_id
    if p.kind == "actor_create":
        spec.update(class_name=p.class_name, methods=list(p.methods),
                    max_restarts=p.max_restarts,
                    max_concurrency=p.max_concurrency,
                    namespace=p.namespace, get_if_exists=p.get_if_exists)
        if p.concurrency_groups:
            spec["concurrency_groups"] = dict(p.concurrency_groups)
    if p.kind == "actor_task":
        spec.update(method=p.method, seq=p.seq)
        if p.concurrency_group:
            spec["concurrency_group"] = p.concurrency_group
    if p.trace_id:
        spec["trace_ctx"] = {"trace_id": p.trace_id,
                             "span_id": p.span_id}
    if p.owner_node:
        spec["owner_node"] = tuple(p.owner_node)
    if p.env_hash:
        spec["env_hash"] = p.env_hash
    if p.arg_owner_ids:
        spec["arg_owners"] = {
            b: (p.arg_owner_locs[2 * i], p.arg_owner_locs[2 * i + 1])
            for i, b in enumerate(p.arg_owner_ids)}
    return spec


# -- message envelope ----------------------------------------------------

# dict "t" tag → (oneof field name, to_proto, from_proto)
def _simple(field: str, keys: Dict[str, str], bin_lists=(), payloads=()):
    """Builder for flat messages: keys maps dict key → proto field."""

    def to_proto(m: dict, env: "pb.Message"):
        sub = getattr(env, field)
        for dk, pk in keys.items():
            if dk in m and m[dk] is not None:
                setattr(sub, pk, m[dk])
        for dk in bin_lists:
            getattr(sub, dk).extend(m.get(dk, []))
        for dk in payloads:
            if m.get(dk) is not None:
                setattr(sub, dk + "_payload", _dumps(m[dk]))

    def from_proto(env: "pb.Message") -> dict:
        sub = getattr(env, field)
        out = {}
        for dk, pk in keys.items():
            out[dk] = getattr(sub, pk)
        for dk in bin_lists:
            out[dk] = list(getattr(sub, dk))
        for dk in payloads:
            blob = getattr(sub, dk + "_payload")
            out[dk] = _loads(blob) if blob else None
        return out

    return field, to_proto, from_proto


_TABLE: Dict[str, tuple] = {
    "register": _simple("register", {"kind": "kind",
                                     "worker_id": "worker_id",
                                     "pid": "pid", "tpu": "tpu",
                                     "node_hex": "node_hex"}),
    "put_inline": _simple("put_inline", {"object_id": "object_id",
                                         "data": "data",
                                         "is_error": "is_error",
                                         "owner": "owner"},
                          bin_lists=("nested_refs",)),
    "get_objects": _simple("get_objects", {}, bin_lists=("object_ids",)),
    "free_objects": _simple("free_objects", {},
                            bin_lists=("object_ids",)),
    "release_pins": _simple("release_pins", {},
                            bin_lists=("object_ids",)),
    "release_refs": _simple("release_refs", {},
                            bin_lists=("object_ids",)),
    "task_done": _simple("task_done", {"task_id": "task_id",
                                       "error": "error"}),
    "kill_actor": _simple("kill_actor", {"actor_id": "actor_id",
                                         "no_restart": "no_restart"}),
    "kv_put": _simple("kv_put", {"key": "key", "value": "value",
                                 "overwrite": "overwrite",
                                 "namespace": "namespace"}),
    "kv_get": _simple("kv_get", {"key": "key", "namespace": "namespace"}),
    "kv_del": _simple("kv_del", {"key": "key", "namespace": "namespace"}),
    "subscribe": _simple("subscribe", {"channel": "channel"}),
}


def message_to_proto(m: Dict[str, Any]) -> "pb.Message":
    """One live control-plane dict → typed envelope."""
    env = pb.Message()
    if "reqid" in m:
        env.reqid = int(m["reqid"])
        env.has_reqid = True
    t = m.get("t", "")
    if t in ("submit_task", "submit_actor_task", "create_actor"):
        env.submit_task.spec.CopyFrom(spec_to_proto(m["spec"]))
        return env
    if t == "wait":
        env.wait.object_ids.extend(m.get("object_ids", []))
        env.wait.num_returns = int(m.get("num_returns", 1))
        if m.get("timeout") is not None:
            env.wait.timeout = float(m["timeout"])
            env.wait.has_timeout = True
        return env
    if t == "publish":
        env.publish.channel = m.get("channel", "")
        env.publish.payload = _dumps(m.get("data"))
        return env
    if t == "heartbeat":
        env.heartbeat.node_id = m.get("node_id", "")
        for field_name in ("available", "total", "queued"):
            dst = getattr(env.heartbeat, field_name)
            for k, v in (m.get(field_name) or {}).items():
                dst[k] = float(v)
        env.heartbeat.seq = int(m.get("seq", 0))
        return env
    if t in _TABLE:
        field, to_proto, _ = _TABLE[t]
        getattr(env, field).SetInParent()   # select the oneof arm even
        to_proto(m, env)                    # when every field is empty
        return env
    # long tail: typed tag + pickled body
    env.raw.type = t
    env.raw.payload = _dumps({k: v for k, v in m.items()
                              if k not in ("t", "reqid")})
    return env


def message_from_proto(env: "pb.Message") -> Dict[str, Any]:
    body = env.WhichOneof("body")
    # fire-and-forget messages carry no reqid; materializing one would
    # flip the service's `"reqid" in m` reply gate for every such
    # message (and reqid=0 IS a valid first request id, hence the
    # explicit presence flag)
    out: Dict[str, Any] = {}
    if env.has_reqid:
        out["reqid"] = env.reqid
    if body == "submit_task":
        spec = spec_from_proto(env.submit_task.spec)
        t = {"task": "submit_task", "actor_create": "create_actor",
             "actor_task": "submit_actor_task"}[spec["kind"]]
        out.update(t=t, spec=spec)
        return out
    if body == "wait":
        out.update(t="wait", object_ids=list(env.wait.object_ids),
                   num_returns=env.wait.num_returns,
                   timeout=(env.wait.timeout if env.wait.has_timeout
                            else None))
        return out
    if body == "publish":
        out.update(t="publish", channel=env.publish.channel,
                   data=_loads(env.publish.payload))
        return out
    if body == "heartbeat":
        out.update(t="heartbeat", node_id=env.heartbeat.node_id,
                   available=dict(env.heartbeat.available),
                   total=dict(env.heartbeat.total),
                   queued=dict(env.heartbeat.queued),
                   seq=env.heartbeat.seq)
        return out
    if body == "raw":
        out.update(t=env.raw.type, **_loads(env.raw.payload))
        return out
    for t, (field, _, from_proto) in _TABLE.items():
        if body == field:
            out.update(t=t, **from_proto(env))
            return out
    raise ValueError(f"unmapped proto body {body!r}")


def encode(m: Dict[str, Any]) -> bytes:
    return message_to_proto(m).SerializeToString()


def decode(data: bytes) -> Dict[str, Any]:
    env = pb.Message()
    env.ParseFromString(data)
    return message_from_proto(env)
