"""Shared event-loop service base for the head and node services.

Single-threaded selector loop, framed-pickle connections, posted-callback
injection from other threads, and reqid-correlated RPC in BOTH directions:
incoming requests dispatch to ``_h_<type>`` handlers; incoming
``{"t": "reply"}`` frames resolve callbacks registered with ``_rpc``.
All state mutation happens on the loop thread.

The reference splits this substrate across its gRPC services
(src/ray/rpc/grpc_server.h, client_call.h); here one loop per service is
enough because bulk data rides the shared-memory plane, not this one.
"""

from __future__ import annotations

import heapq
import os
import pickle
import selectors
import socket
import struct
import sys
import threading
import time
import traceback
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from ray_tpu.core import fault_injection as _fi
from ray_tpu.core import protocol
from ray_tpu.core.ids import ActorID
from ray_tpu.core.protocol import dumps_frame

_HDR = struct.Struct("<Q")


@dataclass
class ClientRec:
    conn_id: int
    sock: socket.socket
    kind: str = ""               # driver | worker | tpu_executor | node | peer
    worker_id: str = ""
    pid: int = 0
    tpu: bool = False            # may execute TPU tasks
    state: str = "idle"          # idle | busy | blocked
    current_task: Optional[bytes] = None
    dedicated_actor: Optional[ActorID] = None
    rbuf: bytearray = field(default_factory=bytearray)
    wbuf: bytearray = field(default_factory=bytearray)
    held_pins: list = field(default_factory=list)
    closed: bool = False
    node_hex: str = ""           # for kind in (node, peer): peer node id
    encoding: str = "pickle"     # wire encoding this client speaks
    seen_envs: set = field(default_factory=set)  # runtime-env hashes run
    # image this worker was exec'd inside (runtime_env.container); ""
    # for plain host workers.  Container tasks only dispatch to a
    # matching-image worker and vice versa.
    container_image: str = ""
    # in-process clients (core/local_lane.py): pushes are handed over as
    # objects on the loop thread instead of being framed onto a socket
    lane: Any = None


_WAKER = object()   # selector sentinel for the self-pipe


def _NOOP() -> None:
    pass


class EventLoopService:
    """Base: listener + selector loop + push/reply plumbing."""

    name = "service"

    def __init__(self, listen_host: str = "127.0.0.1", port: int = 0):
        from ray_tpu.core import grpc_transport
        self._grpc_server = None
        grpc_mode = grpc_transport.transport() == "grpc"
        self.sel = selectors.DefaultSelector()
        self.listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # grpc mode: the selector keeps its loop on a private loopback
        # port and the PUBLIC address is the gRPC front that bridges
        # streams onto it (core/grpc_transport.py)
        self.listener.bind(("127.0.0.1", 0) if grpc_mode
                           else (listen_host, port))
        self.listener.listen(512)
        self.listener.setblocking(False)
        self.address = "%s:%d" % self.listener.getsockname()
        self.sel.register(self.listener, selectors.EVENT_READ, None)
        if grpc_mode:
            self._grpc_server, self.address = \
                grpc_transport.start_grpc_front(
                    self.address, host=listen_host, port=port)

        self._next_conn = 0
        self._extra_listeners: list = []   # (socket, unlink_path)
        self.clients: dict[int, ClientRec] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._posted: deque = deque()
        self._posted_lock = threading.Lock()
        self._last_tick = 0.0
        self.tick_interval = 0.25
        # observability: how late the last periodic tick ran vs its
        # schedule — a saturated loop (GIL-starved, handler stuck in a
        # long copy) shows up here before anything else degrades.
        # Exported as ray_tpu_event_loop_lag_seconds (metrics.py).
        self.loop_lag_s = 0.0
        # Opt-in adaptive busy-poll: for a short window after each event
        # the loop polls (select timeout=0) instead of blocking — on
        # hosts with spare cores and slow idle wakeups this skips a
        # cold epoll wake on every hot-path message (reference:
        # gRPC/DPDK-style busy polling).  Default OFF: on small hosts
        # the spinning loop steals cycles from the workers it serves
        # (measured 2x WORSE on a 2-core box).
        import os as _os
        self._spin_s = float(_os.environ.get("RAY_TPU_SPIN_US", "0")) / 1e6
        self._spin_until = 0.0
        # deferred callbacks (post_later): min-heap drained by the loop
        self._timers: list = []
        self._timer_seq = 0
        self._timer_lock = threading.Lock()
        # self-pipe waker: post() from another thread (peer receivers,
        # the head channel, timers) must interrupt select() NOW — waiting
        # out the poll timeout adds up to 50 ms to every cross-thread
        # event (object chunks, forwarded tasks, ...)
        self._waker_r, self._waker_w = socket.socketpair()
        self._waker_r.setblocking(False)
        self._waker_w.setblocking(False)
        self._wake_armed = False
        self.sel.register(self._waker_r, selectors.EVENT_READ, _WAKER)
        # outbound RPC correlation: reqid -> callback(reply_msg)
        self._rpc_seq = 0
        self._rpc_pending: dict[int, Callable[[dict], None]] = {}
        # same-process clients skip the socket stack entirely
        from ray_tpu.core import local_lane
        local_lane.register_service(self)
        # write coalescing: _push appends to rec.wbuf and the loop sends
        # each connection's accumulated frames in ONE syscall per
        # iteration — N small sends per event (task_done -> dispatch ->
        # waiter resolution ...) otherwise cost N syscalls + N GIL drops
        # + N receiver wakeups each
        self._cork_dirty: dict[int, ClientRec] = {}

    # ------------------------------------------------------------ threading

    def post(self, fn) -> None:
        with self._posted_lock:
            self._posted.append(fn)
            if not self._wake_armed:
                self._wake_armed = True
                try:
                    self._waker_w.send(b"x")
                except (BlockingIOError, OSError):
                    pass   # already saturated: the loop will wake anyway

    def post_later(self, delay: float, fn) -> None:
        """Run `fn` on the loop thread after ~`delay` seconds.  Timers
        ride the select timeout (a heap popped each iteration) — the
        previous per-call threading.Timer burned a whole thread
        start/join per deferred call, which at thousands of
        events/s was a measurable slice of the scheduler's CPU."""
        deadline = time.monotonic() + delay
        with self._timer_lock:
            self._timer_seq += 1
            heapq.heappush(self._timers, (deadline, self._timer_seq, fn))
            wake = self._timers[0][0] == deadline
        if wake and threading.current_thread() is not self._thread:
            # new earliest deadline: force a loop pass so the select
            # timeout shrinks to it
            self.post(_NOOP)

    def _run_due_timers(self, now: float) -> None:
        while True:
            with self._timer_lock:
                if not self._timers or self._timers[0][0] > now:
                    return
                _, _, fn = heapq.heappop(self._timers)
            try:
                fn()
            except Exception:
                sys.stderr.write(f"[{self.name}] timer callback failed:\n"
                                 + traceback.format_exc())

    def _next_timeout(self, now: float) -> float:
        with self._timer_lock:
            if not self._timers:
                return 0.05
            return min(0.05, max(0.0, self._timers[0][0] - now))

    def start_thread(self) -> None:
        self._thread = threading.Thread(target=self.run,
                                        name=f"raytpu-{self.name}",
                                        daemon=True)
        self._thread.start()

    def run(self) -> None:
        self._thread = threading.current_thread()   # enables write corking
        while not self._stop.is_set():
            with self._posted_lock:
                self._wake_armed = False
            while True:
                with self._posted_lock:
                    if not self._posted:
                        break
                    fn = self._posted.popleft()
                try:
                    fn()
                except Exception:
                    sys.stderr.write(f"[{self.name}] posted callback "
                                     "failed:\n" + traceback.format_exc())
            now = time.monotonic()
            self._run_due_timers(now)
            if now - self._last_tick > self.tick_interval:
                if self._last_tick:
                    self.loop_lag_s = max(
                        0.0, (now - self._last_tick) - self.tick_interval)
                self._last_tick = now
                try:
                    if _fi._active is not None:
                        # chaos plane: scripted per-tick triggers (e.g.
                        # "stop the head at tick N")
                        _fi._active.on_service_tick(self)
                    self.on_tick()
                except Exception:
                    sys.stderr.write(f"[{self.name}] tick error:\n"
                                     + traceback.format_exc())
            # everything the previous iteration (posted callbacks, tick,
            # event handlers) queued goes out now, one syscall per peer
            self._flush_corked()
            try:
                events = self.sel.select(
                    timeout=0 if now < self._spin_until
                    else self._next_timeout(now))
            except OSError:
                continue
            if events or self._posted:
                self._spin_until = time.monotonic() + self._spin_s
            for key, mask in events:
                if key.data is _WAKER:
                    try:
                        while self._waker_r.recv(4096):
                            pass
                    except (BlockingIOError, OSError):
                        pass
                elif key.data is None:
                    self._accept(key.fileobj)
                else:
                    rec: ClientRec = key.data
                    try:
                        if mask & selectors.EVENT_READ:
                            self._on_readable(rec)
                        if mask & selectors.EVENT_WRITE:
                            self._on_writable(rec)
                    except Exception:
                        sys.stderr.write(f"[{self.name}] connection handler "
                                         "error:\n" + traceback.format_exc())
                        try:
                            self._drop_client(rec)
                        except Exception:
                            sys.stderr.write(f"[{self.name}] drop_client "
                                             "error:\n"
                                             + traceback.format_exc())
        self._cleanup()

    def stop(self) -> None:
        self._stop.set()
        if self._grpc_server is not None:
            try:
                self._grpc_server.stop(0)
            except Exception:
                pass
        if (self._thread is not None
                and self._thread is not threading.current_thread()):
            self._thread.join(timeout=5)

    # hooks -----------------------------------------------------------------

    def on_tick(self) -> None:
        pass

    def on_client_drop(self, rec: ClientRec) -> None:
        pass

    def _cleanup(self) -> None:
        from ray_tpu.core import local_lane
        local_lane.unregister_service(self)
        for rec in list(self.clients.values()):
            try:
                self._push(rec, {"t": "shutdown"})
                self._flush(rec)
            except Exception:
                pass
        for rec in list(self.clients.values()):
            try:
                rec.sock.close()
            except OSError:
                pass
            if rec.lane is not None:
                rec.lane._mark_closed()
        self.listener.close()
        self._close_extra_listeners()
        for s in (self._waker_r, self._waker_w):
            try:
                s.close()
            except OSError:
                pass
        self.sel.close()

    # ----------------------------------------------------------------- io

    def add_unix_listener(self, path: str) -> str:
        """Second accept socket on a unix path — same-host clients
        (worker pool) skip the TCP loopback stack, which costs ~1.5x a
        unix send per message on some hosts (reference: the raylet
        serves local workers over a unix socket, node_manager.cc)."""
        lst = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            os.unlink(path)
        except FileNotFoundError:
            pass
        lst.bind(path)
        lst.listen(512)
        lst.setblocking(False)
        self.sel.register(lst, selectors.EVENT_READ, None)
        self._extra_listeners.append((lst, path))
        return "unix://" + path

    def _accept(self, listener=None) -> None:
        lst = listener if listener is not None else self.listener
        try:
            sock, _ = lst.accept()
        except OSError:
            return
        sock.setblocking(False)
        if sock.family != socket.AF_UNIX:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._next_conn += 1
        rec = ClientRec(conn_id=self._next_conn, sock=sock)
        self.clients[rec.conn_id] = rec
        self.sel.register(sock, selectors.EVENT_READ, rec)

    def _on_readable(self, rec: ClientRec) -> None:
        try:
            data = rec.sock.recv(1 << 20)
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            self._drop_client(rec)
            return
        if not data:
            self._drop_client(rec)
            return
        rec.rbuf += data
        while True:
            if len(rec.rbuf) < _HDR.size:
                break
            (n,) = _HDR.unpack_from(rec.rbuf)
            if len(rec.rbuf) < _HDR.size + n:
                break
            frame = bytes(rec.rbuf[_HDR.size:_HDR.size + n])
            del rec.rbuf[:_HDR.size + n]
            # frames are self-describing; replies/pushes follow the
            # client's encoding
            rec.encoding = protocol.payload_encoding(frame)
            msg = protocol.decode_payload(frame)
            self._dispatch(rec, msg)

    def _on_writable(self, rec: ClientRec) -> None:
        if rec.wbuf:
            try:
                sent = rec.sock.send(rec.wbuf)
                del rec.wbuf[:sent]
            except (BlockingIOError, InterruptedError):
                return
            except OSError:
                self._drop_client(rec)
                return
        if not rec.wbuf:
            self.sel.modify(rec.sock, selectors.EVENT_READ, rec)

    def _attach_lane(self, lane) -> None:
        """Register an in-process client (core/local_lane.py) as a
        ClientRec.  Runs the mutation on the loop thread; the caller
        blocks until its rec exists so its first send can't race."""
        done = threading.Event()

        def attach():
            self._next_conn += 1
            rec = ClientRec(conn_id=self._next_conn, sock=None)
            from ray_tpu.core.local_lane import _LaneSock
            rec.sock = _LaneSock()
            rec.lane = lane
            self.clients[rec.conn_id] = rec
            lane.rec = rec
            done.set()
        self.post(attach)
        if not done.wait(timeout=10.0):
            raise RuntimeError(f"[{self.name}] lane attach timed out "
                               "(service loop not running?)")

    def _push_blob(self, rec: ClientRec, meta: dict, data) -> None:
        """Queue a bulk frame without pickling `data` (one copy into the
        write buffer instead of slice+pickle+buffer)."""
        if rec.closed:
            return
        if rec.lane is not None:
            m = dict(meta)
            # the receiver must own the payload: the source buffer is a
            # view into this service's arena and can be evicted after
            # the push (a socket send would have copied it to the wire)
            m["data"] = bytes(data)
            rec.lane._deliver(m)
            return
        from ray_tpu.core.protocol import blob_frame_parts
        for part in blob_frame_parts(meta, data):
            rec.wbuf += part
        self._queue_write(rec)

    def _queue_write(self, rec: ClientRec) -> None:
        if threading.current_thread() is self._thread:
            self._cork_dirty[rec.conn_id] = rec
        else:
            self._write_out(rec)

    def _push(self, rec: ClientRec, msg: dict,
              stamp: Optional[str] = None) -> None:
        if rec.closed:
            return
        if rec.lane is not None:
            if stamp is not None:
                from ray_tpu.core.rt_frames import py_stamp
                py_stamp(msg, stamp)
            rec.lane._deliver(msg)
            return
        rec.wbuf += dumps_frame(msg, rec.encoding, stamp)
        if threading.current_thread() is self._thread:
            # loop thread: defer the syscall; _flush_corked sends the
            # whole batch right before the next select
            self._cork_dirty[rec.conn_id] = rec
        else:
            self._write_out(rec)

    def _write_out(self, rec: ClientRec) -> None:
        if not rec.wbuf or rec.closed:
            return
        try:
            sent = rec.sock.send(rec.wbuf)
            del rec.wbuf[:sent]
        except (BlockingIOError, InterruptedError):
            pass
        except OSError:
            self._drop_client(rec)
            return
        if rec.wbuf:
            try:
                self.sel.modify(rec.sock,
                                selectors.EVENT_READ | selectors.EVENT_WRITE,
                                rec)
            except KeyError:
                pass

    def _flush_corked(self) -> None:
        if not self._cork_dirty:
            return
        dirty = self._cork_dirty
        self._cork_dirty = {}
        for rec in dirty.values():
            self._write_out(rec)

    def _flush(self, rec: ClientRec) -> None:
        if rec.lane is not None:
            return
        rec.sock.setblocking(True)
        if rec.wbuf:
            try:
                rec.sock.sendall(bytes(rec.wbuf))
            except OSError:
                pass
            rec.wbuf.clear()

    def _close_extra_listeners(self) -> None:
        for lst, path in self._extra_listeners:
            try:
                lst.close()
            except OSError:
                pass
            try:
                os.unlink(path)
            except OSError:
                pass
        self._extra_listeners = []

    def _reply(self, rec: ClientRec, reqid: int, **kw) -> None:
        kw["t"] = "reply"
        kw["reqid"] = reqid
        self._push(rec, kw)

    # ------------------------------------------------------------- dispatch

    def _dispatch(self, rec: ClientRec, msg: dict) -> None:
        if _fi._active is not None:
            # chaos plane: scripted triggers keyed on the Nth matching
            # service message ("head dies mid-cluster_submit"); True
            # swallows the message, as if the crash preceded it
            if _fi._active.on_service_msg(self, rec, msg):
                return
        if msg.get("t") == "reply":
            cb = self._rpc_pending.pop(msg.get("reqid"), None)
            if cb is not None:
                try:
                    cb(msg)
                except Exception:
                    sys.stderr.write(f"[{self.name}] rpc callback failed:\n"
                                     + traceback.format_exc())
            return
        handler = getattr(self, "_h_" + msg["t"], None)
        if handler is None:
            if "reqid" in msg:
                self._reply(rec, msg["reqid"],
                            error=f"unknown message {msg['t']}")
            return
        try:
            handler(rec, msg)
        except Exception:
            tb = traceback.format_exc()
            sys.stderr.write(f"[{self.name}] handler {msg['t']} "
                             f"failed:\n{tb}")
            if "reqid" in msg:
                self._reply(rec, msg["reqid"], error=tb)

    def _rpc(self, rec: ClientRec, msg: dict,
             cb: Optional[Callable[[dict], None]] = None) -> None:
        """Push a request to a connected peer; `cb(reply)` runs on the
        loop thread when the peer answers with {"t": "reply"}."""
        if cb is not None:
            self._rpc_seq += 1
            msg["reqid"] = self._rpc_seq
            self._rpc_pending[self._rpc_seq] = cb
        self._push(rec, msg)

    # -------------------------------------------------------- disconnect

    def _drop_client(self, rec: ClientRec) -> None:
        if rec.closed:
            return
        rec.closed = True
        if rec.lane is None:
            try:
                self.sel.unregister(rec.sock)
            except (KeyError, ValueError):
                pass
            try:
                rec.sock.close()
            except OSError:
                pass
        else:
            rec.lane._mark_closed()
        self.clients.pop(rec.conn_id, None)
        self.on_client_drop(rec)


class ClusterStoreMixin:
    """KV store, pubsub fan-out, and function store — identical local
    semantics on the head (cluster scope) and on a standalone node
    (single-node scope), so both inherit one implementation
    (reference: gcs_kv_manager.cc, gcs pubsub, function_manager.py).

    The node overrides these handlers to proxy to the head in cluster
    mode; `_publish` is defined per-class (the node routes cluster-wide
    publishes through the head)."""

    def _init_stores(self) -> None:
        self.kv: dict[tuple[str, bytes], bytes] = {}
        self.pubsub: dict[str, set[int]] = {}
        self.functions: dict[str, bytes] = {}
        self._fn_waiters: dict[str, list] = {}

    # -- kv

    def _h_kv_put(self, rec: ClientRec, m: dict) -> None:
        key = (m.get("namespace") or "default", m["key"])
        if m.get("overwrite", True) or key not in self.kv:
            self.kv[key] = m["value"]
            added = True
        else:
            added = False
        if "reqid" in m:
            self._reply(rec, m["reqid"], added=added)

    def _h_kv_get(self, rec: ClientRec, m: dict) -> None:
        self._reply(rec, m["reqid"],
                    value=self.kv.get((m.get("namespace") or "default",
                                       m["key"])))

    def _h_kv_del(self, rec: ClientRec, m: dict) -> None:
        existed = self.kv.pop((m.get("namespace") or "default", m["key"]),
                              None) is not None
        if "reqid" in m:
            self._reply(rec, m["reqid"], deleted=existed)

    def _h_kv_keys(self, rec: ClientRec, m: dict) -> None:
        ns = m.get("namespace") or "default"
        prefix = m.get("prefix", b"")
        self._reply(rec, m["reqid"],
                    keys=[k for (n, k) in self.kv
                          if n == ns and k.startswith(prefix)])

    # -- pubsub

    def _h_subscribe(self, rec: ClientRec, m: dict) -> None:
        self.pubsub.setdefault(m["channel"], set()).add(rec.conn_id)
        if "reqid" in m:
            self._reply(rec, m["reqid"], ok=True)

    def _h_publish(self, rec: ClientRec, m: dict) -> None:
        self._publish(m["channel"], m["data"])
        if "reqid" in m:
            self._reply(rec, m["reqid"], ok=True)

    def _publish_local(self, channel: str, data: Any) -> None:
        for conn_id in list(self.pubsub.get(channel, ())):
            c = self.clients.get(conn_id)
            if c is not None:
                self._push(c, {"t": "pub", "channel": channel,
                               "data": data})

    def _publish(self, channel: str, data: Any) -> None:
        self._publish_local(channel, data)

    # -- functions

    def _store_function(self, fid: str, pickled: bytes) -> None:
        self.functions[fid] = pickled
        for conn_id, reqid in self._fn_waiters.pop(fid, []):
            c = self.clients.get(conn_id)
            if c is not None:
                self._reply(c, reqid, pickled=pickled)

    def _h_register_function(self, rec: ClientRec, m: dict) -> None:
        self._store_function(m["function_id"], m["pickled"])
        if "reqid" in m:
            self._reply(rec, m["reqid"], ok=True)

    def _h_fetch_function(self, rec: ClientRec, m: dict) -> None:
        fid = m["function_id"]
        if fid in self.functions:
            self._reply(rec, m["reqid"], pickled=self.functions[fid])
        else:
            self._fn_waiters.setdefault(fid, []).append(
                (rec.conn_id, m["reqid"]))
