"""Node memory monitor: watch usage, pick OOM-kill victims.

Reference capability: src/ray/common/memory_monitor.h:52 (periodic
usage refresh against a kill threshold, cgroup-aware) and
src/ray/raylet/worker_killing_policy_group_by_owner.h:85 (victim
selection: group running tasks by their submitter, shrink the largest
group, newest task first, preferring retriable tasks).

TPU redesign delta: the monitor lives inside the fused node-service
event loop (one `maybe_check` per tick) instead of a dedicated thread,
and the in-process TPU executor is never a candidate — killing it would
kill the driver that owns the accelerator.
"""

from __future__ import annotations

import os
import time
from typing import Callable, Optional, Tuple

_CGROUP_V2 = "/sys/fs/cgroup"
_CGROUP_V1_MEM = "/sys/fs/cgroup/memory"


def _read_int(path: str) -> Optional[int]:
    try:
        with open(path, "rb") as f:
            raw = f.read().strip()
        if raw in (b"max", b""):
            return None
        return int(raw)
    except (OSError, ValueError):
        return None


def _cgroup_inactive_file(stat_path: str) -> int:
    """Reclaimable page cache charged to the cgroup — must not count
    toward kill pressure (reference: memory_monitor.cc subtracts
    inactive_file from the cgroup's used bytes)."""
    try:
        with open(stat_path) as f:
            for line in f:
                if line.startswith("inactive_file "):
                    return int(line.split()[1])
                if line.startswith("total_inactive_file "):   # v1
                    return int(line.split()[1])
    except (OSError, ValueError):
        pass
    return 0


def system_usage() -> Tuple[int, int]:
    """(used_bytes, total_bytes) — cgroup v2, then v1, then /proc/meminfo
    (reference: memory_monitor.cc GetMemoryBytes cgroup-first order)."""
    cur = _read_int(os.path.join(_CGROUP_V2, "memory.current"))
    lim = _read_int(os.path.join(_CGROUP_V2, "memory.max"))
    if cur is not None and lim is not None:
        cache = _cgroup_inactive_file(os.path.join(_CGROUP_V2,
                                                   "memory.stat"))
        return max(cur - cache, 0), lim
    cur = _read_int(os.path.join(_CGROUP_V1_MEM, "memory.usage_in_bytes"))
    lim = _read_int(os.path.join(_CGROUP_V1_MEM, "memory.limit_in_bytes"))
    # v1 reports an absurd limit when unconstrained
    if cur is not None and lim is not None and lim < (1 << 60):
        cache = _cgroup_inactive_file(os.path.join(_CGROUP_V1_MEM,
                                                   "memory.stat"))
        return max(cur - cache, 0), lim
    total = avail = None
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    total = int(line.split()[1]) * 1024
                elif line.startswith("MemAvailable:"):
                    avail = int(line.split()[1]) * 1024
                if total is not None and avail is not None:
                    break
    except OSError:
        pass
    if total is None or avail is None:
        return 0, 0
    return total - avail, total


def process_rss(pid: int) -> int:
    """Resident set size of one process in bytes (/proc/<pid>/statm)."""
    try:
        with open(f"/proc/{pid}/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, IndexError, ValueError):
        return 0


class MemoryMonitor:
    """Threshold watcher with an injectable usage source (tests swap
    `get_usage` to simulate pressure without allocating)."""

    def __init__(self, threshold: float, refresh_ms: int,
                 get_usage: Optional[Callable[[], Tuple[int, int]]] = None):
        self.threshold = threshold
        self.refresh_s = max(refresh_ms, 1) / 1000.0
        self.get_usage = get_usage or system_usage
        self._last_check = 0.0

    def due(self) -> bool:
        now = time.monotonic()
        if now - self._last_check < self.refresh_s:
            return False
        self._last_check = now
        return True

    def over_threshold(self) -> Optional[Tuple[int, int]]:
        """(used, total) when usage exceeds the kill threshold, else
        None."""
        used, total = self.get_usage()
        if total > 0 and used / total >= self.threshold:
            return used, total
        return None


def pick_victim(candidates: list) -> Optional[tuple]:
    """Group-by-owner policy (reference:
    worker_killing_policy_group_by_owner.h:85): shrink the LARGEST
    owner's group, newest task first, retriable tasks before
    non-retriable.  `candidates` is a list of (rec, task_rec) with
    task_rec.spec/.started_at/.retries_left; returns one of them."""
    if not candidates:
        return None
    groups: dict = {}
    for item in candidates:
        owner = item[1].spec.get("owner", "")
        groups.setdefault(owner, []).append(item)
    grp = max(groups.values(), key=len)
    # newest first; retriable preferred so work is lost, not failed
    grp.sort(key=lambda it: it[1].started_at, reverse=True)
    for item in grp:
        if item[1].retries_left > 0:
            return item
    return grp[0]
