"""Task/actor execution engine.

The analogue of the reference's executor half of CoreWorker (reference:
src/ray/core_worker/core_worker.cc:2528 task_execution_callback →
python/ray/_raylet.pyx:701 execute_task): fetch the function by id,
resolve arguments, run user code, store returns (inline vs shm by size),
report completion.  Used by worker processes (ray_tpu.core.worker) and by
the driver's in-process TPU executor thread (single-host fast path — the
driver keeps jax device ownership, SURVEY.md §7 design delta 1).
"""

from __future__ import annotations

import contextlib
import inspect
import queue
import threading
import time
import traceback
from typing import Any, Optional

import cloudpickle

from ray_tpu.core.client import NodeClient, TaskError
from ray_tpu.core.ids import ActorID, ObjectID, TaskID
from ray_tpu.core.object_ref import ObjectRef, ObjectRefGenerator
from ray_tpu.core.serialization import SerializedObject, get_context


# reusable span stand-in for the no-tracing hot path (nullcontext is
# stateless, so one instance serves every task)
_NULL_SPAN = contextlib.nullcontext()


def _task_span(name: str, spec: dict):
    from ray_tpu.util.tracing import start_span, tracing_enabled
    if not tracing_enabled():
        return _NULL_SPAN
    return start_span(name, kind="server", remote_ctx=spec.get("trace_ctx"))


class _ArgSlot:
    """Marker for a top-level ObjectRef argument resolved before execution."""

    __slots__ = ("index",)

    def __init__(self, index: int):
        self.index = index


def make_message_queue() -> "queue.SimpleQueue":
    """Create the executor inbox BEFORE connecting the client, so pushes
    that arrive during registration are never dropped."""
    return queue.SimpleQueue()


def queue_push_handler(q: "queue.SimpleQueue",
                       client_cell: Optional[dict] = None):
    """Route pushes into the executor inbox.  With ``client_cell``
    (filled with {"client": NodeClient} after connect), "profile"
    requests are served straight off the RECEIVE thread — a worker
    busy inside a long task is exactly the one worth profiling, and
    its inbox won't drain until the task ends."""
    def push(msg: dict) -> None:
        if (msg.get("t") == "profile" and client_cell
                and client_cell.get("client") is not None):
            _serve_profile(client_cell["client"], msg)
            return
        q.put(msg)
    return push


def _serve_profile(client, msg: dict) -> None:
    def run():
        from ray_tpu.util.profiling import sample_folded
        try:
            folded = sample_folded(
                duration=float(msg.get("duration", 2.0)),
                hz=float(msg.get("hz", 99.0)))
            client.send({"t": "profile_result",
                         "prof_id": msg["prof_id"], "folded": folded})
        except Exception as e:
            client.send({"t": "profile_result",
                         "prof_id": msg["prof_id"], "error": str(e)})
    threading.Thread(target=run, daemon=True,
                     name="raytpu-sampler").start()


class _ActorAsyncState:
    """Long-lived event loop for ONE async actor: every in-flight call
    runs as a coroutine on this loop, so calls interleave at awaits and
    share asyncio primitives (reference: fiber-based async actors,
    core_worker/transport/fiber.h — vs. a fresh asyncio.run per call,
    which isolates each call on its own loop)."""

    def __init__(self, name: str = "raytpu-actor-loop"):
        import asyncio
        self.loop = asyncio.new_event_loop()
        self.thread = threading.Thread(target=self._run, daemon=True,
                                       name=name)
        self.thread.start()
        self._sems: dict[str, Any] = {}   # concurrency group -> Semaphore
        self._sems_lock = threading.Lock()

    def _run(self) -> None:
        import asyncio
        asyncio.set_event_loop(self.loop)
        self.loop.run_forever()

    def group_sem(self, group: str, limit: int):
        import asyncio
        with self._sems_lock:
            sem = self._sems.get(group)
            if sem is None:
                sem = self._sems[group] = asyncio.Semaphore(limit)
            return sem

    def stop(self) -> None:
        self.loop.call_soon_threadsafe(self.loop.stop)


class Executor:
    def __init__(self, client: NodeClient,
                 msg_queue: Optional["queue.SimpleQueue"] = None,
                 threaded_actors: bool = False):
        self.client = client
        self._functions: dict[str, Any] = {}
        self._actors: dict[bytes, Any] = {}
        self._actor_envs: dict[bytes, dict] = {}
        self._actor_lock = threading.Lock()
        # async-actor loops + concurrency-group state (reference:
        # concurrency_group_manager.cc named groups with own executors)
        self._actor_loops: dict[bytes, _ActorAsyncState] = {}
        self._actor_groups: dict[bytes, dict] = {}     # aid -> {name: limit}
        self._sync_sems: dict[tuple, Any] = {}         # (aid, group) -> sem
        self._serde = get_context()
        self._queue = msg_queue if msg_queue is not None else queue.SimpleQueue()
        self._shutdown = threading.Event()
        # threaded_actors: dedicated CPU workers honor max_concurrency>1
        # by running each dispatched actor call on its own thread.  The
        # SHARED in-process TPU executor must stay single-threaded — all
        # TPU actors and tasks share the driver's jax device, and
        # concurrent dispatch from multiple threads would break the
        # driver-owns-device invariant.
        self._threaded_actors = threaded_actors

    # -- message pump ------------------------------------------------------

    def push_handler(self, msg: dict) -> None:
        """Called on the client's receive thread."""
        self._queue.put(msg)

    def run_loop(self) -> None:
        """Blocking execution loop (reference:
        CoreWorkerProcess::RunTaskExecutionLoop, core_worker_process.h:100)."""
        while not self._shutdown.is_set():
            msg = self._queue.get()
            t = msg.get("t")
            if t in ("stop", "shutdown", "exit"):
                self._shutdown.set()
                break
            if t == "execute":
                self.execute_task(msg["spec"])
            elif t == "execute_actor":
                # the node dispatches up to the actor's max_concurrency
                # in-flight calls; a dedicated worker honors that with
                # one thread per dispatched call (no pool cap: a bounded
                # pool could deadlock waiter-pattern actors whose
                # unblocking call queues behind blocked threads).  With
                # max_concurrency=1 the node sends one call at a time,
                # so ordering is preserved.  Reference: concurrency
                # groups, core_worker task_execution_service
                if self._threaded_actors:
                    threading.Thread(
                        target=self.execute_actor_task,
                        args=(msg["spec"],), daemon=True,
                        name="raytpu-actor-task").start()
                else:
                    self.execute_actor_task(msg["spec"])
            elif t == "create_actor_exec":
                self.create_actor(msg["spec"])
            elif t == "profile":
                # normally served on the receive thread
                # (queue_push_handler); kept here for executors fed by
                # other transports
                _serve_profile(self.client, msg)
            elif t == "destroy_actor":
                with self._actor_lock:
                    aid = msg["actor_id"]
                    self._actors.pop(aid, None)
                    self._actor_envs.pop(aid, None)
                    self._actor_groups.pop(aid, None)
                    self._sync_sems = {k: v for k, v in
                                       self._sync_sems.items()
                                       if k[0] != aid}
                    st = self._actor_loops.pop(aid, None)
                if st is not None:
                    st.stop()

    # -- function store ----------------------------------------------------

    def _get_function(self, function_id: str):
        fn = self._functions.get(function_id)
        if fn is None:
            reply = self.client.request({"t": "fetch_function",
                                         "function_id": function_id})
            fn = cloudpickle.loads(reply["pickled"])
            self._functions[function_id] = fn
        return fn

    # -- argument resolution ----------------------------------------------

    def _load_args(self, spec: dict):
        blob_id = spec.get("arg_blob")
        if blob_id is not None:
            args, kwargs = self.client.get_objects([ObjectID(blob_id)])[0]
        else:
            so = SerializedObject.from_buffer(spec["args"])
            args, kwargs = self._serde.deserialize(so)
        ref_ids = [ObjectID(b) for b in spec.get("arg_ids", [])
                   if b != blob_id]
        if ref_ids:
            values = self.client.get_objects(ref_ids)
            args = [values[a.index] if isinstance(a, _ArgSlot) else a
                    for a in args]
            kwargs = {k: (values[v.index] if isinstance(v, _ArgSlot) else v)
                      for k, v in kwargs.items()}
        return list(args), dict(kwargs)

    # -- return storage ----------------------------------------------------

    def _store_returns(self, spec: dict, result: Any) -> None:
        return_ids = [ObjectID(b) for b in spec["return_ids"]]
        num_returns = spec.get("num_returns", 1)
        # returns are OWNED by the submitter (spec["owner"]), not this
        # executor — its release_refs must be able to reclaim them
        owner = spec.get("owner") or self.client.worker_id
        if num_returns == "dynamic":
            refs = []
            task_id = TaskID(spec["task_id"])
            for i, item in enumerate(result):
                oid = ObjectID.for_task_return(task_id, i + 2)
                self.client.put_object(oid, item, owner=owner)
                refs.append(ObjectRef(oid, owner=owner))
            self.client.put_object(return_ids[0], ObjectRefGenerator(refs),
                                   owner=owner)
            return
        if num_returns == 0:
            return
        if num_returns == 1:
            outs = [result]
        else:
            outs = list(result)
            if len(outs) != num_returns:
                raise ValueError(
                    f"Task declared num_returns={num_returns} but returned "
                    f"{len(outs)} values")
        for oid, val in zip(return_ids, outs):
            self.client.put_object(oid, val, owner=owner)

    def _store_error(self, spec: dict, exc: BaseException, tb: str) -> None:
        err = TaskError(exc, tb) if not isinstance(exc, TaskError) else exc
        for b in spec["return_ids"]:
            try:
                self.client.put_object(ObjectID(b), err, is_error=True)
            except Exception:
                # even the error failed to serialize — store a plain one
                self.client.put_object(
                    ObjectID(b),
                    TaskError(RuntimeError(
                        f"unserializable {type(exc).__name__}: {exc}"), tb),
                    is_error=True)

    # -- execution ---------------------------------------------------------

    def execute_task(self, spec: dict) -> None:
        from ray_tpu.core.runtime import task_context
        from ray_tpu.runtime_env import applied_env
        error = None
        # flight recorder: data-driven — stamp only when the submitter
        # started a lifecycle record (one dict.get when disabled), and
        # ship the stamps back inside task_done for the node to fold in
        fr = spec.get("fr")
        if fr is not None:
            fr.append(("worker_recv", time.monotonic()))
        try:
            fn = self._get_function(spec["function_id"])
            args, kwargs = self._load_args(spec)
            if fr is not None:
                fr.append(("exec_start", time.monotonic()))
            with task_context(TaskID(spec["task_id"])), \
                    applied_env(spec.get("runtime_env"), self.client), \
                    _task_span(f"task::{spec.get('name', '?')}.execute",
                               spec):
                result = fn(*args, **kwargs)
            if fr is not None:
                fr.append(("exec_end", time.monotonic()))
            # one syscall for inline result puts + completion (hot path:
            # per-task overhead, SURVEY hard part 6)
            with self.client.batched_sends():
                self._store_returns(spec, result)
                done = {"t": "task_done", "task_id": spec["task_id"],
                        "error": None}
                if fr is not None:
                    fr.append(("result_store", time.monotonic()))
                    done["fr"] = fr
                self.client.send(done)
            return
        except BaseException as e:  # noqa: BLE001 — report all task errors
            tb = traceback.format_exc()
            error = f"{type(e).__name__}: {e}"
            self._store_error(spec, e, tb)
        done = {"t": "task_done", "task_id": spec["task_id"],
                "error": error}
        if fr is not None:
            done["fr"] = fr
        self.client.send(done)

    def create_actor(self, spec: dict) -> None:
        error = None
        try:
            cls = self._get_function(spec["function_id"])
            args, kwargs = self._load_args(spec)
            from ray_tpu.core.runtime import task_context
            from ray_tpu.runtime_env import applied_env
            env = spec.get("runtime_env")
            if env and self._threaded_actors:
                # dedicated worker: the env spans the actor's LIFETIME
                # (applied once, never popped)
                applied_env(env, self.client).__enter__()
                env = None
            elif env:
                # SHARED executor (in-process TPU): the env must never
                # leak into the driver/other actors — scope it around
                # construction and around every method call instead
                self._actor_envs[spec["actor_id"]] = env
            with task_context(TaskID(spec["task_id"])), \
                    applied_env(env, self.client):
                instance = cls(*args, **kwargs)
            with self._actor_lock:
                self._actors[spec["actor_id"]] = instance
                groups = dict(spec.get("concurrency_groups") or {})
                if groups:
                    # "" = the default group, bounded by max_concurrency
                    groups[""] = int(spec.get("max_concurrency", 1))
                self._actor_groups[spec["actor_id"]] = groups
        except BaseException as e:  # noqa: BLE001
            error = (f"{type(e).__name__}: {e}\n{traceback.format_exc()}")
        self.client.send({"t": "actor_created", "actor_id": spec["actor_id"],
                          "error": error})

    def _actor_loop_state(self, aid: bytes) -> _ActorAsyncState:
        with self._actor_lock:
            st = self._actor_loops.get(aid)
            if st is None:
                st = self._actor_loops[aid] = _ActorAsyncState()
            return st

    def _sync_group_sem(self, aid: bytes, group: str, limit: int):
        with self._actor_lock:
            sem = self._sync_sems.get((aid, group))
            if sem is None:
                sem = self._sync_sems[(aid, group)] = \
                    threading.BoundedSemaphore(limit)
            return sem

    def _group_limit(self, spec: dict) -> Optional[int]:
        groups = self._actor_groups.get(spec["actor_id"]) or {}
        if not groups:
            # no named groups declared: the node's max_concurrency
            # admission cap alone governs
            return None
        # the node raises its dispatch cap to default+sum(groups), so
        # the DEFAULT group ("" key, = max_concurrency) must be enforced
        # here too — otherwise declaring any named group would unbound
        # the default group's concurrency
        group = spec.get("concurrency_group") or ""
        limit = groups.get(group)
        if limit is None:
            raise ValueError(
                f"Unknown concurrency group {group!r}; declared groups: "
                f"{sorted(g for g in groups if g)}")
        return int(limit)

    def _finish_actor_task(self, spec: dict, result: Any,
                           exc: Optional[BaseException],
                           tb: str = "") -> None:
        fr = spec.get("fr")
        if exc is None:
            try:
                with self.client.batched_sends():
                    self._store_returns(spec, result)
                    done = {"t": "task_done", "task_id": spec["task_id"],
                            "error": None}
                    if fr is not None:
                        fr.append(("result_store", time.monotonic()))
                        done["fr"] = fr
                    self.client.send(done)
                return
            except BaseException as e:  # noqa: BLE001
                exc, tb = e, traceback.format_exc()
        error = f"{type(exc).__name__}: {exc}"
        self._store_error(spec, exc, tb)
        done = {"t": "task_done", "task_id": spec["task_id"],
                "error": error}
        if fr is not None:
            done["fr"] = fr
        self.client.send(done)

    def execute_actor_task(self, spec: dict) -> None:
        from ray_tpu.core.runtime import task_context
        from ray_tpu.runtime_env import applied_env
        fr = spec.get("fr")
        if fr is not None:
            fr.append(("worker_recv", time.monotonic()))
        try:
            instance = self._actors.get(spec["actor_id"])
            if instance is None:
                raise RuntimeError("actor instance not found in this worker")
            method = getattr(instance, spec["method"])
            args, kwargs = self._load_args(spec)
            limit = self._group_limit(spec)
            if fr is not None:
                fr.append(("exec_start", time.monotonic()))
            if inspect.iscoroutinefunction(method) or \
                    inspect.iscoroutinefunction(
                        getattr(method, "__func__", method)):
                self._run_async_actor_task(spec, method, args, kwargs, limit)
                return
            sem = (self._sync_group_sem(spec["actor_id"],
                                        spec.get("concurrency_group") or "",
                                        limit)
                   if limit is not None else None)
            with task_context(TaskID(spec["task_id"])), \
                    applied_env(self._actor_envs.get(spec["actor_id"]),
                                self.client), \
                    _task_span(f"actor::{spec.get('name', '?')}.execute",
                               spec):
                if sem is not None:
                    with sem:
                        result = method(*args, **kwargs)
                else:
                    result = method(*args, **kwargs)
                if inspect.iscoroutine(result):
                    # async value from a non-coroutine callable (rare):
                    # still run it on the shared actor loop
                    self._run_async_actor_task(
                        spec, lambda: result, (), {}, limit)
                    return
        except BaseException as e:  # noqa: BLE001
            self._finish_actor_task(spec, None, e, traceback.format_exc())
            return
        if fr is not None:
            fr.append(("exec_end", time.monotonic()))
        self._finish_actor_task(spec, result, None)

    def _run_async_actor_task(self, spec: dict, method, args, kwargs,
                              limit: Optional[int]) -> None:
        """Schedule the call on the actor's long-lived loop and return —
        completion is reported from the loop.  All in-flight calls
        interleave at awaits and share asyncio primitives."""
        import asyncio
        from ray_tpu.core.runtime import task_context
        from ray_tpu.runtime_env import applied_env
        st = self._actor_loop_state(spec["actor_id"])

        async def runner():
            from ray_tpu.util.tracing import start_span
            with task_context(TaskID(spec["task_id"])), \
                    applied_env(self._actor_envs.get(spec["actor_id"]),
                                self.client), \
                    start_span(f"actor::{spec.get('name', '?')}.execute",
                               kind="server",
                               remote_ctx=spec.get("trace_ctx")):
                if limit is not None:
                    sem = st.group_sem(
                        spec.get("concurrency_group") or "", limit)
                    async with sem:
                        return await method(*args, **kwargs)
                return await method(*args, **kwargs)

        def schedule():
            task = st.loop.create_task(runner())

            def done(t):
                fr = spec.get("fr")
                if fr is not None:
                    # async path returns before execute_actor_task's
                    # sync-side exec_end stamp — stamp here instead so
                    # coroutine runtime isn't folded into result_store
                    fr.append(("exec_end", time.monotonic()))
                exc = t.exception()
                if exc is not None:
                    tb = "".join(traceback.format_exception(
                        type(exc), exc, exc.__traceback__))
                    self._finish_actor_task(spec, None, exc, tb)
                else:
                    self._finish_actor_task(spec, t.result(), None)
            task.add_done_callback(done)

        st.loop.call_soon_threadsafe(schedule)

    def get_actor_instance(self, actor_id: bytes) -> Optional[Any]:
        return self._actors.get(actor_id)
