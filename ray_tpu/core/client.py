"""NodeClient: every process's handle to the node service + object plane.

The analogue of the reference CoreWorker's client half (reference:
src/ray/core_worker/core_worker.h:278 — submit tasks, put/get objects, reach
the control plane) minus task execution, which lives in
``ray_tpu.core.worker`` / the driver executor thread.

Request/response correlation is by ``reqid``; pushed messages (execute,
pub, shutdown) are delivered to a handler callback on the receive thread.
"""

from __future__ import annotations

import os
import queue
import random
import threading
import time
import uuid
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Optional

from ray_tpu.core import protocol
from ray_tpu.core.device_objects import DeviceObjectTable
from ray_tpu.core.ids import ObjectID
from ray_tpu.core.object_store import ObjectExists, make_shm_client
from ray_tpu.core.serialization import (SerializedObject, get_context)


class GetTimeoutError(TimeoutError):
    pass


class TaskError(Exception):
    """Wraps an exception raised inside a task, carrying the remote
    traceback (reference: ray.exceptions.RayTaskError)."""

    def __init__(self, cause: BaseException, remote_tb: str = ""):
        self.cause = cause
        self.remote_tb = remote_tb
        super().__init__(f"{type(cause).__name__}: {cause}\n"
                         f"--- remote traceback ---\n{remote_tb}")

    def __reduce__(self):
        # Preserve (cause, tb) structure across pickling; the default
        # BaseException reduce would re-init with the formatted message.
        return (type(self), (self.cause, self.remote_tb))


class ActorDiedError(RuntimeError):
    pass


class ObjectLostError(RuntimeError):
    """All copies of an object died with their node(s) and it could not
    be reconstructed (reference: ray.exceptions.ObjectLostError)."""


class OutOfMemoryError(RuntimeError):
    """The node's memory monitor killed this task's worker to protect
    the node, and its retry budget is exhausted (reference:
    ray.exceptions.OutOfMemoryError / memory_monitor.h:52)."""


class RetryPolicy:
    """One retry discipline for control-plane requests, replacing the
    ad-hoc per-call timeouts that used to decide each call site's fate
    independently: jittered exponential backoff, a total deadline, and
    an explicit retryable-error classification (reference:
    gcs_rpc_client.h RETRYABLE_RPC macros — every GCS-bound call gets
    the same backoff/deadline treatment).

    Only TRANSIENT CLUSTER-PLANE failures are retryable — the error
    strings a node reply carries while the head is failing over.  A
    dead local node (ConnectionClosed) is terminal: the node is this
    process's lifeline.  Caller-visible timeouts (GetTimeoutError) stay
    timeouts: a caller that bounded its wait keeps that bound."""

    # substrings of reply errors that mean "the cluster plane is mid-
    # failover; the standby head will pick this up"
    TRANSIENT = ("head connection lost", "no head connection",
                 "chosen node vanished", "head registration failed")

    def __init__(self, deadline_s: Optional[float] = None,
                 base_s: float = 0.05, multiplier: float = 2.0,
                 max_backoff_s: float = 2.0, jitter: float = 0.25,
                 seed: Optional[int] = None):
        self.deadline_s = deadline_s
        self.base_s = base_s
        self.multiplier = multiplier
        self.max_backoff_s = max_backoff_s
        self.jitter = jitter
        self._rng = random.Random(seed)

    @classmethod
    def from_config(cls, config: dict) -> "RetryPolicy":
        return cls(deadline_s=float(config.get("client_retry_deadline_s",
                                               30.0)),
                   base_s=float(config.get("client_retry_base_ms", 50))
                   / 1000.0)

    def retryable(self, exc: BaseException) -> bool:
        if isinstance(exc, RuntimeError) and not isinstance(
                exc, (ActorDiedError, ObjectLostError, OutOfMemoryError)):
            text = str(exc)
            return any(p in text for p in self.TRANSIENT)
        return False

    def backoffs(self):
        """Infinite jittered backoff schedule; the deadline cuts it."""
        delay = self.base_s
        while True:
            yield delay * (1.0 + self.jitter * self._rng.random())
            delay = min(delay * self.multiplier, self.max_backoff_s)


# Requests safe to re-issue after a transient failure: pure reads, or
# writes whose repeat is a no-op.  Submission-like messages (actor
# creation, task submit) are NOT here — a blind resend could double
# them.
_IDEMPOTENT = frozenset((
    "get_objects", "wait", "free_objects", "kv_put", "kv_get", "kv_del",
    "kv_keys", "ping", "pg_state", "get_named_actor", "list_named_actors",
    "state", "object_stats", "head_flush", "need_space", "remove_pg",
))


class _SendBatch:
    """Scope for NodeClient.batched_sends(): reentrant per thread; only
    the outermost scope flushes."""

    def __init__(self, client: "NodeClient"):
        self._client = client
        self._owner = False

    def __enter__(self):
        tls = self._client._batch_tls
        if getattr(tls, "batch", None) is None:
            tls.batch = []
            self._owner = True
        return self

    def __exit__(self, *exc) -> bool:
        if self._owner:
            try:
                self._client._flush_batch()
            finally:
                self._client._batch_tls.batch = None
        return False


class NodeClient:
    def __init__(self, address: str, kind: str, tpu: bool = False,
                 push_handler: Optional[Callable[[dict], None]] = None):
        self.address = address
        self.kind = kind
        self.worker_id = f"{kind}-{uuid.uuid4().hex[:12]}"
        self.conn = protocol.connect(address,
                                     label=(f"client:{kind}", address))
        self._reqid = 0
        self._reqlock = threading.Lock()
        self._replies: dict[int, queue.SimpleQueue] = {}
        self._push_handler = push_handler
        self._closed = threading.Event()
        self._batch_tls = threading.local()   # per-thread send batching
        # submit auto-batching: bursts of fire-and-forget submissions
        # coalesce into one syscall; a micro-flusher bounds the delay and
        # request()/send() flush first so same-socket ordering holds
        self._auto: list = []
        self._auto_lock = threading.Lock()
        # held across swap+send: concurrent flushes (micro-flusher vs a
        # request() on another thread) must not reorder batches on the
        # wire — actor-call ordering rides arrival order
        self._auto_send_lock = threading.Lock()
        self._auto_event = threading.Event()
        self._auto_thread: Optional[threading.Thread] = None
        # armed after registration (needs the node's resolved config);
        # pre-registration requests run un-retried
        self._retry_policy: Optional[RetryPolicy] = None
        from ray_tpu.core.local_lane import LaneConnection
        if isinstance(self.conn, LaneConnection):
            # in-process node: replies/pushes are delivered by the node
            # loop calling straight into this client — no recv thread,
            # no decode, no extra wakeup hop on the reply path
            self._recv_thread = None
            self.conn.deliver = self._on_message
            self.conn.on_close = self._on_conn_closed
        else:
            self._recv_thread = threading.Thread(target=self._recv_loop,
                                                 daemon=True,
                                                 name=f"raytpu-recv-{kind}")
            self._recv_thread.start()
        info = self.request({"t": "register", "kind": kind, "tpu": tpu,
                             "worker_id": self.worker_id, "pid": os.getpid(),
                             "container_image": os.environ.get(
                                 "RAY_TPU_CONTAINER_IMAGE", "")})
        self.session: str = info["session"]
        self.node_id: str = info["node_id"]
        self.config_dict: dict = info["config"]
        self._retry_policy = RetryPolicy.from_config(self.config_dict)
        if self._recv_thread is not None:
            # socket channel (workers, remote drivers): arm the native
            # send-combining ring so concurrent senders — actor executor
            # threads on the done-return leg, driver threads mid-burst —
            # batch their preassembled frames into one syscall.  No-op
            # without the native codec (core/rt_frames.py).
            self.conn.enable_ring()
        self.shm = make_shm_client(self.session,
                                   native=bool(info.get("native_store")),
                                   on_full=self._need_space)
        self._serde = get_context()
        # device-resident entries this process owns (HBM objects — see
        # core/device_objects.py); materialization runs off the recv
        # thread so big device→host copies don't stall reply routing
        budget_mb = self.config_dict.get("device_object_budget_mb", 0)
        self.device_table = DeviceObjectTable(
            budget_bytes=int(budget_mb) * (1 << 20) if budget_mb else None)
        # eager: lazy init from both the recv thread and caller threads
        # could race into two pools, losing one-at-a-time ordering
        self._materialize_pool = ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="raytpu-devmat")

    # ----------------------------------------------------------- plumbing

    def _next_reqid(self) -> int:
        with self._reqlock:
            self._reqid += 1
            return self._reqid

    def _recv_loop(self) -> None:
        while not self._closed.is_set():
            try:
                msg = self.conn.recv()
            except protocol.ConnectionClosed:
                self._on_conn_closed()
                return
            except Exception:
                continue
            self._on_message(msg)

    def _on_conn_closed(self) -> None:
        self._closed.set()
        # wake all pending requesters with an error
        for q in list(self._replies.values()):
            q.put({"error": "node connection closed"})
        if self._push_handler is not None:
            try:
                self._push_handler({"t": "shutdown"})
            except Exception:
                pass

    def _on_message(self, msg: dict) -> None:
        """One incoming message — called from the recv thread, or (lane
        clients) directly from the node's loop thread, so every branch
        must stay quick and non-blocking."""
        if msg.get("t") == "reply":
            q = self._replies.pop(msg["reqid"], None)
            if q is not None:
                q.put(msg)
        elif msg.get("t") == "materialize_object":
            self._materialize_async(msg["object_id"])
        elif msg.get("t") == "drop_device_object":
            self.device_table.pop(msg["object_id"])
        elif self._push_handler is not None:
            try:
                self._push_handler(msg)
            except Exception:
                import traceback
                traceback.print_exc()

    def batched_sends(self):
        """Context manager: coalesce fire-and-forget sends on this
        thread into one syscall at exit (e.g. inline result puts +
        task_done).  request() flushes first, so the node still sees
        puts strictly before any later read from this thread."""
        return _SendBatch(self)

    def _flush_batch(self) -> None:
        batch = getattr(self._batch_tls, "batch", None)
        if batch:
            self._batch_tls.batch = []
            self._flush_auto()   # older coalesced submits go first
            self.conn.send_batch(batch)

    def request(self, msg: dict, timeout: Optional[float] = None,
                retry: Optional[RetryPolicy] = None) -> dict:
        """Round-trip a request.  Idempotent message types ride the
        client's RetryPolicy by default: a transient cluster-plane
        error (head failover mid-get) retries with jittered backoff
        until the policy deadline instead of surfacing — callers see
        the post-failover answer, not the failover."""
        t = msg.get("t")
        if retry is None and t in _IDEMPOTENT:
            # kv_put's added-flag is first-writer-wins ONLY with
            # overwrite: a retried conditional put that actually landed
            # would tell its own writer it lost
            if not (t == "kv_put" and not msg.get("overwrite", True)):
                retry = self._retry_policy
        if retry is None:
            return self._request_once(msg, timeout)
        # a caller-bounded wait keeps its bound even when the failure
        # surfaces as a fast transient error reply rather than a timeout
        budget = retry.deadline_s or 30.0
        if timeout is not None:
            budget = min(budget, timeout)
        deadline = time.monotonic() + budget
        backoffs = retry.backoffs()
        while True:
            remaining = deadline - time.monotonic()
            attempt_timeout = timeout if timeout is None \
                else min(timeout, max(0.001, remaining))
            try:
                return self._request_once(msg, attempt_timeout)
            except BaseException as e:
                if (self._closed.is_set() or not retry.retryable(e)
                        or time.monotonic() >= deadline):
                    raise
                time.sleep(min(next(backoffs),
                               max(0.0, deadline - time.monotonic())))

    def _request_once(self, msg: dict, timeout: Optional[float] = None
                      ) -> dict:
        self._flush_batch()
        reqid = self._next_reqid()
        msg["reqid"] = reqid
        q: queue.SimpleQueue = queue.SimpleQueue()
        self._replies[reqid] = q
        # piggyback coalesced fire-and-forget sends (submits, puts) into
        # the SAME syscall as the request — the sync-task hot path is
        # exactly submit-then-get, previously two sendalls
        with self._auto_send_lock:
            with self._auto_lock:
                batch, self._auto = self._auto, []
            if batch:
                batch.append(msg)
                self.conn.send_batch(batch)
            else:
                self.conn.send(msg)
        try:
            reply = q.get(timeout=timeout)
        except queue.Empty:
            self._replies.pop(reqid, None)
            raise GetTimeoutError(f"request {msg['t']} timed out") from None
        if reply.get("error"):
            raise RuntimeError(reply["error"])
        return reply

    def send(self, msg: dict) -> None:
        batch = getattr(self._batch_tls, "batch", None)
        if batch is not None:
            batch.append(msg)
        else:
            self._flush_auto()
            self.conn.send(msg)

    def send_soon(self, msg: dict) -> None:
        """Fire-and-forget send that MAY be coalesced with neighbors
        (bounded-delay flush).  Any later send()/request() on this
        client flushes first, so ordering relative to subsequent
        traffic is preserved."""
        with self._auto_lock:
            self._auto.append(msg)
            n = len(self._auto)
            if self._auto_thread is None:
                self._auto_thread = threading.Thread(
                    target=self._auto_flusher, daemon=True,
                    name="raytpu-autoflush")
                self._auto_thread.start()
        if n >= 64:
            self._flush_auto()
            return
        self._auto_event.set()

    def _flush_auto(self) -> None:
        if not self._auto:
            return
        with self._auto_send_lock:
            with self._auto_lock:
                batch, self._auto = self._auto, []
            if len(batch) == 1:
                self.conn.send(batch[0])
            elif batch:
                self.conn.send_batch(batch)

    def _auto_flusher(self) -> None:
        while not self._closed.is_set():
            self._auto_event.wait(0.5)
            self._auto_event.clear()
            if self._auto:
                time.sleep(0.0005)   # let the burst accumulate
                try:
                    self._flush_auto()
                except protocol.ConnectionClosed:
                    return

    def close(self) -> None:
        try:
            self._flush_auto()
        except Exception:
            pass
        self._closed.set()
        self._auto_event.set()   # unblock the flusher so it exits
        self.conn.close()
        self.shm.shutdown()

    @property
    def closed(self) -> bool:
        return self._closed.is_set()

    def _need_space(self, nbytes: int) -> None:
        """Arena full: ask the node to spill, then the caller retries."""
        self.request({"t": "need_space", "nbytes": int(nbytes)})

    # ------------------------------------------------------- object plane

    def put_object(self, object_id: ObjectID, value: Any,
                   owner: Optional[str] = None,
                   is_error: bool = False,
                   allow_device: bool = False) -> int:
        """Serialize and store; returns stored size.

        With ``allow_device`` (the explicit ray.put path), values holding
        jax.Array leaves become device-resident entries: the buffers stay
        in HBM in this process, only a placeholder descriptor reaches the
        store (reference contrast: plasma store.h:55 is host-only)."""
        if allow_device and not is_error:
            captured: list = []
            so = self._serde.serialize(value, device_capture=captured)
            if captured:
                return self._put_device(object_id, so, captured, owner)
        so = self._serde.serialize(value)
        return self.put_serialized(object_id, so, owner=owner,
                                   is_error=is_error)

    def _put_device(self, object_id: ObjectID, descriptor: SerializedObject,
                    leaves: list, owner: Optional[str]) -> int:
        desc_bytes = descriptor.to_bytes()
        spill = self.device_table.put(object_id.binary(), leaves, desc_bytes)
        nested = [r.binary() for r in descriptor.nested_refs]
        nbytes = sum(int(getattr(a, "nbytes", 0) or 0) for a in leaves)
        self.send({"t": "put_device", "object_id": object_id.binary(),
                   "descriptor": desc_bytes, "size": nbytes,
                   "owner": owner or self.worker_id,
                   "nested_refs": nested})
        for ob in spill:
            # budget pressure: flush oldest entries to the host store
            self._materialize_async(ob)
        return nbytes

    def _materialize_async(self, oid_bin: bytes) -> None:
        self._materialize_pool.submit(self._materialize, oid_bin)

    def _materialize(self, oid_bin: bytes) -> None:
        """Spill one device entry to the host store (on remote demand or
        budget pressure): rebuild the value from descriptor + leaves,
        store it the ordinary way, then drop the HBM references."""
        try:
            leaves = self.device_table.leaves(oid_bin)
            desc = self.device_table.descriptor(oid_bin)
            if leaves is None or desc is None:
                return  # freed concurrently
            so = SerializedObject.from_buffer(desc)
            value = self._serde.deserialize_with_leaves(so, leaves)
            self.put_object(ObjectID(oid_bin), value, allow_device=False)
            self.device_table.pop(oid_bin)
        except Exception as e:
            # the node flipped the entry to pending; if we stay silent
            # every getter hangs — report so it seals an error object
            import traceback
            traceback.print_exc()
            try:
                self.send({"t": "materialize_failed", "object_id": oid_bin,
                           "error": f"{type(e).__name__}: {e}"})
            except Exception:
                pass

    def put_serialized(self, object_id: ObjectID, so: SerializedObject,
                       owner: Optional[str] = None,
                       is_error: bool = False) -> int:
        size = so.total_bytes()
        inline_limit = self.config_dict["max_direct_call_object_size"]
        # nested refs: the node must keep the inner objects alive while
        # the outer object exists (reference: reference_count.h borrower
        # tracking, scoped to container-holds-ref here)
        nested = [r.binary() for r in so.nested_refs]
        # Fire-and-forget: same-socket ordering guarantees the node sees the
        # put before any later get/submit from this process (reference: Put
        # is async in CoreWorker too, core_worker.h:500).
        if size <= inline_limit or is_error:
            self.send({"t": "put_inline", "object_id": object_id.binary(),
                       "data": so.to_bytes(), "is_error": is_error,
                       "owner": owner or self.worker_id,
                       "nested_refs": nested})
        else:
            try:
                buf = self.shm.create(object_id, size)
                _write_into(so, buf)
                del buf
                self.shm.seal(object_id)
            except ObjectExists:
                pass  # identical value already stored (retried put)
            self.send({"t": "register_object",
                       "object_id": object_id.binary(), "size": size,
                       "owner": owner or self.worker_id,
                       "nested_refs": nested})
        return size

    def get_objects(self, object_ids: list[ObjectID],
                    timeout: Optional[float] = None) -> list[Any]:
        reply = self.request({"t": "get_objects",
                              "object_ids": [o.binary() for o in object_ids]},
                             timeout=timeout)
        out = []
        shm_ids = [oid.binary() for oid, res in zip(object_ids,
                                                    reply["results"])
                   if res["loc"] == "shm"]
        try:
            for oid, res in zip(object_ids, reply["results"]):
                if res["loc"] == "shm":
                    buf = self.shm.map(oid)
                    so = SerializedObject.from_buffer(buf[:res["size"]])
                elif res["loc"] == "device_local":
                    # we ARE the owner: splice our own HBM leaves back in
                    leaves = self.device_table.leaves(oid.binary())
                    if leaves is None:
                        # raced a budget spill: the entry just moved to
                        # the host store (its register preceded our pop on
                        # this same socket, so a re-get sees the host copy)
                        out.append(self.get_objects([oid],
                                                    timeout=timeout)[0])
                        continue
                    so = SerializedObject.from_buffer(res["data"])
                    out.append(self._serde.deserialize_with_leaves(
                        so, leaves))
                    continue
                else:
                    so = SerializedObject.from_buffer(res["data"])
                value = self._serde.deserialize(so)
                if res.get("is_error"):
                    if isinstance(value, BaseException):
                        raise value
                    raise RuntimeError(str(value))
                out.append(value)
        finally:
            # ack: node pinned shm objects for this get; release now that
            # this process has the segments mapped
            if shm_ids:
                self.send({"t": "release_pins", "object_ids": shm_ids})
        return out

    def wait(self, object_ids: list[ObjectID], num_returns: int,
             timeout: Optional[float]) -> list[bytes]:
        reply = self.request({"t": "wait",
                              "object_ids": [o.binary() for o in object_ids],
                              "num_returns": num_returns, "timeout": timeout})
        return reply["ready"]

    def free(self, object_ids: list[ObjectID]) -> None:
        self.request({"t": "free_objects",
                      "object_ids": [o.binary() for o in object_ids]})

    # -------------------------------------------------------------- kv

    def kv_put(self, key: bytes, value: bytes, overwrite: bool = True,
               namespace: str = "default") -> bool:
        return self.request({"t": "kv_put", "key": key, "value": value,
                             "overwrite": overwrite,
                             "namespace": namespace})["added"]

    def kv_get(self, key: bytes, namespace: str = "default") -> Optional[bytes]:
        return self.request({"t": "kv_get", "key": key,
                             "namespace": namespace})["value"]

    def kv_del(self, key: bytes, namespace: str = "default") -> bool:
        return self.request({"t": "kv_del", "key": key,
                             "namespace": namespace})["deleted"]

    def kv_keys(self, prefix: bytes = b"",
                namespace: str = "default") -> list[bytes]:
        return self.request({"t": "kv_keys", "prefix": prefix,
                             "namespace": namespace})["keys"]


class _MemoryviewWriter:
    """File-like writer over a memoryview so SerializedObject.write_to is
    the single encoder of the wire layout."""

    def __init__(self, buf: memoryview):
        self._buf = buf
        self._off = 0

    def write(self, b) -> int:
        mv = memoryview(b).cast("B") if not isinstance(b, bytes) else b
        n = len(mv)
        self._buf[self._off:self._off + n] = mv
        self._off += n
        return n


def _write_into(so: SerializedObject, buf: memoryview) -> None:
    so.write_to(_MemoryviewWriter(buf))
