"""Actor classes and handles.

Reference analogue: python/ray/actor.py (ActorClass:377, ActorClass._remote
:659, ActorHandle:1022, _actor_method_call:1111, named actors w/ namespaces
:581).  Method calls are ordered per-handle by sequence number; the node
service's per-actor queue preserves submission order (reference:
sequential_actor_submit_queue.h).
"""

from __future__ import annotations

import os
import threading
from typing import Any, Optional

from ray_tpu.core.ids import ActorID, _Counter
from ray_tpu.core.remote_function import (_pg_tuple, _resources_from_options,
                                          _validate_options)
from ray_tpu.core.runtime import get_runtime


def _public_methods(cls) -> list[str]:
    return [n for n in dir(cls)
            if callable(getattr(cls, n, None)) and not n.startswith("__")]


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str,
                 num_returns: Any = 1, concurrency_group: str = ""):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns
        self._concurrency_group = concurrency_group

    def options(self, **opts) -> "ActorMethod":
        return ActorMethod(
            self._handle, self._name,
            num_returns=opts.get("num_returns", self._num_returns),
            concurrency_group=opts.get("concurrency_group",
                                       self._concurrency_group))

    def remote(self, *args, **kwargs):
        return self._handle._actor_method_call(
            self._name, args, kwargs, num_returns=self._num_returns,
            concurrency_group=self._concurrency_group)

    def __call__(self, *args, **kwargs):
        raise TypeError(f"Actor method '{self._name}' cannot be called "
                        f"directly; use .remote().")


class ActorHandle:
    def __init__(self, actor_id: ActorID, methods: list[str],
                 class_name: str = ""):
        self._actor_id = actor_id
        self._methods = set(methods)
        self._class_name = class_name
        self._seq = _Counter()
        # fresh nonce per handle instance (incl. unpickled copies) so
        # callers in different processes never collide on task ids
        self._nonce = os.urandom(8)

    @property
    def actor_id(self) -> ActorID:
        return self._actor_id

    def __getattr__(self, name: str) -> ActorMethod:
        if name.startswith("_"):
            raise AttributeError(name)
        if name not in self._methods:
            raise AttributeError(
                f"Actor {self._class_name!r} has no method {name!r}")
        return ActorMethod(self, name)

    def _actor_method_call(self, method: str, args, kwargs, num_returns=1,
                           concurrency_group: str = ""):
        rt = get_runtime()
        return rt.submit_actor_task(self._actor_id, self._nonce,
                                    self._seq.next(), method,
                                    args, kwargs, num_returns=num_returns,
                                    name=f"{self._class_name}.{method}",
                                    concurrency_group=concurrency_group)

    def __reduce__(self):
        return (_rebuild_handle,
                (self._actor_id, sorted(self._methods), self._class_name))

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()[:12]}…)"


def _rebuild_handle(actor_id, methods, class_name):
    return ActorHandle(actor_id, methods, class_name)


class ActorClass:
    def __init__(self, cls: type, **options):
        _validate_options(options)
        self._cls = cls
        self._options = options
        self._function_id: Optional[str] = None
        self._exported_to = None
        self._export_lock = threading.Lock()

    def options(self, **options) -> "ActorClass":
        merged = {**self._options, **options}
        ac = ActorClass(self._cls, **merged)
        ac._function_id = self._function_id
        ac._exported_to = self._exported_to
        return ac

    def remote(self, *args, **kwargs) -> ActorHandle:
        rt = get_runtime()
        with self._export_lock:
            if self._function_id is None or self._exported_to is not rt:
                self._function_id = rt.export_function(self._cls)
                self._exported_to = rt
        o = self._options
        methods = _public_methods(self._cls)
        # async actors default to high concurrency (reference:
        # DEFAULT_MAX_CONCURRENCY_ASYNC=1000) — their calls interleave as
        # coroutines on one long-lived loop, not as parallel threads
        import inspect as _inspect
        has_async = any(
            _inspect.iscoroutinefunction(getattr(self._cls, n, None))
            for n in methods)
        default_mc = 1000 if has_async else 1
        actor_id = rt.create_actor(
            self._function_id, args, kwargs,
            class_name=self._cls.__name__,
            methods=methods,
            name=o.get("name") or "",
            namespace=o.get("namespace") or rt.namespace,
            get_if_exists=bool(o.get("get_if_exists")),
            resources=_resources_from_options(o),
            num_tpus=float(o.get("num_tpus") or 0),
            max_restarts=o.get("max_restarts",
                               -1 if o.get("lifetime") == "detached" else 0),
            max_concurrency=o.get("max_concurrency", default_mc),
            concurrency_groups=o.get("concurrency_groups"),
            placement_group=_pg_tuple(o),
            runtime_env=o.get("runtime_env"))
        return ActorHandle(actor_id, methods, self._cls.__name__)

    def bind(self, *args, **kwargs):
        """Lazy actor-DAG node (reference: ray DAG ClassNode .bind)."""
        from ray_tpu.dag.dag_node import ClassNode
        return ClassNode(self._cls, args, kwargs)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class '{self._cls.__name__}' cannot be instantiated "
            f"directly; use .remote().")

    def __getstate__(self):
        state = self.__dict__.copy()
        state["_exported_to"] = None
        state["_export_lock"] = None
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)
        self._export_lock = threading.Lock()


def get_actor(name: str, namespace: str | None = None) -> ActorHandle:
    """Look up a named actor (reference: ray.get_actor,
    _private/worker.py:2590).  Defaults to the namespace given to init()."""
    rt = get_runtime()
    reply = rt.client.request({"t": "get_named_actor", "name": name,
                               "namespace": namespace or rt.namespace})
    meta = reply["spec_meta"]
    return ActorHandle(ActorID(reply["actor_id"]), meta["methods"],
                       meta["class_name"])


def list_named_actors(all_namespaces: bool = False,
                      namespace: str | None = None) -> list:
    """Names of live named actors (reference: ray.util.list_named_actors).
    Default scope is this driver's namespace; ``all_namespaces=True``
    returns ``{"namespace", "name"}`` dicts across all of them."""
    if all_namespaces and namespace is not None:
        raise ValueError("namespace= conflicts with all_namespaces=True "
                         "(the scan already spans every namespace)")
    rt = get_runtime()
    reply = rt.client.request({"t": "list_named_actors",
                               "namespace": namespace or rt.namespace,
                               "all_namespaces": all_namespaces})
    actors = reply["actors"]
    if all_namespaces:
        return actors
    return [a["name"] for a in actors]


def kill(actor: ActorHandle, *, no_restart: bool = True) -> None:
    get_runtime().kill_actor(actor.actor_id, no_restart=no_restart)
