"""Small shared resource-arithmetic helpers (used by the node scheduler,
the head's placement-group planner, and feasibility checks — one
definition so reservation and feasibility can't disagree)."""

from __future__ import annotations


def bundle_total(bundles: list[dict]) -> dict[str, float]:
    """Element-wise sum of resource bundles."""
    total: dict[str, float] = {}
    for b in bundles:
        for k, v in b.items():
            total[k] = total.get(k, 0.0) + v
    return total


def covers(capacity: dict, demand: dict, eps: float = 1e-9) -> bool:
    """capacity >= demand on every resource key (with float slack)."""
    return all(capacity.get(k, 0.0) + eps >= v for k, v in demand.items())
