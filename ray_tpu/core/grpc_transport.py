"""gRPC transport for the control plane (``RAY_TPU_RPC=grpc``).

Reference parity: the reference hosts every control-plane service over
gRPC (src/ray/rpc/grpc_server.h, client_call.h, 22 protos under
src/ray/protobuf/).  Here the services speak the same framed message
protocol regardless of transport (core/protocol.py — typed proto
payloads on remote links), and this module hosts that byte stream over
a gRPC bidirectional-streaming method instead of a raw TCP socket.

Stubless wiring (this image has protoc but not the grpc_tools stub
generator): ``grpc.method_handlers_generic_handler`` with identity
serializers carries the frame bytes verbatim — the same pattern
serve/grpc_ingress.py uses for typed messages.  Server side, each
incoming stream is bridged to the service's internal loopback listener
with two byte pumps, so the single-threaded selector loop is completely
unaware of the transport; client side, ``grpc_connect_socket`` returns
an ordinary socket whose peer is pumped through the channel.

Service surface:  /ray_tpu.rpc.ControlPlane/Conn  (bidi byte stream).
"""

from __future__ import annotations

import socket
import threading
from typing import Optional, Tuple

_SERVICE = "ray_tpu.rpc.ControlPlane"
_METHOD = f"/{_SERVICE}/Conn"
_CHUNK = 1 << 16

# streams are long-lived (one per cluster connection) and each holds a
# handler thread for its lifetime — size the pool for a busy node's
# workers + peers + drivers, not for request concurrency.  A connection
# beyond this cap queues silently (gRPC gives no pool-exhausted error),
# so the cap is set far above any realistic link count for this opt-in
# transport; threads are created lazily, idle ones cost only stack
# reservation.
_MAX_STREAMS = 1024


def _identity(b: bytes) -> bytes:
    return b


def start_grpc_front(internal_address: str, host: str = "127.0.0.1",
                     port: int = 0) -> Tuple[object, str]:
    """Host a service's internal loopback listener over gRPC.

    Returns (server, public_address).  Every incoming Conn stream gets a
    fresh TCP connection to ``internal_address``; bytes are pumped both
    ways until either side closes."""
    import grpc
    from concurrent import futures

    ihost, iport = internal_address.rsplit(":", 1)

    def conn_handler(request_iterator, context):
        sock = socket.create_connection((ihost, int(iport)))
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

        def pump_in():
            try:
                for chunk in request_iterator:
                    if chunk:
                        sock.sendall(chunk)
            except Exception:
                pass
            finally:
                # client finished sending (or stream broke): propagate
                # half-close so the service sees EOF and drops the client
                try:
                    sock.shutdown(socket.SHUT_WR)
                except OSError:
                    pass

        t = threading.Thread(target=pump_in, daemon=True,
                             name="raytpu-grpc-in")
        t.start()
        try:
            while True:
                data = sock.recv(_CHUNK)
                if not data:
                    break
                yield data
        finally:
            try:
                sock.close()
            except OSError:
                pass

    handler = grpc.stream_stream_rpc_method_handler(
        conn_handler, request_deserializer=_identity,
        response_serializer=_identity)
    service = grpc.method_handlers_generic_handler(
        _SERVICE, {"Conn": handler})
    server = grpc.server(
        futures.ThreadPoolExecutor(max_workers=_MAX_STREAMS,
                                   thread_name_prefix="raytpu-grpc"))
    server.add_generic_rpc_handlers((service,))
    bound = server.add_insecure_port(f"{host}:{port}")
    if bound == 0:
        raise RuntimeError(f"could not bind gRPC port {host}:{port}")
    server.start()
    return server, f"{host}:{bound}"


def grpc_connect_socket(address: str, timeout: float = 30.0):
    """Open a Conn stream to ``address`` and return a plain socket whose
    bytes ride it (the caller wraps it in protocol.Connection)."""
    import grpc

    # channel_ready_future retries a dead endpoint until its deadline —
    # a raw TCP probe keeps down-endpoint detection at socket-mode
    # latency (milliseconds, not the full reconnect timeout)
    host, port = address.rsplit(":", 1)
    socket.create_connection((host, int(port)),
                             timeout=min(timeout, 5.0)).close()

    channel = grpc.insecure_channel(address, options=[
        ("grpc.max_send_message_length", -1),
        ("grpc.max_receive_message_length", -1)])
    try:
        grpc.channel_ready_future(channel).result(timeout=timeout)
    except Exception as e:
        # normalize to the socket-connect contract: callers (peer
        # connect retries, head reconnect) catch OSError — and the
        # channel must not leak its threads on a dead endpoint
        try:
            channel.close()
        except Exception:
            pass
        raise ConnectionRefusedError(
            f"gRPC connect to {address} failed: {e}") from e
    call = channel.stream_stream(_METHOD, request_serializer=_identity,
                                 response_deserializer=_identity)
    ours, theirs = socket.socketpair()

    def req_iter():
        try:
            while True:
                data = theirs.recv(_CHUNK)
                if not data:
                    break
                yield data
        except OSError:
            pass

    responses = call(req_iter())

    def pump_out():
        try:
            for chunk in responses:
                if chunk:
                    theirs.sendall(chunk)
        except Exception:
            pass
        finally:
            try:
                theirs.close()
            except OSError:
                pass
            try:
                channel.close()
            except Exception:
                pass

    threading.Thread(target=pump_out, daemon=True,
                     name="raytpu-grpc-out").start()
    return ours


def transport() -> str:
    """Selected control-plane transport ("socket" | "grpc") — from the
    config table (which honors both _system_config and RAY_TPU_RPC)."""
    from ray_tpu._config import get_config
    return str(get_config().rpc).lower()
