"""Scheduling half of the node service (split out of core/node.py).

Task admission → two-queue dispatch → completion, and everything that
decides WHERE work runs: spillover forwarding through the head,
re-routing parked backlogs when remote capacity appears (_rebalance),
incremental queued-demand aggregates, actor placement / per-actor
ordered queues / restart bookkeeping, cluster actor-task routing with
location caching, and placement-group reservation (local queue + 2PC
participant).  Reference: local_task_manager.h, cluster_task_manager.h,
gcs_actor_manager.cc, gcs_placement_group_scheduler.h.

``NodeSchedMixin`` holds no state; ``NodeService.__init__``
(core/node.py) owns every attribute.  Record types shared with the
object plane (``ObjInfo``, ``_wire_spec``) are imported from
node_transfer — that module is the shared lower layer, keeping the
import graph acyclic.
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Optional

from ray_tpu.core import fault_injection as _fi
from ray_tpu.core import flight_recorder as _fr
from ray_tpu.core import rt_frames as _rtf
from ray_tpu.core import protocol
from ray_tpu.core.ids import ActorID, ObjectID, PlacementGroupID
from ray_tpu.core.node_transfer import ObjInfo, _wire_spec
from ray_tpu.core.resources import bundle_total, covers
from ray_tpu.core.service import ClientRec


@dataclass
class TaskRec:
    spec: dict
    state: str = "pending"       # pending | running | forwarded | finished | failed
    worker: Optional[int] = None
    retries_left: int = 0
    submitted_at: float = field(default_factory=time.time)
    started_at: float = 0.0
    finished_at: float = 0.0
    error: str = ""


@dataclass
class ActorRec:
    actor_id: ActorID
    spec: dict                   # creation spec (reusable for restart)
    state: str = "pending"       # pending | alive | restarting | dead
    conn_id: Optional[int] = None
    name: str = ""
    namespace: str = ""
    restarts_left: int = 0
    seq: int = 0
    queue: deque = field(default_factory=deque)   # pending method-call specs
    running: dict = field(default_factory=dict)   # task_id -> in-flight spec
    max_concurrency: int = 1
    death_cause: str = ""

    @property
    def inflight(self) -> int:
        return len(self.running)


@dataclass
class PGRec:
    pg_id: PlacementGroupID
    bundles: list                # list[dict resource->qty]
    strategy: str
    state: str = "created"       # single-node: reserve succeeds or raises

class NodeSchedMixin:
    """Scheduling / parking / rebalance + actors + placement groups
    (mixed into NodeService)."""

    def _expire_parked_actor_waits(self) -> None:
        """Actor-bound tasks parked through a head failover fail once
        the grace window runs out with the head still gone."""
        if not self._actor_wait_parked or self.head_conn is not None:
            return
        grace = self.config.actor_locate_failover_grace_s
        cutoff = time.monotonic() - grace
        for ab, since in list(self._actor_wait_parked.items()):
            if since < cutoff:
                self._actor_wait_parked.pop(ab, None)
                for spec in self._awaiting_actor.pop(ab, []):
                    self._fail_task(
                        spec, "Actor location unknown: head connection "
                              f"lost and not recovered within {grace:.0f}s")

    def _rebalance(self) -> None:
        """Queued work meets new capacity: spillover decisions are made
        at enqueue time, so when another node gains availability LATER
        (autoscaler launch, task completion elsewhere), re-route queue
        heads this node can't start now (reference: the cluster
        scheduler re-evaluates pending queues on resource updates,
        cluster_task_manager.cc ScheduleAndDispatchTasks)."""
        if self.head_conn is None:
            return
        moved = 0
        for q in (self.runnable_cpu, self.runnable_tpu):
            while q and moved < 8:
                spec = q[0]
                if spec.get("placement_group"):
                    break   # FIFO: don't reorder past an unmovable head
                demand = self._demand(spec)
                if all(self.available.get(k, 0.0) + 1e-9 >= v
                       for k, v in demand.items()):
                    break   # dispatches here as soon as a worker frees
                if not self._cluster_has_capacity(spec):
                    break
                # _routed (head-parked) specs move too: during a burst
                # the head parks work on saturated nodes; when capacity
                # appears LATER (autoscaler launch, drain elsewhere) the
                # parked backlog must chase it.  No ping-pong: we only
                # re-forward when the view shows another node free NOW,
                # and the head ranks available-now targets first.
                self._queue_pop(q)
                self._forward_task(spec)
                moved += 1

    def _repark_queued_to_head(self) -> None:
        """Drain begin: queued-but-unstarted specs leave for the head so
        the decommission never waits on a backlog (and the backlog never
        dies with the node).  Placement-group specs stay — their bundle
        reservation lives on this node and cannot move.  The head gets
        a fresh placement choice for everything else; if this node is
        truly the only feasible host it routes the spec straight back
        (reply local=True) and the drain waits for it like any running
        work."""
        if self.head_conn is None:
            return
        moved = 0
        for q in (self.runnable_cpu, self.runnable_tpu,
                  self.runnable_zero):
            keep: list = []
            while q:
                spec = self._queue_pop(q)
                if spec.get("placement_group"):
                    keep.append(spec)
                    continue
                self._forward_task(spec)
                moved += 1
            for spec in keep:
                self._make_runnable(spec)
        if moved:
            import sys as _sys
            _sys.stderr.write(f"[node] drain re-parked {moved} queued "
                              "spec(s) to the head\n")

    # -- tasks

    def _h_submit_task(self, rec, m):
        spec = m["spec"]
        spec["submitter"] = rec.conn_id
        self._admit_task(spec)
        if "reqid" in m:
            self._reply(rec, m["reqid"], ok=True)

    def _admit_task(self, spec: dict) -> None:
        tr = TaskRec(spec=spec, retries_left=spec.get("max_retries", 0))
        self.tasks[spec["task_id"]] = tr
        if _fr._active is not None:
            _fr._active.start_or_stamp(spec, "node_recv")
        if self.head_conn is not None and not spec.get("owner_node"):
            # first admission on the submitter's node: WE own the returns
            spec["owner_node"] = (self.node_id.hex(), self.address)
            if spec.get("max_retries", 0) != 0:
                # retry-disabled tasks are not reconstructable, matching
                # the reference (max_retries=0 -> ObjectLostError)
                self._record_lineage(spec)
        self._absorb_arg_owners(spec)
        onode = tuple(spec.get("owner_node") or ())
        for b in spec["return_ids"]:
            info = self.objects.setdefault(ObjectID(b), ObjInfo())
            info.owner = info.owner or spec.get("owner", "")
            if onode and not info.owner_node:
                info.owner_node = onode
        self._record_event(spec, "PENDING")
        self._enqueue_task(spec)

    def _projected_available(self) -> dict:
        """Availability net of demand already sitting in the runnable
        queues: resources are only acquired at dispatch, so raw
        `available` over-promises (the reference's hybrid policy counts
        committed resources the same way,
        hybrid_scheduling_policy.h)."""
        proj = dict(self.available)
        for k, v in self._queued_demand.items():
            proj[k] = proj.get(k, 0.0) - v
        return {k: max(0.0, v) for k, v in proj.items()}

    def _available_covers(self, spec: dict) -> bool:
        proj = self._projected_available()
        return all(proj.get(k, 0.0) + 1e-9 >= v
                   for k, v in self._demand(spec).items())

    def _cluster_has_capacity(self, spec: dict) -> bool:
        demand = self._demand(spec)
        me = self.node_id.hex()
        for h, n in self.cluster_view.items():
            if h == me or not n.get("alive") or n.get("draining"):
                continue
            if all(n["available"].get(k, 0.0) + 1e-9 >= v
                   for k, v in demand.items()):
                return True
        return False

    def _enqueue_task(self, spec: dict) -> None:
        routed = spec.get("_routed")
        pg = spec.get("placement_group")
        clustered = self.head_conn is not None and not routed
        if self._draining and clustered and pg is None:
            # DRAINING: nothing new starts here.  Un-routed specs leave
            # for the head, which places them on a survivor; specs the
            # head explicitly routed BACK (this node is the only
            # feasible host) fall through and run — a drain delays
            # work, never bounces it forever.  PG specs stay: their
            # bundle lives here.
            self._forward_task(spec)
            return
        if pg is not None:
            if (pg[0], pg[1]) not in self.pg_available:
                if clustered:
                    # bundle lives on another node: the head routes it there
                    self._forward_task(spec)
                    return
                if routed:
                    # routed here for a bundle that was removed in the
                    # meantime: fail fast — queueing would head-of-line
                    # block every later task behind an unacquirable spec
                    self._fail_task(
                        spec, "Placement group bundle no longer exists "
                              "on this node (group removed?)")
                    return
        elif not self._feasible(spec):
            if clustered:
                self._forward_task(spec)
                return
            self._fail_task(spec, "Infeasible resource demand: "
                            f"{self._demand(spec)} on {self.total_resources}")
            return
        elif clustered and not self._available_covers(spec):
            # spillover: we can't run it NOW — let the head place it.
            # The head ranks by availability AND parked backlog, so this
            # must not be gated on the view showing free capacity: the
            # view's availability is optimistically debited to zero
            # during any burst, and gating on it made a submitter keep
            # ~95% of a 4000-task burst while seven nodes sat idle
            # (reference: saturated tasks go to the cluster scheduler,
            # cluster_task_manager.h — placement is ITS call, not the
            # submitting raylet's)
            self._forward_task(spec)
            return
        if spec.get("_routed") and not self._feasible(spec):
            # routing race: the head's view was stale
            self._fail_task(spec, "Infeasible resource demand after "
                            f"routing: {self._demand(spec)} on "
                            f"{self.total_resources}")
            return
        ndeps = 0
        for b in spec.get("arg_ids", []):
            oid = ObjectID(b)
            info = self.objects.setdefault(oid, ObjInfo())
            if info.state == "pending":
                ndeps += 1
                self.dep_waiting.setdefault(oid, []).append(spec)
                self._ensure_remote_watch([oid])
        spec["_ndeps"] = ndeps
        if ndeps == 0:
            self._make_runnable(spec)
            self._schedule()

    def _forward_task(self, spec: dict) -> None:
        tid = spec["task_id"]
        if _fr._active is not None:
            # the interval ending at the DESTINATION's node_recv stamp
            # is then the head-route + wire hop
            _fr._active.stamp(spec, "forward")

        def cb(reply):
            if reply.get("error"):
                self._fail_task(spec, reply["error"])
                return
            if reply.get("local"):
                spec["_routed"] = True
                self._enqueue_task(spec)
                return
            dst = reply["node"]
            tr = self.tasks.get(tid)
            if tr is not None:
                tr.state = "forwarded"
            self._fwd_tasks[tid] = {"spec": spec, "dst": dst,
                                    "retries": spec.get("max_retries", 0)}
            for b in spec["return_ids"]:
                self._fwd_by_oid[b] = tid
            self._ensure_remote_watch(
                [ObjectID(b) for b in spec["return_ids"]])
        wire = _wire_spec(spec)
        self._attach_arg_owners(wire, spec)
        self._head_rpc({"t": "cluster_submit", "spec": wire,
                        "src_available": self._projected_available()}, cb)

    def _hh_remote_submit(self, m: dict) -> None:
        spec = m["spec"]
        spec["_routed"] = True
        self._admit_task(spec)

    def _make_runnable(self, spec: dict) -> None:
        if self._draining and self.head_conn is not None \
                and not spec.get("_routed") \
                and not spec.get("placement_group"):
            # a dep-waiting spec resolved MID-drain: forward instead of
            # queueing (the drain-begin re-park only saw the runnable
            # queues).  _routed specs are terminal here — the head
            # already chose this node — so no forward ping-pong.
            self._forward_task(spec)
            return
        if _fr._active is not None:
            _fr._active.stamp(spec, "enqueue")
        if spec.get("num_tpus"):
            self.runnable_tpu.append(spec)
        elif self._is_zero_demand(spec):
            # zero-demand tasks (PlacementGroup.ready() pollers) get
            # their own queue: they can always run, so they must not sit
            # behind a resource-blocked FIFO head — and keeping them out
            # of runnable_cpu keeps _schedule O(1), no per-event scans
            self.runnable_zero.append(spec)
        else:
            self.runnable_cpu.append(spec)
        if spec.get("placement_group"):
            self._queued_pg += 1
        else:
            for k, v in self._demand(spec).items():
                self._queued_demand[k] = self._queued_demand.get(k, 0.0) + v

    def _queue_pop(self, q: deque) -> dict:
        spec = q.popleft()
        if spec.get("placement_group"):
            self._queued_pg = max(0, self._queued_pg - 1)
        else:
            for k, v in self._demand(spec).items():
                self._queued_demand[k] = self._queued_demand.get(k, 0.0) - v
        if (not self.runnable_cpu and not self.runnable_tpu
                and not self.runnable_zero):
            # drain point: clear float drift
            self._queued_demand.clear()
            self._queued_pg = 0
        return spec

    def _h_task_done(self, rec, m):
        tid = m["task_id"]
        # the task outran its SIGKILL: it is not an OOM casualty (and a
        # stale entry must not mislabel a later failure of this task id)
        self._oom_kills.pop(tid, None)
        tr = self.tasks.get(tid)
        if tr is not None:
            tr.state = "failed" if m.get("error") else "finished"
            tr.finished_at = time.time()
            tr.error = m.get("error", "")
            self._note_task_finished(tid)
            self._release_arg_blob(tr.spec)
            if _fr._active is not None:
                self._fr_finish(tr, m)
            self._record_event(tr.spec, "FAILED" if m.get("error") else "FINISHED")
        if rec.dedicated_actor is not None:
            ar = self.actors.get(rec.dedicated_actor)
            if ar is not None:
                ar.running.pop(tid, None)
                self._dispatch_actor_queue(ar)
        else:
            if rec.state in ("busy", "blocked"):
                rec.state = "idle"
            rec.current_task = None
            if tr is not None and not tr.spec.get("_cpu_released"):
                self._return_resources(tr.spec)
        # unpin args
        if tr is not None:
            for b in tr.spec.get("arg_ids", []):
                self.store.unpin(ObjectID(b))
        self._schedule()

    def _release_task_cpu(self, rec: ClientRec) -> None:
        """Worker blocked on get: release its task's resources so the node
        can keep making progress (reference: raylet releases CPU for
        blocked workers)."""
        if rec.current_task is None:
            return
        tr = self.tasks.get(rec.current_task)
        if tr is not None and not tr.spec.get("_cpu_released"):
            tr.spec["_cpu_released"] = True
            self._return_resources(tr.spec)

    def _demand(self, spec) -> dict:
        d = dict(spec.get("resources") or {})
        # Tasks default to 1 CPU; actors hold 0 CPU for their lifetime
        # unless explicitly requested (reference: ray actor default
        # num_cpus=0 after creation, ray_option_utils.py).
        d.setdefault("CPU", 0.0 if spec.get("kind") == "actor_create" else 1.0)
        if spec.get("num_tpus"):
            d["TPU"] = float(spec["num_tpus"])
        return d

    def _try_acquire(self, spec) -> bool:
        demand = self._demand(spec)
        pg = spec.get("placement_group")
        if pg is not None:
            key = (pg[0], pg[1])
            free = self.pg_available.get(key)
            if free is None:
                return False
            if all(free.get(k, 0.0) + 1e-9 >= v for k, v in demand.items()):
                for k, v in demand.items():
                    free[k] = free.get(k, 0.0) - v
                return True
            return False
        if all(self.available.get(k, 0.0) + 1e-9 >= v for k, v in demand.items()):
            for k, v in demand.items():
                self.available[k] = self.available.get(k, 0.0) - v
            return True
        return False

    def _return_resources(self, spec) -> None:
        demand = self._demand(spec)
        pg = spec.get("placement_group")
        if pg is not None:
            free = self.pg_available.get((pg[0], pg[1]))
            if free is not None:
                for k, v in demand.items():
                    free[k] = free.get(k, 0.0) + v
            return
        for k, v in demand.items():
            self.available[k] = self.available.get(k, 0.0) + v
        if self._pending_local_pgs:
            self._try_place_local_pgs()

    def _feasible(self, spec) -> bool:
        demand = self._demand(spec)
        if spec.get("placement_group"):
            return True
        return all(self.total_resources.get(k, 0.0) + 1e-9 >= v
                   for k, v in demand.items())

    def _args_ready(self, spec) -> bool:
        for b in spec.get("arg_ids", []):
            info = self.objects.get(ObjectID(b))
            if info is None or info.state == "pending":
                return False
        return True

    def _schedule(self) -> None:
        """FIFO dispatch from the runnable queues (reference:
        LocalTaskManager::DispatchScheduledTasksToWorkers,
        local_task_manager.cc:101).  O(1) amortized per event: stops at the
        first queue head that cannot be placed."""
        for q, tpu in ((self.runnable_cpu, False), (self.runnable_tpu, True),
                       (self.runnable_zero, False)):
            while q:
                spec = q[0]
                container = (spec.get("runtime_env") or {}).get("container")
                if container and tpu:
                    # the TPU executor lives in the driver process; a
                    # containerized worker can never satisfy it — fail
                    # fast instead of wedging the TPU queue head
                    self._queue_pop(q)
                    self._fail_task(
                        spec, "runtime_env.container is not supported "
                              "for TPU tasks (TPU work runs on the "
                              "driver's in-process executor)")
                    continue
                w = self._find_idle_worker(
                    tpu=tpu, env_hash=spec.get("env_hash"),
                    container_image=(container or {}).get("image", ""))
                if w is None:
                    if container:
                        self._maybe_spawn_container_worker(container)
                    elif not tpu:
                        self._maybe_spawn_worker()
                    break
                if not self._try_acquire(spec):
                    break
                self._queue_pop(q)
                self._dispatch_task(w, spec)

    def _is_zero_demand(self, spec: dict) -> bool:
        """True for specs that take nothing from the pool (e.g.
        PlacementGroup.ready() pollers) — they always deserve a worker
        and ride their own queue, immune to CPU-FIFO head blocking."""
        return (not spec.get("placement_group")
                and not spec.get("num_tpus")
                and all(v <= 0 for v in self._demand(spec).values()))

    def _find_idle_worker(self, tpu: bool,
                          env_hash: Optional[str] = None,
                          container_image: str = ""
                          ) -> Optional[ClientRec]:
        best = None
        for rec in self.clients.values():
            if (rec.kind in ("worker", "tpu_executor") and rec.state == "idle"
                    and rec.dedicated_actor is None and rec.tpu == tpu):
                # container tasks only run inside a matching image;
                # plain tasks never borrow a containerized worker (its
                # filesystem is the image's, not the host's)
                if rec.container_image != container_image:
                    continue
                if not env_hash:
                    return rec
                # prefer a worker that already materialized this env
                # (reference: worker_pool.h:192 runtime-env-hash cache)
                if env_hash in rec.seen_envs:
                    return rec
                if best is None:
                    best = rec
        return best

    def _dispatch_task(self, w: ClientRec, spec: dict) -> None:
        tr = self.tasks[spec["task_id"]]
        tr.state = "running"
        tr.worker = w.conn_id
        tr.started_at = time.time()
        w.state = "busy"
        w.current_task = spec["task_id"]
        if spec.get("env_hash"):
            w.seen_envs.add(spec["env_hash"])
        for b in spec.get("arg_ids", []):
            self.store.pin(ObjectID(b))
        self._record_event(spec, "RUNNING", worker=w.conn_id)
        stamp = None
        if _fr._active is not None:
            if w.lane is None and spec.get("fr") is not None \
                    and _rtf._active is not None:
                # socket worker: the dispatch stamp folds into the wire
                # frame inside the native encoder (C-side monotonic
                # read, no Python tuple/append) — the worker's decoded
                # spec carries it and ships it back in task_done, which
                # is the copy the node's flight-recorder fold prefers
                stamp = "dispatch"
            else:
                _fr._active.stamp(spec, "dispatch")
        self._push(w, {"t": "execute", "spec": spec}, stamp=stamp)
        if _fi._active is not None:
            # chaos plane: "kill the worker that got the K-th dispatch"
            # — the task is in flight, so this exercises the
            # worker-death retry/FAILED path deterministically
            _fi._active.on_dispatch(self, w, spec)

    def _release_arg_blob(self, spec: dict) -> None:
        """Oversized (args, kwargs) tuples ride the store as a blob put
        by the submitter purely to carry them (runtime._prepare_args);
        no ObjectRef ever wraps the blob, so nothing releases it —
        reclaim it on TERMINAL task completion (retries still need it)."""
        b = spec.get("arg_blob")
        if b:
            self._released_wait.add(ObjectID(b))
            self._sweep_released()

    def _note_task_finished(self, tid: bytes) -> None:
        """Bound the finished-task history (the live dict stays O(recent),
        dupes are harmless — eviction re-checks state)."""
        self._done_order.append(tid)
        cap = max(1000, self.config.task_events_buffer_size // 5)
        while len(self._done_order) > cap:
            old = self._done_order.popleft()
            tr = self.tasks.get(old)
            if tr is not None and tr.state in ("finished", "failed"):
                del self.tasks[old]

    def _fail_task(self, spec: dict, error: str) -> None:
        tr = self.tasks.get(spec["task_id"])
        if tr is not None:
            tr.state = "failed"
            tr.error = error
            tr.finished_at = time.time()
            self._note_task_finished(spec["task_id"])
        self._release_arg_blob(spec)
        self._record_event(spec, "FAILED")
        for b in spec["return_ids"]:
            self._seal_error_object(ObjectID(b), RuntimeError(error))

    # -- actors

    def _h_create_actor(self, rec, m):
        spec = m["spec"]
        if self.head_conn is not None:
            # head owns names, placement, and the cluster directory
            reqid = m["reqid"]

            def cb(reply):
                w = self.clients.get(rec.conn_id)
                if w is None:
                    return
                if reply.get("error"):
                    self._reply(w, reqid, error=reply["error"])
                else:
                    self._reply(w, reqid, actor_id=reply["actor_id"],
                                existing=reply.get("existing", False))
            self._head_rpc({"t": "cluster_create_actor",
                            "spec": _wire_spec(spec)}, cb)
            return
        actor_id = ActorID(spec["actor_id"])
        name = spec.get("name") or ""
        ns = spec.get("namespace") or "default"
        if name:
            key = (ns, name)
            if key in self.named_actors and \
                    self.actors[self.named_actors[key]].state != "dead":
                if spec.get("get_if_exists"):
                    self._reply(rec, m["reqid"],
                                actor_id=self.named_actors[key].binary(),
                                existing=True)
                    return
                self._reply(rec, m["reqid"],
                            error=f"Actor name '{name}' already taken in "
                                  f"namespace '{ns}'")
                return
            self.named_actors[key] = actor_id
        if not self._feasible(spec):
            self.named_actors.pop((ns, name), None) if name else None
            self._reply(rec, m["reqid"],
                        error=f"Infeasible actor resource demand: "
                              f"{self._demand(spec)} on {self.total_resources}")
            return
        self._reply(rec, m["reqid"], actor_id=actor_id.binary())
        self._admit_actor(spec)

    def _admit_actor(self, spec: dict) -> ActorRec:
        actor_id = ActorID(spec["actor_id"])
        # named concurrency groups add their own in-flight budget on top
        # of the default group's (reference: concurrency_group_manager.cc
        # — per-group executors; the executor enforces per-group limits,
        # the node only caps the total it pushes)
        mc = spec.get("max_concurrency", 1) + \
            sum((spec.get("concurrency_groups") or {}).values())
        ar = ActorRec(actor_id=actor_id, spec=spec,
                      name=spec.get("name") or "",
                      namespace=spec.get("namespace") or "default",
                      restarts_left=spec.get("max_restarts", 0),
                      max_concurrency=mc)
        self.actors[actor_id] = ar
        self._place_actor(ar)
        return ar

    def _hh_place_actor(self, m: dict) -> None:
        """Head chose this node to host the actor (fresh or node-death
        re-place: the constructor re-runs; reference:
        gcs_actor_manager.cc RestartActor)."""
        spec = m["spec"]
        old = self.actors.get(ActorID(spec["actor_id"]))
        if old is not None and old.state not in ("dead",):
            return  # duplicate placement push
        self._admit_actor(spec)

    def _place_actor(self, ar: ActorRec) -> None:
        needs_tpu = bool(ar.spec.get("num_tpus"))
        container = (ar.spec.get("runtime_env") or {}).get("container")
        if container and needs_tpu:
            self._mark_actor_dead(
                ar, "runtime_env.container is not supported for TPU "
                    "actors (TPU work runs on the driver's in-process "
                    "executor)")
            return
        w = self._find_idle_worker(
            tpu=needs_tpu,
            container_image=(container or {}).get("image", ""))
        if w is None:
            if container:
                self._maybe_spawn_container_worker(container)
            else:
                self._maybe_spawn_worker(tpu=needs_tpu)
            # event-driven retry on the next worker registration (the
            # 50 ms poll alone serialized bursts of actor creations)
            self._actors_wanting_worker.append(ar)
            self.post_later(0.05, lambda: self._place_actor_if_pending(ar))
            return
        if not self._try_acquire(ar.spec):
            self.post_later(0.05, lambda: self._place_actor_if_pending(ar))
            return
        if not w.tpu:
            # CPU actors get a dedicated worker process (reference: one
            # worker per actor); the in-process TPU executor is shared —
            # it hosts all TPU actors and tasks in the driver.
            w.dedicated_actor = ar.actor_id
            w.state = "busy"
        ar.conn_id = w.conn_id
        self._push(w, {"t": "create_actor_exec", "spec": ar.spec})

    def _place_actor_if_pending(self, ar: ActorRec) -> None:
        if ar.state in ("pending", "restarting") and ar.conn_id is None:
            self._place_actor(ar)

    def _report_actor_state(self, ar: ActorRec) -> None:
        """State fan-out: via the head in cluster mode (it publishes and
        resolves watchers), locally otherwise."""
        if self.head_conn is not None:
            self._head_send({"t": "actor_state_report",
                             "actor_id": ar.actor_id.binary(),
                             "state": ar.state,
                             "death_cause": ar.death_cause})
        else:
            self._publish_local("actor_state",
                                {"actor_id": ar.actor_id.hex(),
                                 "state": ar.state})

    def _h_actor_created(self, rec, m):
        ar = self.actors.get(ActorID(m["actor_id"]))
        if ar is None:
            return
        if m.get("error"):
            ar.state = "dead"
            ar.death_cause = m["error"]
            self._fail_actor_queue(ar, m["error"])
            if rec.dedicated_actor == ar.actor_id:
                rec.dedicated_actor = None
                rec.state = "idle"
            ar.conn_id = None
            self._return_resources(ar.spec)
            self._report_actor_state(ar)
        else:
            ar.state = "alive"
            self._report_actor_state(ar)
            self._dispatch_actor_queue(ar)

    def _h_submit_actor_task(self, rec, m):
        spec = m["spec"]
        actor_id = ActorID(spec["actor_id"])
        ar = self.actors.get(actor_id)
        if self.head_conn is not None and not spec.get("owner_node"):
            # actor-task returns get the ownership directory but NOT
            # lineage: re-running actor methods is not loss-transparent
            # (reference: actor results -> ObjectLostError by default)
            spec["owner_node"] = (self.node_id.hex(), self.address)
        onode = tuple(spec.get("owner_node") or ())
        for b in spec["return_ids"]:
            info = self.objects.setdefault(ObjectID(b), ObjInfo())
            info.owner = info.owner or spec.get("owner", "")
            if onode and not info.owner_node:
                info.owner_node = onode
        self.tasks[spec["task_id"]] = TaskRec(spec=spec)
        if _fr._active is not None:
            _fr._active.start_or_stamp(spec, "node_recv")
        self._record_event(spec, "PENDING")
        if ar is not None:
            if ar.state == "dead":
                self._fail_task(spec, f"Actor is dead: {ar.death_cause}")
                return
            ar.queue.append(spec)
            self._dispatch_actor_queue(ar)
            return
        if self.head_conn is None:
            self._fail_task(spec, "Actor is dead: actor not found")
            return
        self._route_actor_task(spec)

    # ---- cluster actor-task routing

    def _route_actor_task(self, spec: dict) -> None:
        ab = spec["actor_id"]
        cached = self.actor_cache.get(ab)
        if cached is not None:
            # on forward failure: invalidate the cache and re-route via a
            # fresh head lookup (the actor may have moved)
            self._forward_actor_task(
                spec, cached[0], cached[1],
                on_fail=lambda: (self.actor_cache.pop(ab, None),
                                 self._queue_actor_locate(spec)))
            return
        self._queue_actor_locate(spec)

    def _queue_actor_locate(self, spec: dict) -> None:
        ab = spec["actor_id"]
        waiting = self._awaiting_actor.setdefault(ab, [])
        waiting.append(spec)
        if len(waiting) == 1:
            self._head_rpc({"t": "locate_actor", "actor_id": ab},
                           lambda reply: self._on_actor_located(ab, reply))

    def _on_actor_located(self, ab: bytes, reply: dict) -> None:
        state = reply.get("state")
        if reply.get("error") and self.head_conn is None:
            # transient: the head died mid-locate.  Keep the specs
            # parked through the failover grace window — the rejoin
            # path re-asks, on_tick expires the window.
            self._actor_wait_parked.setdefault(ab, time.monotonic())
            return
        self._actor_wait_parked.pop(ab, None)   # the head answered
        if reply.get("error") or state in ("dead", "unknown"):
            cause = reply.get("death_cause") or reply.get("error") \
                or "actor not found"
            for spec in self._awaiting_actor.pop(ab, []):
                self._fail_task(spec, f"Actor is dead: {cause}")
            return
        if state == "alive":
            self.actor_cache[ab] = (reply["node"], reply["address"])
            for spec in self._awaiting_actor.pop(ab, []):
                self._forward_actor_task(
                    spec, reply["node"], reply["address"],
                    on_fail=lambda s=spec: self._fail_task(
                        s, "Actor's node is unreachable"))
            return
        # pending/restarting: the head registered us as a watcher and will
        # push actor_at when it settles — keep the specs queued

    def _hh_actor_at(self, m: dict) -> None:
        self._on_actor_located(m["actor_id"], m)

    def _forward_actor_task(self, spec: dict, node_hex: str,
                            address: str, on_fail) -> None:
        def go(conn):
            if conn is None:
                on_fail()
                return
            wire = _wire_spec(spec)
            wire["_routed"] = True
            self._attach_arg_owners(wire, spec)
            try:
                conn.send({"t": "remote_actor_task", "spec": wire})
            except protocol.ConnectionClosed:
                self._drop_peer(node_hex)
                on_fail()
                return
            tid = spec["task_id"]
            tr = self.tasks.get(tid)
            if tr is not None:
                tr.state = "forwarded"
            self._fwd_tasks[tid] = {"spec": spec, "dst": node_hex,
                                    "retries": 0, "actor": True}
            for b in spec["return_ids"]:
                self._fwd_by_oid[b] = tid
            self._ensure_remote_watch(
                [ObjectID(b) for b in spec["return_ids"]])
        self._peer_conn_async(node_hex, address, go)

    def _h_remote_actor_task(self, rec, m):
        """A peer node forwarded a method call for an actor hosted here."""
        spec = m["spec"]
        spec["_routed"] = True
        actor_id = ActorID(spec["actor_id"])
        self._absorb_arg_owners(spec)
        onode = tuple(spec.get("owner_node") or ())
        for b in spec["return_ids"]:
            info = self.objects.setdefault(ObjectID(b), ObjInfo())
            info.owner = info.owner or spec.get("owner", "")
            if onode and not info.owner_node:
                info.owner_node = onode
        self.tasks[spec["task_id"]] = TaskRec(spec=spec)
        self._record_event(spec, "PENDING")
        ar = self.actors.get(actor_id)
        if ar is None or ar.state == "dead":
            cause = ar.death_cause if ar else "actor not on this node"
            self._fail_task(spec, f"Actor is dead: {cause}")
            return
        ar.queue.append(spec)
        self._dispatch_actor_queue(ar)

    def _dispatch_actor_queue(self, ar: ActorRec) -> None:
        if ar.state != "alive" or ar.conn_id is None:
            return
        w = self.clients.get(ar.conn_id)
        if w is None:
            return
        while ar.queue and ar.inflight < ar.max_concurrency:
            spec = ar.queue.popleft()
            if not self._args_ready(spec):
                # actors preserve submission order: put back and stop
                ar.queue.appendleft(spec)
                self._ensure_remote_watch(
                    [ObjectID(b) for b in spec.get("arg_ids", [])
                     if self.objects.setdefault(ObjectID(b),
                                                ObjInfo()).state == "pending"])
                self._wait_args_then(spec, lambda: self._dispatch_actor_queue(ar))
                return
            ar.running[spec["task_id"]] = spec
            for b in spec.get("arg_ids", []):
                self.store.pin(ObjectID(b))
            tr = self.tasks.get(spec["task_id"])
            if tr is not None:
                tr.state = "running"
                tr.started_at = time.time()
                tr.worker = w.conn_id
            self._record_event(spec, "RUNNING", worker=w.conn_id)
            stamp = None
            if _fr._active is not None:
                if w.lane is None and spec.get("fr") is not None \
                        and _rtf._active is not None:
                    stamp = "dispatch"   # folded by the native encoder
                else:
                    _fr._active.stamp(spec, "dispatch")
            self._push(w, {"t": "execute_actor", "spec": spec},
                       stamp=stamp)

    def _wait_args_then(self, spec, cb) -> None:
        remaining = [ObjectID(b) for b in spec.get("arg_ids", [])
                     if self.objects.get(ObjectID(b), ObjInfo()).state == "pending"]
        if not remaining:
            cb()
            return
        # Poll via the event loop until the dependency lands (v1; the
        # reference stages deps through the DependencyManager).
        self.post_later(0.02, lambda: self._wait_args_then(spec, cb))

    def _fail_actor_queue(self, ar: ActorRec, error: str) -> None:
        while ar.queue:
            self._fail_task(ar.queue.popleft(), f"Actor died: {error}")

    def _h_kill_actor(self, rec, m):
        actor_id = ActorID(m["actor_id"])
        ar = self.actors.get(actor_id)
        if ar is None and self.head_conn is not None:
            # actor lives elsewhere: the head routes the kill
            reqid = m.get("reqid")

            def cb(reply):
                w = self.clients.get(rec.conn_id)
                if reqid is not None and w is not None:
                    self._reply(w, reqid, ok=bool(reply.get("ok")))
            self._head_rpc({"t": "kill_actor", "actor_id": m["actor_id"],
                            "no_restart": m.get("no_restart", True)}, cb)
            return
        if ar is None:
            if "reqid" in m:
                self._reply(rec, m["reqid"], ok=False)
            return
        self._kill_local_actor(ar, m.get("no_restart", True))
        if "reqid" in m:
            self._reply(rec, m["reqid"], ok=True)

    def _kill_local_actor(self, ar: ActorRec, no_restart: bool) -> None:
        if no_restart:
            ar.restarts_left = 0
        w = self.clients.get(ar.conn_id) if ar.conn_id is not None else None
        if w is not None and not w.tpu:
            self._push(w, {"t": "exit"})
        elif w is not None:
            # shared in-process TPU executor: destroy only this actor's
            # instance, keep the executor alive for other work
            self._push(w, {"t": "destroy_actor",
                           "actor_id": ar.actor_id.binary()})
            self._mark_actor_dead(ar, "killed")
        else:
            self._mark_actor_dead(ar, "killed")

    def _hh_kill_local_actor(self, m: dict) -> None:
        ar = self.actors.get(ActorID(m["actor_id"]))
        if ar is not None:
            self._kill_local_actor(ar, m.get("no_restart", True))

    def _mark_actor_dead(self, ar: ActorRec, cause: str) -> None:
        if ar.state == "dead":
            return
        ar.state = "dead"
        ar.death_cause = cause
        ar.conn_id = None
        for spec in list(ar.running.values()):
            self._fail_task(spec, f"Actor died: {cause}")
        ar.running.clear()
        self._fail_actor_queue(ar, cause)
        self._return_resources(ar.spec)
        self._report_actor_state(ar)

    def _h_get_named_actor(self, rec, m):
        if self._cluster_scope(rec, m):
            return
        key = (m.get("namespace") or "default", m["name"])
        aid = self.named_actors.get(key)
        if aid is None or self.actors[aid].state == "dead":
            self._reply(rec, m["reqid"], error="not found")
        else:
            ar = self.actors[aid]
            self._reply(rec, m["reqid"], actor_id=aid.binary(), spec_meta={
                "methods": ar.spec.get("methods", []),
                "class_name": ar.spec.get("class_name", "")})

    def _h_list_named_actors(self, rec, m):
        if self._cluster_scope(rec, m):
            return
        out = [{"namespace": ns, "name": n}
               for (ns, n), aid in self.named_actors.items()
               if self.actors[aid].state != "dead"
               and (m.get("all_namespaces") or ns == (m.get("namespace")
                                                      or "default"))]
        self._reply(rec, m["reqid"], actors=out)

    # -- placement groups

    def _h_create_pg(self, rec, m):
        if self._cluster_scope(rec, m):
            return   # head (or failover error) ran the cross-node 2PC
        bundles = m["bundles"]
        total = bundle_total(bundles)
        if not covers(self.total_resources, total):
            # can NEVER fit on this node — fail creation synchronously
            self._reply(rec, m["reqid"],
                        error=f"Infeasible placement group {total}; "
                              f"node total {self.total_resources}")
            return
        # creation is async: reply now, reserve when resources allow;
        # PlacementGroup.ready() gates on pg_state == "created"
        self._reply(rec, m["reqid"], ok=True, state="pending")
        self._pending_local_pgs[m["pg_id"]] = {
            "bundles": bundles, "strategy": m.get("strategy", "PACK")}
        self._try_place_local_pgs()

    def _try_place_local_pgs(self) -> None:
        """Reserve queued single-node PGs once resources free up."""
        for pgb, info in list(self._pending_local_pgs.items()):
            total = bundle_total(info["bundles"])
            if not covers(self.available, total):
                continue
            for k, v in total.items():
                self.available[k] -= v
            pg_id = PlacementGroupID(pgb)
            self.pgs[pg_id] = PGRec(pg_id=pg_id, bundles=info["bundles"],
                                    strategy=info["strategy"])
            for i, b in enumerate(info["bundles"]):
                self.pg_available[(pgb, i)] = dict(b)
            del self._pending_local_pgs[pgb]
            self._schedule()

    def _h_pg_state(self, rec, m):
        if self._cluster_scope(rec, m):
            return
        pg_id = PlacementGroupID(m["pg_id"])
        if pg_id in self.pgs:
            st = "created"
        elif m["pg_id"] in self._pending_local_pgs:
            st = "pending"
        else:
            st = "removed"
        self._reply(rec, m["reqid"], ok=True, state=st)

    def _h_remove_pg(self, rec, m):
        if self._cluster_scope(rec, m):
            return
        pg_id = PlacementGroupID(m["pg_id"])
        self._pending_local_pgs.pop(m["pg_id"], None)
        pg = self.pgs.pop(pg_id, None)
        if pg is not None:
            for i, b in enumerate(pg.bundles):
                self.pg_available.pop((pg_id.binary(), i), None)
                for k, v in b.items():
                    self.available[k] = self.available.get(k, 0.0) + v
            self._try_place_local_pgs()
        if "reqid" in m:
            self._reply(rec, m["reqid"], ok=True)

    def _hh_pg_prepare(self, m: dict) -> None:
        bundle = m["bundle"]
        ok = all(self.available.get(k, 0.0) + 1e-9 >= v
                 for k, v in bundle.items())
        if ok:
            for k, v in bundle.items():
                self.available[k] -= v
            self._pg_prepared[(m["pg_id"], m["bundle_idx"])] = dict(bundle)
        self._head_reply(m["reqid"], ok=ok)

    def _hh_pg_commit(self, m: dict) -> None:
        key = (m["pg_id"], m["bundle_idx"])
        bundle = self._pg_prepared.pop(key, None)
        if bundle is not None:
            self.pg_available[key] = dict(bundle)
            self._pg_bundles[key] = dict(bundle)   # original reservation

    def _hh_pg_rollback(self, m: dict) -> None:
        bundle = self._pg_prepared.pop((m["pg_id"], m["bundle_idx"]), None)
        if bundle is not None:
            for k, v in bundle.items():
                self.available[k] = self.available.get(k, 0.0) + v

    def _hh_pg_remove_local(self, m: dict) -> None:
        key = (m["pg_id"], m["bundle_idx"])
        free = self.pg_available.pop(key, None)
        # hand the ORIGINAL bundle reservation back to the node; tasks
        # still drawing on the bundle release into the void afterwards,
        # same as the reference's bundle-return semantics
        orig = self._pg_bundles.pop(key, None)
        if orig is None and free is None:
            return
        for k, v in (orig or free).items():
            self.available[k] = self.available.get(k, 0.0) + v

    # -- state API

    def _fr_finish(self, tr: TaskRec, m: dict) -> None:
        """Fold a completed task's lifecycle stamps into the flight
        recorder.  The worker ships its stamps back inside task_done
        (socket workers executed a COPY of the spec); lane executors
        appended to the shared list, in which case both sides are the
        same object and the merge is a no-op."""
        spec = tr.spec
        if spec.get("fr_done"):
            # already folded: a duplicated task_done (chaos dup) must
            # not re-install the message's stamps and count twice
            return
        wfr = m.get("fr")
        nfr = spec.get("fr")
        if wfr is not None and wfr is not nfr \
                and (nfr is None or len(wfr) >= len(nfr)):
            spec["fr"] = wfr
        if spec.get("fr") is not None:
            rec = _fr._active
            if rec is not None:
                rec.stamp(spec, "done")
                rec.finish(spec, worker=tr.worker)
            spec["fr"] = None
            spec["fr_done"] = True

    def _record_event(self, spec: dict, state: str,
                      worker: Optional[int] = None) -> None:
        self.task_events.append({
            "task_id": spec["task_id"].hex() if isinstance(spec["task_id"], bytes)
            else spec["task_id"],
            "name": spec.get("name", ""),
            "state": state,
            "actor_id": spec.get("actor_id", b"").hex()
            if spec.get("actor_id") else None,
            "worker": worker,
            "time": time.time(),
        })
