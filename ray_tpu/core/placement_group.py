"""Placement groups: atomic reservation of resource bundles.

Reference analogue: python/ray/util/placement_group.py +
gcs_placement_group_manager.h:222 / gcs_placement_group_scheduler.h:265
(2PC prepare/commit across raylets).  On a single node the 2PC collapses to
one reservation step in the node service; the strategy field is kept so the
multi-node scheduler (later milestone) can pack/spread bundles.  The TPU
delta (SURVEY.md §7 design delta 3): bundles may demand "TPU" with slice
topology handled by the gang layer on top (ray_tpu.parallel).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

from ray_tpu.core.ids import PlacementGroupID
from ray_tpu.core.runtime import get_runtime

VALID_STRATEGIES = ("PACK", "SPREAD", "STRICT_PACK", "STRICT_SPREAD")

_pg_ready_fn = None


def _get_pg_ready_fn():
    """Module-level zero-cpu poller (one registration, shared by every
    PlacementGroup instance)."""
    global _pg_ready_fn
    if _pg_ready_fn is None:
        from ray_tpu.core.remote_function import remote

        @remote(num_cpus=0)
        def _pg_ready(pg_id_bin: bytes) -> bool:
            import time as _t
            from ray_tpu._config import get_config
            rt = get_runtime()
            delay = 0.02
            deadline = _t.monotonic() + get_config().pg_ready_poll_timeout_s
            while True:
                st = rt.client.request({"t": "pg_state",
                                        "pg_id": pg_id_bin})["state"]
                if st == "created":
                    return True
                if st == "removed":
                    raise RuntimeError(
                        "placement group was removed before it was "
                        "scheduled")
                if _t.monotonic() > deadline:
                    # an abandoned ready() on a never-placeable PG must
                    # not hold this pool worker forever
                    raise RuntimeError(
                        "placement group was still pending after "
                        "pg_ready_poll_timeout_s; call ready() again to "
                        "keep waiting")
                _t.sleep(delay)
                # back off: pending groups can pend for minutes — don't
                # hammer the single-threaded head with 50 Hz state RPCs
                delay = min(delay * 1.5, 0.5)

        _pg_ready_fn = _pg_ready
    return _pg_ready_fn


@dataclass
class PlacementGroup:
    id: PlacementGroupID
    bundles: list
    strategy: str
    _ready_ref: object = None

    def ready(self):
        """ObjectRef resolving when the group's 2PC reservation commits
        (reference: python/ray/util/placement_group.py ready() gating on
        gcs_placement_group_manager.h:222 creation).  Creation is async —
        on a busy cluster the ref stays unresolved until capacity frees;
        a removed group makes the ref raise."""
        rt = get_runtime()
        if self._ready_ref is not None:
            # if the cached poller already gave up (poll-timeout error),
            # respawn instead of handing back a permanently failed ref
            done, _ = rt.wait([self._ready_ref], timeout=0)
            if done:
                try:
                    rt.get([self._ready_ref], timeout=1)
                except Exception as e:
                    if "pg_ready_poll_timeout_s" in str(e):
                        self._ready_ref = None
        if self._ready_ref is None:
            self._ready_ref = _get_pg_ready_fn().remote(self.id.binary())
        return self._ready_ref

    def wait(self, timeout_seconds: float = 30.0) -> bool:
        """Block until created (True) or timeout (False).  A REMOVED
        group raises instead — callers retry-looping on wait() must be
        able to tell a busy cluster from a permanently dead PG."""
        import time
        from ray_tpu.core.client import GetTimeoutError
        deadline = time.monotonic() + timeout_seconds
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            try:
                get_runtime().get([self.ready()], timeout=remaining)
                return True
            except GetTimeoutError:
                return False
            except Exception as e:
                # remote exceptions surface as TaskError (not
                # RuntimeError) — match the poll-timeout by its marker
                if "pg_ready_poll_timeout_s" in str(e):
                    # poller expired mid-wait: spawn a fresh one and keep
                    # blocking for the caller's remaining budget
                    self._ready_ref = None
                    continue
                raise

    @property
    def bundle_specs(self) -> list:
        return list(self.bundles)


def placement_group(bundles: list[dict], strategy: str = "PACK",
                    name: str = "") -> PlacementGroup:
    if strategy not in VALID_STRATEGIES:
        raise ValueError(f"strategy must be one of {VALID_STRATEGIES}")
    if not bundles or any(not b for b in bundles):
        raise ValueError("bundles must be a non-empty list of non-empty dicts")
    norm = [{k: float(v) for k, v in b.items()} for b in bundles]
    pg_id = PlacementGroupID.from_random()
    rt = get_runtime()
    rt.client.request({"t": "create_pg", "pg_id": pg_id.binary(),
                       "bundles": norm, "strategy": strategy, "name": name})
    return PlacementGroup(pg_id, norm, strategy)


def remove_placement_group(pg: PlacementGroup) -> None:
    get_runtime().client.request({"t": "remove_pg", "pg_id": pg.id.binary()})


@dataclass
class PlacementGroupSchedulingStrategy:
    """Reference analogue: python/ray/util/scheduling_strategies.py:15."""
    placement_group: PlacementGroup
    placement_group_bundle_index: int = 0
    placement_group_capture_child_tasks: Optional[bool] = None
