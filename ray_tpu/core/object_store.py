"""Node-local shared-memory object store.

Capability analogue of plasma (reference: src/ray/object_manager/plasma/
store.h:55 — node-local immutable shared-memory objects, zero-copy reads,
refcount + LRU eviction, fallback spill to disk).  v1 backs each large
object with one POSIX shm segment (``multiprocessing.shared_memory``);
small objects (≤ max_direct_call_object_size) never reach this store — they
live inline in the control plane, mirroring the reference's in-process
memory store (src/ray/core_worker/store_provider/memory_store/).

The store has two halves:
  * ``ObjectStoreCore`` — bookkeeping that lives in the node service
    (sizes, refcounts, LRU order, spill state).
  * ``SharedMemoryClient`` — used by every worker/driver to create or map
    segments by name (zero-copy ``memoryview`` reads).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from multiprocessing import shared_memory
from typing import Optional

from ray_tpu.core.ids import ObjectID


def _segment_name(session: str, object_id: ObjectID) -> str:
    # Full object-id hex: the return/put index lives in the LAST 4 bytes,
    # so any truncation that drops the tail collides across puts.
    return f"rt_{session[:8]}_{object_id.hex()}"


class SharedMemoryClient:
    """Create/map shm segments. One per process."""

    def __init__(self, session: str):
        self._session = session
        self._open: dict[str, shared_memory.SharedMemory] = {}

    def create(self, object_id: ObjectID, size: int) -> memoryview:
        name = _segment_name(self._session, object_id)
        try:
            seg = shared_memory.SharedMemory(name=name, create=True,
                                             size=max(size, 1))
        except FileExistsError:
            # stale segment from a retried task whose first attempt died
            # mid-store (object ids are deterministic) — replace it
            self.unlink(object_id)
            seg = shared_memory.SharedMemory(name=name, create=True,
                                             size=max(size, 1))
        self._open[name] = seg
        return seg.buf[:size]

    def map(self, object_id: ObjectID) -> memoryview:
        name = _segment_name(self._session, object_id)
        seg = self._open.get(name)
        if seg is None:
            seg = shared_memory.SharedMemory(name=name)
            self._open[name] = seg
        return seg.buf

    def close(self, object_id: ObjectID) -> None:
        name = _segment_name(self._session, object_id)
        seg = self._open.pop(name, None)
        if seg is not None:
            try:
                seg.close()
            except BufferError:
                # A zero-copy view is still alive in this process; the
                # segment stays mapped until process exit.
                self._open[name] = seg

    def unlink(self, object_id: ObjectID) -> None:
        name = _segment_name(self._session, object_id)
        seg = self._open.pop(name, None)
        try:
            if seg is None:
                seg = shared_memory.SharedMemory(name=name)
            seg.close()
            seg.unlink()
        except (FileNotFoundError, BufferError):
            pass

    def shutdown(self) -> None:
        for seg in self._open.values():
            try:
                seg.close()
            except BufferError:
                pass
        self._open.clear()


@dataclass
class _Entry:
    size: int
    in_shm: bool                  # False once spilled
    spill_path: Optional[str] = None
    pin_count: int = 0            # task-arg / get pins
    created_at: float = field(default_factory=time.monotonic)
    last_access: float = field(default_factory=time.monotonic)


class ObjectStoreCore:
    """Bookkeeping for the node's shm budget: admission, eviction, spill.

    Eviction: refcount-aware LRU (reference: plasma eviction_policy.h);
    unpinned objects spill to disk when the budget is exceeded (reference:
    local_object_manager.h spilling via IO workers — here spill is done by
    the node service thread itself in v1).
    """

    def __init__(self, session: str, capacity: int, spill_dir: str):
        self.session = session
        self.capacity = capacity
        self.used = 0
        self.spill_dir = spill_dir
        self.entries: dict[ObjectID, _Entry] = {}
        self._shm = SharedMemoryClient(session)
        os.makedirs(spill_dir, exist_ok=True)
        self.num_spilled = 0
        self.num_restored = 0

    def register(self, object_id: ObjectID, size: int) -> None:
        if object_id in self.entries:
            return
        self.entries[object_id] = _Entry(size=size, in_shm=True)
        self.used += size
        if self.used > self.capacity:
            self._evict(self.used - self.capacity)

    def pin(self, object_id: ObjectID) -> None:
        e = self.entries.get(object_id)
        if e is not None:
            e.pin_count += 1
            e.last_access = time.monotonic()

    def unpin(self, object_id: ObjectID) -> None:
        e = self.entries.get(object_id)
        if e is not None and e.pin_count > 0:
            e.pin_count -= 1

    def contains(self, object_id: ObjectID) -> bool:
        return object_id in self.entries

    def is_spilled(self, object_id: ObjectID) -> Optional[str]:
        e = self.entries.get(object_id)
        return e.spill_path if e is not None and not e.in_shm else None

    def touch(self, object_id: ObjectID) -> None:
        e = self.entries.get(object_id)
        if e is not None:
            e.last_access = time.monotonic()

    def restore(self, object_id: ObjectID) -> None:
        """Bring a spilled object back into shm."""
        e = self.entries[object_id]
        if e.in_shm:
            return
        with open(e.spill_path, "rb") as f:
            data = f.read()
        buf = self._shm.create(object_id, len(data))
        buf[:] = data
        del buf
        e.in_shm = True
        self.used += e.size
        os.unlink(e.spill_path)
        e.spill_path = None
        self.num_restored += 1
        if self.used > self.capacity:
            self._evict(self.used - self.capacity)

    def delete(self, object_id: ObjectID) -> None:
        e = self.entries.pop(object_id, None)
        if e is None:
            return
        if e.in_shm:
            self.used -= e.size
            self._shm.unlink(object_id)
        elif e.spill_path:
            try:
                os.unlink(e.spill_path)
            except FileNotFoundError:
                pass

    def _evict(self, nbytes: int) -> int:
        """Spill unpinned objects, oldest-access first, until `nbytes` freed."""
        victims = sorted(
            (oid for oid, e in self.entries.items()
             if e.in_shm and e.pin_count == 0),
            key=lambda oid: self.entries[oid].last_access)
        freed = 0
        for oid in victims:
            if freed >= nbytes:
                break
            freed += self._spill(oid)
        return freed

    def _spill(self, object_id: ObjectID) -> int:
        e = self.entries[object_id]
        path = os.path.join(self.spill_dir, object_id.hex())
        buf = self._shm.map(object_id)
        with open(path, "wb") as f:
            f.write(buf[: e.size])
        del buf
        self._shm.unlink(object_id)
        e.in_shm = False
        e.spill_path = path
        self.used -= e.size
        self.num_spilled += 1
        return e.size

    def stats(self) -> dict:
        return {
            "num_objects": len(self.entries),
            "used_bytes": self.used,
            "capacity_bytes": self.capacity,
            "num_spilled": self.num_spilled,
            "num_restored": self.num_restored,
        }

    def shutdown(self) -> None:
        for oid in list(self.entries):
            self.delete(oid)
        self._shm.shutdown()
