"""Node-local shared-memory object store.

Capability analogue of plasma (reference: src/ray/object_manager/plasma/
store.h:55 — node-local immutable shared-memory objects, zero-copy reads,
refcount + LRU eviction, fallback spill to disk).  v1 backs each large
object with one POSIX shm segment (``multiprocessing.shared_memory``);
small objects (≤ max_direct_call_object_size) never reach this store — they
live inline in the control plane, mirroring the reference's in-process
memory store (src/ray/core_worker/store_provider/memory_store/).

The store has two halves:
  * ``ObjectStoreCore`` — bookkeeping that lives in the node service
    (sizes, refcounts, LRU order, spill state).
  * ``SharedMemoryClient`` — used by every worker/driver to create or map
    segments by name (zero-copy ``memoryview`` reads).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from multiprocessing import shared_memory
from typing import Optional

from ray_tpu.core.ids import ObjectID


def _segment_name(session: str, object_id: ObjectID) -> str:
    # Full object-id hex: the return/put index lives in the LAST 4 bytes,
    # so any truncation that drops the tail collides across puts.
    return f"rt_{session[:8]}_{object_id.hex()}"


def arena_name(session: str) -> str:
    """Name of the node-wide native shm arena for a session."""
    return f"rta_{session[:8]}"


def native_store_enabled() -> bool:
    """Native C++ arena store is the default; RAY_TPU_NATIVE_STORE=0
    falls back to the pure-python per-object-segment store."""
    if os.environ.get("RAY_TPU_NATIVE_STORE", "1") == "0":
        return False
    from ray_tpu import native
    return native.available()


class SharedMemoryClient:
    """Create/map shm segments. One per process."""

    def __init__(self, session: str):
        self._session = session
        self._open: dict[str, shared_memory.SharedMemory] = {}

    def seal(self, object_id: ObjectID) -> None:
        """Per-object segments are implicitly sealed by the register
        message ordering; the native arena needs an explicit seal."""

    def create(self, object_id: ObjectID, size: int) -> memoryview:
        name = _segment_name(self._session, object_id)
        try:
            seg = shared_memory.SharedMemory(name=name, create=True,
                                             size=max(size, 1))
        except FileExistsError:
            # stale segment from a retried task whose first attempt died
            # mid-store (object ids are deterministic) — replace it
            self.unlink(object_id)
            seg = shared_memory.SharedMemory(name=name, create=True,
                                             size=max(size, 1))
        self._open[name] = seg
        return seg.buf[:size]

    def map(self, object_id: ObjectID) -> memoryview:
        name = _segment_name(self._session, object_id)
        seg = self._open.get(name)
        if seg is None:
            seg = shared_memory.SharedMemory(name=name)
            self._open[name] = seg
        return seg.buf

    def close(self, object_id: ObjectID) -> None:
        name = _segment_name(self._session, object_id)
        seg = self._open.pop(name, None)
        if seg is not None:
            try:
                seg.close()
            except BufferError:
                # A zero-copy view is still alive in this process; the
                # segment stays mapped until process exit.
                self._open[name] = seg

    def unlink(self, object_id: ObjectID) -> None:
        name = _segment_name(self._session, object_id)
        seg = self._open.pop(name, None)
        try:
            if seg is None:
                seg = shared_memory.SharedMemory(name=name)
            seg.close()
            seg.unlink()
        except (FileNotFoundError, BufferError):
            pass

    def shutdown(self) -> None:
        for seg in self._open.values():
            try:
                seg.close()
            except BufferError:
                pass
        self._open.clear()


@dataclass
class _Entry:
    size: int
    in_shm: bool                  # False once spilled
    spill_path: Optional[str] = None
    pin_count: int = 0            # task-arg / get pins
    created_at: float = field(default_factory=time.monotonic)
    last_access: float = field(default_factory=time.monotonic)


class ObjectStoreCore:
    """Bookkeeping for the node's shm budget: admission, eviction, spill.

    Eviction: refcount-aware LRU (reference: plasma eviction_policy.h);
    unpinned objects spill to disk when the budget is exceeded (reference:
    local_object_manager.h spilling via IO workers — here spill is done by
    the node service thread itself in v1).
    """

    def __init__(self, session: str, capacity: int, spill_dir: str,
                 spill_uri: str = ""):
        from ray_tpu.core.spill import make_spill_backend
        self.session = session
        self.capacity = capacity
        self.used = 0
        self.spill_dir = spill_dir
        self.entries: dict[ObjectID, _Entry] = {}
        self._shm = SharedMemoryClient(session)
        os.makedirs(spill_dir, exist_ok=True)
        # pluggable target (reference: external_storage.py FileSystem/
        # smart_open backends) — file:// by default, s3:// opt-in
        self.spill_backend = make_spill_backend(spill_uri, spill_dir)
        self.num_spilled = 0
        self.num_restored = 0

    def register(self, object_id: ObjectID, size: int) -> None:
        if object_id in self.entries:
            return
        self.entries[object_id] = _Entry(size=size, in_shm=True)
        self.used += size
        if self.used > self.capacity:
            self._evict(self.used - self.capacity)

    def pin(self, object_id: ObjectID) -> None:
        e = self.entries.get(object_id)
        if e is not None:
            e.pin_count += 1
            e.last_access = time.monotonic()

    def unpin(self, object_id: ObjectID) -> None:
        e = self.entries.get(object_id)
        if e is not None and e.pin_count > 0:
            e.pin_count -= 1

    def contains(self, object_id: ObjectID) -> bool:
        return object_id in self.entries

    def is_spilled(self, object_id: ObjectID) -> Optional[str]:
        e = self.entries.get(object_id)
        return e.spill_path if e is not None and not e.in_shm else None

    def touch(self, object_id: ObjectID) -> None:
        e = self.entries.get(object_id)
        if e is not None:
            e.last_access = time.monotonic()

    def restore(self, object_id: ObjectID) -> None:
        """Bring a spilled object back into shm."""
        e = self.entries[object_id]
        if e.in_shm:
            return
        data = self.spill_backend.get(e.spill_path)
        buf = self._shm.create(object_id, len(data))
        buf[:] = data
        del buf
        self._shm.seal(object_id)
        e.in_shm = True
        e.last_access = time.monotonic()
        self.used += e.size
        self.spill_backend.delete(e.spill_path)
        e.spill_path = None
        self.num_restored += 1
        if self.used > self.capacity:
            # hold a pin across the balancing eviction: recency alone
            # does NOT protect the object we just restored — when it is
            # the only unpinned resident, LRU picks it and the caller's
            # reply would describe an object that is no longer mapped
            e.pin_count += 1
            try:
                self._evict(self.used - self.capacity)
            finally:
                e.pin_count -= 1

    def delete(self, object_id: ObjectID) -> None:
        e = self.entries.pop(object_id, None)
        if e is None:
            return
        if e.in_shm:
            self.used -= e.size
            self._shm.unlink(object_id)
        elif e.spill_path:
            self.spill_backend.delete(e.spill_path)

    def evict_for(self, nbytes: int) -> int:
        """Free >= nbytes (client need-space requests)."""
        return self._evict(nbytes)

    def _evict(self, nbytes: int) -> int:
        """Spill unpinned objects, oldest-access first, until `nbytes` freed."""
        victims = sorted(
            (oid for oid, e in self.entries.items()
             if e.in_shm and e.pin_count == 0),
            key=lambda oid: self.entries[oid].last_access)
        freed = 0
        for oid in victims:
            if freed >= nbytes:
                break
            freed += self._spill(oid)
        return freed

    def _spill(self, object_id: ObjectID) -> int:
        e = self.entries[object_id]
        buf = self._shm.map(object_id)
        locator = self.spill_backend.put(object_id.hex(), buf[: e.size])
        del buf
        self._shm.unlink(object_id)
        e.in_shm = False
        e.spill_path = locator
        self.used -= e.size
        self.num_spilled += 1
        return e.size

    def stats(self) -> dict:
        return {
            "num_objects": len(self.entries),
            "used_bytes": self.used,
            "capacity_bytes": self.capacity,
            "num_spilled": self.num_spilled,
            "num_restored": self.num_restored,
        }

    def shutdown(self) -> None:
        for oid in list(self.entries):
            self.delete(oid)
        self._shm.shutdown()


# --------------------------------------------------------------------------
# Native (C++) arena backend — one mmap'd shm arena per session, allocator
# and object table in shared memory (native/src/shm_store.cc), the
# capability analogue of plasma's dlmalloc-over-shm
# (reference: src/ray/object_manager/plasma/{store.h,dlmalloc.cc}).
# --------------------------------------------------------------------------


class ObjectExists(Exception):
    """A sealed object with this id is already in the store; the put is
    an idempotent no-op (the value is deterministic for a given id)."""


class NativeShmClient:
    """SharedMemoryClient-compatible facade over the session arena.

    ``create`` retries through an ``on_full`` callback (a synchronous
    "need space" request to the node service, the analogue of plasma's
    queued create requests, plasma/create_request_queue.h).
    """

    def __init__(self, session: str, on_full=None):
        from ray_tpu.native.store import attach_with_retry
        self._arena = attach_with_retry(arena_name(session))
        self._on_full = on_full

    def create(self, object_id: ObjectID, size: int):
        from ray_tpu.native.store import (NativeObjectExists,
                                          NativeStoreFull)
        attempts = 0
        while True:
            try:
                return self._arena.create(object_id.binary(), size)
            except NativeObjectExists:
                raise ObjectExists(object_id.hex()) from None
            except NativeStoreFull:
                attempts += 1
                if self._on_full is None or attempts > 20:
                    raise
                self._on_full(size)

    def seal(self, object_id: ObjectID) -> None:
        self._arena.seal(object_id.binary())

    def map(self, object_id: ObjectID):
        arr = self._arena.get(object_id.binary())
        if arr is None:
            raise KeyError(f"object {object_id.hex()} not in arena")
        return arr

    def close(self, object_id: ObjectID) -> None:
        # release is GC-driven (weakref.finalize on the mapped array)
        pass

    def unlink(self, object_id: ObjectID) -> None:
        self._arena.delete(object_id.binary())

    def shutdown(self) -> None:
        self._arena.detach()


def make_shm_client(session: str, native: bool, on_full=None):
    """Client-side factory: the node tells clients (register reply)
    whether the session runs the native arena."""
    if native:
        return NativeShmClient(session, on_full=on_full)
    return SharedMemoryClient(session)


class _NodeArenaClient:
    """Node-side SharedMemoryClient-compatible facade over the arena.

    ``create`` evicts (spills) through the owning core when the arena is
    full; ``map`` is a refcount-free lookup (the node holds pins while it
    reads, so GC-driven release is unnecessary on this side).
    """

    def __init__(self, core: "NativeObjectStoreCore"):
        self._core = core

    def create(self, object_id: ObjectID, size: int):
        from ray_tpu.native.store import NativeStoreFull
        for _ in range(8):
            try:
                return self._core._arena.create(object_id.binary(), size)
            except NativeStoreFull:
                freed = self._core._drain_pending_deletes()
                freed += self._core._evict(size)
                if freed == 0:
                    break
        raise NativeStoreFull(size)

    def seal(self, object_id: ObjectID) -> None:
        self._core._arena.seal(object_id.binary())

    def map(self, object_id: ObjectID):
        buf = self._core._arena.lookup(object_id.binary())
        if buf is None:
            raise KeyError(f"object {object_id.hex()} not in arena")
        return buf

    def close(self, object_id: ObjectID) -> None:
        pass

    def unlink(self, object_id: ObjectID) -> None:
        e = self._core.entries.get(object_id)
        self._core._delete_or_defer(object_id, e.size if e else 0)

    def shutdown(self) -> None:
        pass


class NativeObjectStoreCore(ObjectStoreCore):
    """Node-side bookkeeping over the native arena.

    Pin/LRU/spill policy stays in Python (it needs protocol context);
    allocation, the object table, and zero-copy reads are C++.  Deletes
    of objects with live zero-copy views are deferred until the native
    refcount drains (plasma parallels: eviction_policy.h refcount-aware
    eviction).
    """

    def __init__(self, session: str, capacity: int, spill_dir: str,
                 spill_uri: str = ""):
        from ray_tpu.core.spill import make_spill_backend
        from ray_tpu.native.store import NativeArena
        self.session = session
        self.capacity = capacity
        self.used = 0
        self.spill_dir = spill_dir
        self.spill_backend = make_spill_backend(spill_uri, spill_dir)
        self.entries: dict[ObjectID, _Entry] = {}
        self._arena = NativeArena(arena_name(session), capacity=capacity,
                                  create=True)
        try:
            self._shm = _NodeArenaClient(self)
            os.makedirs(spill_dir, exist_ok=True)
        except Exception:
            self._arena.destroy()
            raise
        self.num_spilled = 0
        self.num_restored = 0
        # deferred deletes (live zero-copy views): id -> size, still
        # counted in self.used until the arena block is truly reclaimed
        self._pending_delete: dict[ObjectID, int] = {}

    def register(self, object_id: ObjectID, size: int) -> None:
        # a re-created deterministic id supersedes any deferred delete;
        # its bytes were still counted in `used`, so drop them before
        # the base register re-adds the entry
        pending = self._pending_delete.pop(object_id, None)
        if pending is not None:
            self.used -= pending
        super().register(object_id, size)

    def evict_for(self, nbytes: int) -> int:
        """Free >= nbytes from the arena (client need-space requests)."""
        freed = self._drain_pending_deletes()
        if freed < nbytes:
            freed += self._evict(nbytes - freed)
        return freed

    def _delete_or_defer(self, object_id: ObjectID, size: int) -> bool:
        """Arena delete; defer while zero-copy views hold native refs."""
        from ray_tpu.native.store import RT_ERR_IN_USE
        rc = self._arena.delete_rc(object_id.binary())
        if rc == RT_ERR_IN_USE:
            self._pending_delete[object_id] = size
            return False
        return rc == 0

    def delete(self, object_id: ObjectID) -> None:
        e = self.entries.pop(object_id, None)
        if e is None:
            return
        if e.in_shm:
            # memory is only un-counted once the block is reclaimed
            if self._delete_or_defer(object_id, e.size):
                self.used -= e.size
        elif e.spill_path:
            self.spill_backend.delete(e.spill_path)

    def _spill(self, object_id: ObjectID) -> int:
        e = self.entries[object_id]
        id_bytes = object_id.binary()
        buf = self._arena.lookup(id_bytes)
        if buf is None:
            return 0
        locator = self.spill_backend.put(object_id.hex(), buf[: e.size])
        del buf
        if not self._arena.delete(id_bytes):
            # a zero-copy view is alive somewhere; can't reclaim yet
            self.spill_backend.delete(locator)
            return 0
        e.in_shm = False
        e.spill_path = locator
        self.used -= e.size
        self.num_spilled += 1
        return e.size

    def _drain_pending_deletes(self) -> int:
        from ray_tpu.native.store import RT_ERR_IN_USE
        freed = 0
        for oid, size in list(self._pending_delete.items()):
            rc = self._arena.delete_rc(oid.binary())
            if rc != RT_ERR_IN_USE:
                # deleted now, or already gone (NOT_FOUND): stop tracking
                self._pending_delete.pop(oid, None)
                self.used -= size
                freed += size
        return freed

    def stats(self) -> dict:
        s = super().stats()
        s["native"] = True
        s["arena_used_bytes"] = self._arena.used
        s["arena_num_objects"] = self._arena.num_objects
        return s

    def shutdown(self) -> None:
        for oid in list(self.entries):
            self.delete(oid)
        self._arena.destroy()


def make_object_store_core(session: str, capacity: int, spill_dir: str,
                           spill_uri: str = ""):
    """Node-side factory: native C++ arena when buildable, else python."""
    if native_store_enabled():
        try:
            return NativeObjectStoreCore(session, capacity, spill_dir,
                                         spill_uri=spill_uri)
        except Exception as e:
            import logging
            logging.getLogger("ray_tpu").warning(
                "native object store unavailable (%s: %s); falling back "
                "to the pure-python store", type(e).__name__, e)
    return ObjectStoreCore(session, capacity, spill_dir,
                           spill_uri=spill_uri)
