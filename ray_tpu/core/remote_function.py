"""@remote functions and the options surface.

Reference analogue: python/ray/remote_function.py (RemoteFunction:35,
_remote:241) and option validation (_private/ray_option_utils.py).
TPU delta: ``num_tpus`` replaces ``num_gpus`` and routes the task to the
in-process TPU executor (driver keeps device ownership — SURVEY.md §7).
"""

from __future__ import annotations

import functools
from typing import Any, Optional

from ray_tpu.core.runtime import get_runtime

_VALID_OPTIONS = {
    "name", "num_returns", "num_cpus", "num_tpus", "resources",
    "max_retries", "max_restarts", "max_concurrency", "namespace",
    "get_if_exists", "placement_group", "placement_group_bundle_index",
    "scheduling_strategy", "lifetime", "runtime_env", "concurrency_groups",
}


def _validate_options(opts: dict) -> None:
    unknown = set(opts) - _VALID_OPTIONS
    if unknown:
        raise ValueError(f"Unknown options: {sorted(unknown)}. "
                         f"Valid: {sorted(_VALID_OPTIONS)}")
    nr = opts.get("num_returns")
    if nr is not None and nr != "dynamic" and (not isinstance(nr, int) or nr < 0):
        raise ValueError(f"num_returns must be a non-negative int or "
                         f"'dynamic', got {nr!r}")
    cg = opts.get("concurrency_groups")
    if cg is not None:
        if (not isinstance(cg, dict) or not cg
                or not all(isinstance(k, str) and isinstance(v, int)
                           and v > 0 for k, v in cg.items())):
            raise ValueError(
                "concurrency_groups must be a non-empty dict of "
                f"group name -> positive int limit, got {cg!r}")


def _resources_from_options(opts: dict) -> dict:
    res = dict(opts.get("resources") or {})
    if opts.get("num_cpus") is not None:
        res["CPU"] = float(opts["num_cpus"])
    return res


def _pg_tuple(opts: dict):
    strategy = opts.get("scheduling_strategy")
    pg = opts.get("placement_group")
    idx = opts.get("placement_group_bundle_index", 0)
    if strategy is not None and hasattr(strategy, "placement_group"):
        pg = strategy.placement_group
        idx = strategy.placement_group_bundle_index or 0
    if pg is None:
        return None
    from ray_tpu.core.placement_group import PlacementGroup
    if isinstance(pg, PlacementGroup):
        return (pg.id.binary(), idx)
    return (pg, idx)


class RemoteFunction:
    def __init__(self, fn, **options):
        _validate_options(options)
        self._function = fn
        self._options = options
        self._function_id: Optional[str] = None
        self._exported_to = None
        self._template: Optional[dict] = None
        functools.update_wrapper(self, fn)

    def options(self, **options) -> "RemoteFunction":
        merged = {**self._options, **options}
        rf = RemoteFunction(self._function, **merged)
        rf._function_id = self._function_id
        rf._exported_to = self._exported_to
        return rf

    def remote(self, *args, **kwargs):
        rt = get_runtime()
        # Re-export when the runtime changed (shutdown + re-init): the new
        # node has an empty function store.
        if self._function_id is None or self._exported_to is not rt:
            self._function_id = rt.export_function(self._function)
            self._exported_to = rt
            self._template = None
        o = self._options
        make_template = getattr(rt, "make_task_template", None)
        if make_template is None:
            # duck-typed runtimes (ray:// ClientRuntime) take the plain
            # submit path
            return rt.submit_task(
                self._function_id, args, kwargs,
                name=o.get("name") or self._function.__qualname__,
                num_returns=o.get("num_returns", 1),
                resources=_resources_from_options(o),
                num_tpus=float(o.get("num_tpus") or 0),
                max_retries=o.get("max_retries",
                                  rt.client.config_dict["task_max_retries"]),
                placement_group=_pg_tuple(o),
                runtime_env=o.get("runtime_env"))
        # The static spec fields (descriptor, resources, prepared env)
        # are resolved once per (function, runtime) and cached — each
        # call only stamps ids and args (reference: _raylet.pyx caches
        # the serialized function descriptor on the RemoteFunction).
        if self._template is None:
            self._template = make_template(
                self._function_id,
                name=o.get("name") or self._function.__qualname__,
                num_returns=o.get("num_returns", 1),
                resources=_resources_from_options(o),
                num_tpus=float(o.get("num_tpus") or 0),
                max_retries=o.get("max_retries",
                                  rt.client.config_dict["task_max_retries"]),
                placement_group=_pg_tuple(o),
                runtime_env=o.get("runtime_env"))
        return rt.submit_task_template(self._template, args, kwargs)

    def bind(self, *args, **kwargs):
        """Lazy DAG node (reference: ray DAG .bind, dag/dag_node.py)."""
        from ray_tpu.dag.dag_node import FunctionNode
        return FunctionNode(self._function, args, kwargs,
                            options=self._options)

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Remote function '{self._function.__qualname__}' cannot be "
            f"called directly; use .remote().")

    def __getstate__(self):
        # The runtime handle is process-local (holds sockets) — the
        # receiving process re-exports against its own runtime.  The
        # template embeds this process's worker_id (owner), so it must
        # be rebuilt too.
        state = self.__dict__.copy()
        state["_exported_to"] = None
        state["_template"] = None
        return state


def remote(*args, **options):
    """``@remote`` / ``@remote(num_tpus=1, ...)`` for functions and classes
    (reference: ray.remote decorator, python/ray/__init__.py surface)."""
    from ray_tpu.core.actor import ActorClass
    import inspect

    def decorator(obj):
        if inspect.isclass(obj):
            return ActorClass(obj, **options)
        return RemoteFunction(obj, **options)

    if len(args) == 1 and not options and callable(args[0]):
        return decorator(args[0])
    if args:
        raise TypeError("@remote takes only keyword options")
    return decorator
