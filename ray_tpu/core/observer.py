"""Observer protocol: read-only request channel to a node service.

One shared implementation of the register + reqid-matched request/reply
loop used by the CLI, the dashboard, and anything else that wants
cluster state without a runtime (no shm mapping, no task submission)."""

from __future__ import annotations

import os
import uuid
from typing import Callable


def observer_connect(address: str, *, timeout: float = 10.0,
                     request_timeout: float = 30.0):
    """Returns (conn, request): request(msg) -> reply dict, raising
    RuntimeError on error replies.  Caller closes conn."""
    from ray_tpu.core import protocol

    conn = protocol.connect(address, timeout=timeout)
    conn.send({"t": "register", "kind": "observer", "reqid": 0,
               "worker_id": f"obs-{uuid.uuid4().hex[:8]}",
               "pid": os.getpid()})
    reply = conn.recv(timeout=timeout)
    if reply.get("error"):
        conn.close()
        raise RuntimeError(reply["error"])

    state = {"reqid": 0}

    def request(msg: dict) -> dict:
        state["reqid"] += 1
        rid = state["reqid"]
        msg = dict(msg)
        msg["reqid"] = rid
        conn.send(msg)
        while True:
            r = conn.recv(timeout=request_timeout)
            if r.get("t") == "reply" and r.get("reqid") == rid:
                if r.get("error"):
                    raise RuntimeError(r["error"])
                return r

    return conn, request


def observer_query(address: str, queries: list[dict],
                   request_timeout: float = 30.0) -> list[dict]:
    """One-shot batch of queries over a short-lived connection."""
    conn, request = observer_connect(address,
                                     request_timeout=request_timeout)
    try:
        return [request(q) for q in queries]
    finally:
        conn.close()
