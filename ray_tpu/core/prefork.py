"""Fork-server ("zygote") worker template.

The reference raylet amortizes worker startup with prestarted pool
processes and a startup-concurrency cap (reference:
src/ray/raylet/worker_pool.h:352 PrestartWorkers, :192).  On this
framework's hosts the dominant spawn cost is interpreter + import time
(ambient TPU-plugin site hooks make a cold python ~2.5 s); the fork
server pays it once: the template pre-imports the worker's module
graph, then forks a ready worker per request in milliseconds.

Protocol: the node service connects to the template's unix socket and
sends one JSON line per worker request
``{"address": ..., "stdout": path, "stderr": path, "env": {...}}``;
the template forks and replies ``{"pid": N}``.  Lifecycle ties: the
template exits when the control connection closes (node death leaves
no orphan template), and each child exits when its node connection
drops (normal worker behavior).

The template stays single-threaded and never connects to the node
itself, so fork() is safe: no locks can be mid-held, no recv threads
are lost in children.
"""

from __future__ import annotations

import argparse
import json
import os
import select
import signal
import socket
import sys


def _reap_children() -> None:
    """Collect exited workers so they don't sit as zombies (children of
    the template, not of the node service)."""
    while True:
        try:
            pid, _ = os.waitpid(-1, os.WNOHANG)
        except ChildProcessError:
            return
        if pid == 0:
            return


def _child(conn: socket.socket, req: dict) -> None:
    """Runs in the forked worker.  Never returns."""
    try:
        conn.close()
        os.setsid()
        out = os.open(req["stdout"],
                      os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        err = os.open(req["stderr"],
                      os.O_WRONLY | os.O_CREAT | os.O_APPEND, 0o644)
        os.dup2(out, 1)
        os.dup2(err, 2)
        os.close(out)
        os.close(err)
        os.environ.update(req.get("env") or {})
        from ray_tpu.core.worker import run_worker
        run_worker(req["address"])
        code = 0
    except BaseException:
        import traceback
        traceback.print_exc()
        code = 1
    finally:
        # _exit: the template's inherited atexit hooks / buffered state
        # must not run in the child
        os._exit(code)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--socket", required=True)
    args = ap.parse_args()

    # Pre-import the worker's dependency graph — the whole point of the
    # template.  Everything a worker touches before user code: client,
    # executor, serialization, runtime, numpy + the ctypes-based native
    # store binding (~0.25 s each, measured — at 24 concurrent children
    # on one core the un-preimported tail serializes into seconds).
    # NOT jax: import-time platform plugins may spawn threads, which
    # don't survive fork; workers lazily import jax pinned to CPU.
    import numpy                          # noqa: F401
    import ray_tpu.core.worker            # noqa: F401
    import ray_tpu.core.runtime           # noqa: F401
    import ray_tpu.core.remote_function   # noqa: F401
    import ray_tpu.core.device_objects    # noqa: F401
    import ray_tpu.runtime_env            # noqa: F401
    try:
        import ray_tpu.native.store       # noqa: F401
    except Exception:
        pass   # native store optional; workers fall back to shm
    from ray_tpu.core.serialization import get_context
    get_context()   # build the serde tables once (thread-free)

    lst = socket.socket(socket.AF_UNIX)
    try:
        os.unlink(args.socket)
    except FileNotFoundError:
        pass
    lst.bind(args.socket)
    lst.listen(1)
    # The node may die (SIGKILL, no cleanup) before ever connecting —
    # a plain accept() would orphan this template forever.  Poll for
    # reparenting (our parent IS the node service process).
    lst.settimeout(1.0)
    parent = os.getppid()
    while True:
        try:
            conn, _ = lst.accept()
            break
        except socket.timeout:
            if os.getppid() != parent:
                sys.exit(0)     # orphaned before first connection
    lst.close()
    signal.signal(signal.SIGCHLD, signal.SIG_DFL)
    conn.setblocking(False)

    buf = b""
    while True:
        ready, _, _ = select.select([conn], [], [], 1.0)
        _reap_children()
        if not ready:
            continue
        try:
            chunk = conn.recv(1 << 16)
        except BlockingIOError:
            continue
        except OSError:
            break
        if not chunk:
            break   # node closed the control connection: we're done
        buf += chunk
        while b"\n" in buf:
            line, buf = buf.split(b"\n", 1)
            if not line.strip():
                continue
            req = json.loads(line)
            pid = os.fork()
            if pid == 0:
                _child(conn, req)
            try:
                conn.sendall(json.dumps({"pid": pid}).encode() + b"\n")
            except OSError:
                break
    _reap_children()
    sys.exit(0)


if __name__ == "__main__":
    main()
