"""Cluster launcher: up/down/attach/exec from a YAML cluster config.

The user-facing entrypoint that turns a config file into a running
cluster (reference: python/ray/scripts/scripts.py up:1216 down:1292
attach:1376 exec:1674 over autoscaler/_private/commands.py).  The
north-star flow: ``ray_tpu up cluster.yaml`` provisions a TPU pod as a
head plus workers via TpuPodNodeProvider, ``exec`` runs commands over
ssh, ``down`` tears everything down.

Cluster config schema (the minimal analogue of ray-schema.json):

    cluster_name: demo
    provider:
      type: tpu_pod            # or "local" (testing)
      project: my-project
      zone: us-central2-b
      accelerator_type: v5litepod-8
      runtime_version: v2-alpha-tpuv5-lite
    min_workers: 0
    max_workers: 4
    initial_workers: 1
    head:
      port: 6380
    worker_nodes:              # node_config passed to create_node
      num_tpus: 4

Cluster state (head node id + address, launched workers) persists in
``~/.ray_tpu/clusters/<name>.json`` so later commands find the cluster.
"""

from __future__ import annotations

import json
import os
import subprocess
from typing import Optional

_STATE_DIR = os.path.expanduser("~/.ray_tpu/clusters")


class ClusterConfigError(ValueError):
    pass


def load_cluster_config(path: str) -> dict:
    import yaml
    with open(path) as f:
        cfg = yaml.safe_load(f) or {}
    if not isinstance(cfg, dict):
        raise ClusterConfigError("cluster config must be a mapping")
    if not cfg.get("cluster_name"):
        raise ClusterConfigError("cluster_name is required")
    prov = cfg.get("provider") or {}
    if prov.get("type") not in ("tpu_pod", "local"):
        raise ClusterConfigError(
            "provider.type must be 'tpu_pod' or 'local', got "
            f"{prov.get('type')!r}")
    if prov.get("type") == "tpu_pod":
        for key in ("project", "zone"):
            if not prov.get(key):
                raise ClusterConfigError(f"provider.{key} is required "
                                         "for tpu_pod")
    mn = int(cfg.get("min_workers", 0))
    mx = int(cfg.get("max_workers", max(mn, 1)))
    if mn < 0 or mx < mn:
        raise ClusterConfigError(
            f"need 0 <= min_workers <= max_workers, got {mn}..{mx}")
    cfg["min_workers"], cfg["max_workers"] = mn, mx
    cfg.setdefault("initial_workers", mn)
    cfg.setdefault("head", {})
    cfg.setdefault("worker_nodes", {})
    return cfg


def make_provider(cfg: dict):
    prov = cfg["provider"]
    if prov["type"] == "tpu_pod":
        from ray_tpu.autoscaler.tpu_pod_provider import TpuPodNodeProvider
        kw = {k: prov[k] for k in ("accelerator_type", "runtime_version",
                                   "chips_per_host") if k in prov}
        return TpuPodNodeProvider(
            project=prov["project"], zone=prov["zone"],
            name_prefix=prov.get("name_prefix",
                                 f"ray-tpu-{cfg['cluster_name']}"), **kw)
    from ray_tpu.autoscaler.node_provider import LocalNodeProvider
    # a DETERMINISTIC base dir: `down` runs in a fresh process and finds
    # the nodes `up` started via the provider's pid files
    base = prov.get("base_dir") or os.path.join(
        "/tmp/ray_tpu", f"launcher_{cfg['cluster_name']}")
    return LocalNodeProvider(base_dir=base)


# -- cluster state ----------------------------------------------------------

def _state_path(name: str) -> str:
    return os.path.join(_STATE_DIR, f"{name}.json")


def load_state(name: str) -> Optional[dict]:
    try:
        with open(_state_path(name)) as f:
            return json.load(f)
    except FileNotFoundError:
        return None


def save_state(name: str, state: dict) -> None:
    os.makedirs(_STATE_DIR, exist_ok=True)
    with open(_state_path(name), "w") as f:
        json.dump(state, f, indent=1)


def drop_state(name: str) -> None:
    try:
        os.unlink(_state_path(name))
    except FileNotFoundError:
        pass


# -- commands ---------------------------------------------------------------

def up(cfg: dict, provider=None, log=print) -> dict:
    """Provision head + initial workers; idempotent on the head (a
    second `up` against a live cluster only reconciles workers)."""
    name = cfg["cluster_name"]
    provider = provider or make_provider(cfg)
    state = load_state(name)
    if state is None:
        log(f"[{name}] creating head node ...")
        head_id, head_address = provider.create_head(
            dict(cfg.get("head") or {}),
            port=int((cfg.get("head") or {}).get("port", 6380)))
        state = {"cluster_name": name, "head_id": head_id,
                 "head_address": head_address, "workers": []}
        save_state(name, state)
        log(f"[{name}] head {head_id} at {head_address}")
    else:
        log(f"[{name}] head already up at {state['head_address']}")
    want = max(int(cfg.get("initial_workers", 0)),
               int(cfg.get("min_workers", 0)))
    while len(state["workers"]) < want:
        log(f"[{name}] creating worker "
            f"{len(state['workers']) + 1}/{want} ...")
        wid = provider.create_node(state["head_address"],
                                   dict(cfg.get("worker_nodes") or {}))
        state["workers"].append(wid)
        save_state(name, state)
    log(f"[{name}] up: head + {len(state['workers'])} workers")
    return state


def down(cfg: dict, provider=None, log=print,
         keep_head: bool = False) -> None:
    name = cfg["cluster_name"]
    provider = provider or make_provider(cfg)
    state = load_state(name)
    if state is None:
        log(f"[{name}] no recorded cluster state; checking provider ...")
        for n in provider.non_terminated_nodes():
            log(f"[{name}] terminating {n.node_id}")
            provider.terminate_node(n.node_id)
        return
    for wid in list(state["workers"]):
        log(f"[{name}] terminating worker {wid}")
        try:
            provider.terminate_node(wid)
        except Exception as e:     # keep tearing down the rest
            log(f"[{name}] WARNING: {wid}: {e}")
        state["workers"].remove(wid)
        save_state(name, state)
    if not keep_head:
        log(f"[{name}] terminating head {state['head_id']}")
        try:
            provider.terminate_node(state["head_id"])
        finally:
            drop_state(name)
    log(f"[{name}] down")


def exec_cmd(cfg: dict, command: str, provider=None,
             all_workers: bool = False, on_head: bool = True) -> str:
    """Run a shell command on the head (or every worker host)."""
    name = cfg["cluster_name"]
    state = load_state(name)
    if state is None:
        raise RuntimeError(f"cluster {name!r} is not up (no state)")
    provider = provider or make_provider(cfg)
    targets = [state["head_id"]] if on_head else list(state["workers"])
    out = []
    for t in targets:
        out.append(provider.exec_on(t, command, all_workers=all_workers))
    return "\n".join(out)


def attach_argv(cfg: dict, provider=None) -> list[str]:
    """argv for an interactive shell on the head node."""
    name = cfg["cluster_name"]
    state = load_state(name)
    if state is None:
        raise RuntimeError(f"cluster {name!r} is not up (no state)")
    provider = provider or make_provider(cfg)
    return provider.ssh_command(state["head_id"])


def attach(cfg: dict, provider=None) -> int:
    argv = attach_argv(cfg, provider)
    return subprocess.call(argv)


def submit(cfg: dict, script_path: str, provider=None, log=print) -> str:
    """Copy a local script to the head and run it there (`ray submit`)."""
    name = cfg["cluster_name"]
    state = load_state(name)
    if state is None:
        raise RuntimeError(f"cluster {name!r} is not up (no state)")
    provider = provider or make_provider(cfg)
    import base64
    with open(script_path, "rb") as f:
        body = f.read()
    remote = f"/tmp/ray_tpu_submit_{os.path.basename(script_path)}"
    # base64 keeps the upload safe for ARBITRARY script content (a
    # heredoc delimiter appearing in the body would truncate it and
    # shell-execute the tail) while staying on one ssh primitive
    b64 = base64.b64encode(body).decode()
    provider.exec_on(state["head_id"],
                     f"echo {b64} | base64 -d > {remote}")
    log(f"[{name}] running {remote} on head")
    return provider.exec_on(state["head_id"], f"python {remote}")
