"""ray_tpu.autoscaler: demand-driven cluster scaling.

Reference capability: the autoscaler stack (python/ray/autoscaler/ —
node_provider.py:13 provider interface, _private/autoscaler.py
StandardAutoscaler control loop, _private/monitor.py the monitor
process).  The TPU shape: nodes are whole TPU hosts/slices, so the
provider north star is the TPU-pod provider (gcloud TPU VM surface).
"""

from ray_tpu.autoscaler.autoscaler import Autoscaler, AutoscalerConfig
from ray_tpu.autoscaler.node_provider import (LocalNodeProvider,
                                              NodeProvider, NodeStatus)
from ray_tpu.autoscaler.tpu_pod_provider import TpuPodNodeProvider

__all__ = ["Autoscaler", "AutoscalerConfig", "NodeProvider", "NodeStatus",
           "LocalNodeProvider", "TpuPodNodeProvider"]
