"""TPU-pod node provider: nodes are TPU VM hosts provisioned via gcloud.

The north-star provider (SURVEY.md aux goals; reference interface:
python/ray/autoscaler/node_provider.py:13 — the reference's GCP provider
lives in autoscaler/_private/gcp/node_provider.py).  A "node" is a TPU
VM (single host or one slice), created with
``gcloud compute tpus tpu-vm create`` and bootstrapped with a startup
command that launches a NodeService joined to the head.

Untestable without GCP credentials — every gcloud invocation goes
through ``_run`` so tests can stub the CLI; ``available()`` gates use.
"""

from __future__ import annotations

import json
import shutil
import subprocess
import time
import uuid
from typing import Optional

from ray_tpu.autoscaler.node_provider import NodeProvider, NodeStatus

_BOOTSTRAP = (
    "python -m ray_tpu.core.node --head-address {head} "
    "--session tpu{suffix} --num-tpus {chips} "
    "--label provider_node_id={name} "
    ">> /tmp/ray_tpu_node.log 2>&1 &"
)

_HEAD_BOOTSTRAP = (
    "python -m ray_tpu start --head --port {port} "
    ">> /tmp/ray_tpu_head.log 2>&1 &"
)


def available() -> bool:
    return shutil.which("gcloud") is not None


class TpuPodNodeProvider(NodeProvider):
    def __init__(self, project: str, zone: str,
                 accelerator_type: str = "v5litepod-8",
                 runtime_version: str = "v2-alpha-tpuv5-lite",
                 name_prefix: str = "ray-tpu",
                 chips_per_host: int = 4):
        if not available():
            raise RuntimeError("gcloud CLI not found; TpuPodNodeProvider "
                               "requires the Google Cloud SDK")
        self.project = project
        self.zone = zone
        self.accelerator_type = accelerator_type
        self.runtime_version = runtime_version
        self.name_prefix = name_prefix
        self.chips_per_host = chips_per_host
        self._poll_s = 5.0            # state-poll cadence (tests shrink it)

    # -- gcloud plumbing ----------------------------------------------------

    def _run(self, *args: str, timeout: float = 600.0) -> str:
        cmd = ["gcloud", "compute", "tpus", "tpu-vm", *args,
               f"--project={self.project}", f"--zone={self.zone}",
               "--format=json", "--quiet"]
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout)
        if proc.returncode != 0:
            raise RuntimeError(f"gcloud failed: {' '.join(cmd)}\n"
                               f"{proc.stderr[-2000:]}")
        return proc.stdout

    # -- provider interface -------------------------------------------------

    def create_node(self, head_address: str, node_config: dict) -> str:
        """Full lifecycle: create → wait READY → bootstrap every host →
        verify the node service came up.  Any failure deletes the slice —
        a half-bootstrapped TPU VM must never leak billable capacity
        (reference lifecycle: autoscaler/_private/gcp/node_provider.py
        create_node + wait_for_operation)."""
        suffix = uuid.uuid4().hex[:8]
        name = f"{self.name_prefix}-{suffix}"
        self._run("create", name,
                  f"--accelerator-type="
                  f"{node_config.get('accelerator_type', self.accelerator_type)}",
                  f"--version="
                  f"{node_config.get('runtime_version', self.runtime_version)}")
        try:
            self._wait_state(name, "READY", timeout=600.0)
            bootstrap = _BOOTSTRAP.format(
                head=head_address, suffix=suffix, name=name,
                chips=node_config.get("num_tpus", self.chips_per_host))
            # --worker=all: every host of a multi-host slice starts a node
            # service (one NodeService per TPU host, the gang-member shape)
            self._run("ssh", name, "--worker=all",
                      f"--command={bootstrap}", timeout=900.0)
            self._verify_bootstrap(name)
        except Exception:
            try:
                self._run("delete", name)
            except Exception:
                pass  # already raising the root cause; deletion is best-effort
            raise
        return name

    def _wait_state(self, name: str, want: str, timeout: float) -> None:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            raw = self._run("describe", name)
            state = (json.loads(raw or "{}") or {}).get("state", "")
            if state == want:
                return
            if state in ("FAILED", "TERMINATED", "DELETING"):
                raise RuntimeError(f"TPU VM {name} entered state {state} "
                                   f"while waiting for {want}")
            time.sleep(self._poll_s)
        raise RuntimeError(f"TPU VM {name} not {want} after {timeout:.0f}s")

    def _verify_bootstrap(self, name: str, attempts: int = 5) -> None:
        """The bootstrap command backgrounds the node service, so ssh exit
        0 proves nothing — probe that the process is actually alive on
        every host, and surface the node log tail if it is not."""
        for i in range(attempts):
            try:
                out = self._run(
                    "ssh", name, "--worker=all",
                    "--command=pgrep -f ray_tpu.core.node >/dev/null "
                    "&& echo BOOTSTRAP_ALIVE", timeout=120.0)
                if "BOOTSTRAP_ALIVE" in out:
                    return
            except RuntimeError:
                pass  # ssh itself can flake while the VM settles
            time.sleep(self._poll_s)
        try:
            log = self._run("ssh", name, "--worker=all",
                            "--command=tail -n 40 /tmp/ray_tpu_node.log",
                            timeout=120.0)
        except RuntimeError:
            log = "<log unavailable>"
        raise RuntimeError(
            f"node service never came up on {name}; log tail:\n{log}")

    def create_head(self, node_config: dict, port: int = 6380
                    ) -> tuple[str, str]:
        """Provision the HEAD node: create a TPU VM, start the head
        service on worker 0, return (node_id, head_address) — the
        cluster-launcher entrypoint (reference:
        autoscaler/_private/commands.py get_or_create_head_node)."""
        suffix = uuid.uuid4().hex[:8]
        name = f"{self.name_prefix}-head-{suffix}"
        self._run("create", name,
                  f"--accelerator-type="
                  f"{node_config.get('accelerator_type', self.accelerator_type)}",
                  f"--version="
                  f"{node_config.get('runtime_version', self.runtime_version)}")
        try:
            self._wait_state(name, "READY", timeout=600.0)
            self._run("ssh", name, "--worker=0",
                      f"--command={_HEAD_BOOTSTRAP.format(port=port)}",
                      timeout=900.0)
            self._verify_head(name)
            ip = self._internal_ip(name)
        except Exception:
            try:
                self._run("delete", name)
            except Exception:
                pass
            raise
        return name, f"{ip}:{port}"

    def _verify_head(self, name: str, attempts: int = 5) -> None:
        """The bootstrap backgrounds the head service, so ssh exit 0
        proves nothing — probe the process and surface the log on
        failure (same discipline as _verify_bootstrap; a dead head
        address persisted to cluster state strands every worker)."""
        for _ in range(attempts):
            try:
                out = self._run(
                    "ssh", name, "--worker=0",
                    "--command=pgrep -f 'ray_tpu start --head' "
                    ">/dev/null && echo HEAD_ALIVE", timeout=120.0)
                if "HEAD_ALIVE" in out:
                    return
            except RuntimeError:
                pass
            time.sleep(self._poll_s)
        try:
            log = self._run("ssh", name, "--worker=0",
                            "--command=tail -n 40 /tmp/ray_tpu_head.log",
                            timeout=120.0)
        except RuntimeError:
            log = "<log unavailable>"
        raise RuntimeError(
            f"head service never came up on {name}; log tail:\n{log}")

    def _internal_ip(self, name: str) -> str:
        raw = self._run("describe", name)
        eps = (json.loads(raw or "{}") or {}).get("networkEndpoints") or []
        if not eps or not eps[0].get("ipAddress"):
            raise RuntimeError(f"TPU VM {name} has no network endpoint")
        return eps[0]["ipAddress"]

    def exec_on(self, node_id: str, command: str,
                all_workers: bool = False) -> str:
        """Run a shell command on a node's host(s) (`ray exec` shape)."""
        worker = "all" if all_workers else "0"
        return self._run("ssh", node_id, f"--worker={worker}",
                         f"--command={command}", timeout=900.0)

    def ssh_command(self, node_id: str) -> list[str]:
        """argv for an interactive shell on the node (`ray attach`)."""
        return ["gcloud", "compute", "tpus", "tpu-vm", "ssh", node_id,
                f"--project={self.project}", f"--zone={self.zone}",
                "--worker=0"]

    def terminate_node(self, node_id: str) -> None:
        self._run("delete", node_id)

    def non_terminated_nodes(self) -> list[NodeStatus]:
        raw = self._run("list")
        out = []
        for item in json.loads(raw or "[]"):
            name = item.get("name", "").rsplit("/", 1)[-1]
            if not name.startswith(self.name_prefix):
                continue
            state = item.get("state", "")
            status = {"READY": "running", "CREATING": "pending"}.get(
                state, "terminated" if state in ("DELETING", "TERMINATED")
                else "pending")
            out.append(NodeStatus(name, status, {"state": state}))
        return out
