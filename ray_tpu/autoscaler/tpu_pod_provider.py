"""TPU-pod node provider: nodes are TPU VM hosts provisioned via gcloud.

The north-star provider (SURVEY.md aux goals; reference interface:
python/ray/autoscaler/node_provider.py:13 — the reference's GCP provider
lives in autoscaler/_private/gcp/node_provider.py).  A "node" is a TPU
VM (single host or one slice), created with
``gcloud compute tpus tpu-vm create`` and bootstrapped with a startup
command that launches a NodeService joined to the head.

Untestable without GCP credentials — every gcloud invocation goes
through ``_run`` so tests can stub the CLI; ``available()`` gates use.
"""

from __future__ import annotations

import json
import shutil
import subprocess
import uuid
from typing import Optional

from ray_tpu.autoscaler.node_provider import NodeProvider, NodeStatus

_BOOTSTRAP = (
    "python -m ray_tpu.core.node --head-address {head} "
    "--session tpu{suffix} --num-tpus {chips} "
    "--label provider_node_id={name} "
    ">> /tmp/ray_tpu_node.log 2>&1 &"
)


def available() -> bool:
    return shutil.which("gcloud") is not None


class TpuPodNodeProvider(NodeProvider):
    def __init__(self, project: str, zone: str,
                 accelerator_type: str = "v5litepod-8",
                 runtime_version: str = "v2-alpha-tpuv5-lite",
                 name_prefix: str = "ray-tpu",
                 chips_per_host: int = 4):
        if not available():
            raise RuntimeError("gcloud CLI not found; TpuPodNodeProvider "
                               "requires the Google Cloud SDK")
        self.project = project
        self.zone = zone
        self.accelerator_type = accelerator_type
        self.runtime_version = runtime_version
        self.name_prefix = name_prefix
        self.chips_per_host = chips_per_host

    # -- gcloud plumbing ----------------------------------------------------

    def _run(self, *args: str, timeout: float = 600.0) -> str:
        cmd = ["gcloud", "compute", "tpus", "tpu-vm", *args,
               f"--project={self.project}", f"--zone={self.zone}",
               "--format=json", "--quiet"]
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout)
        if proc.returncode != 0:
            raise RuntimeError(f"gcloud failed: {' '.join(cmd)}\n"
                               f"{proc.stderr[-2000:]}")
        return proc.stdout

    # -- provider interface -------------------------------------------------

    def create_node(self, head_address: str, node_config: dict) -> str:
        suffix = uuid.uuid4().hex[:8]
        name = f"{self.name_prefix}-{suffix}"
        self._run("create", name,
                  f"--accelerator-type="
                  f"{node_config.get('accelerator_type', self.accelerator_type)}",
                  f"--version="
                  f"{node_config.get('runtime_version', self.runtime_version)}")
        bootstrap = _BOOTSTRAP.format(
            head=head_address, suffix=suffix, name=name,
            chips=node_config.get("num_tpus", self.chips_per_host))
        # --worker=all: every host of a multi-host slice starts a node
        # service (one NodeService per TPU host, the gang-member shape)
        self._run("ssh", name, "--worker=all",
                  f"--command={bootstrap}", timeout=900.0)
        return name

    def terminate_node(self, node_id: str) -> None:
        self._run("delete", node_id)

    def non_terminated_nodes(self) -> list[NodeStatus]:
        raw = self._run("list")
        out = []
        for item in json.loads(raw or "[]"):
            name = item.get("name", "").rsplit("/", 1)[-1]
            if not name.startswith(self.name_prefix):
                continue
            state = item.get("state", "")
            status = {"READY": "running", "CREATING": "pending"}.get(
                state, "terminated" if state in ("DELETING", "TERMINATED")
                else "pending")
            out.append(NodeStatus(name, status, {"state": state}))
        return out
