"""The autoscaler control loop.

Reference capability: StandardAutoscaler
(reference: python/ray/autoscaler/_private/autoscaler.py — periodic
reconciliation of demand vs supply) driven by the head's load view:
nodes report queued (unplaceable-now) resource demand in heartbeats, and
the head aggregates it in the state API.  Scale-up launches provider
nodes while queued demand persists; scale-down DRAINS nodes that have
been idle (nothing running, nothing queued) past the timeout — the head
flips them to DRAINING (no new placements), they hand off owned state
and exit via drain_done, and only THEN does the provider instance
terminate (with a drain-deadline backstop so a wedged node still goes
away) — never below min_workers, never above max_workers.  Planned
removal must never masquerade as node failure.

Runs as a thread against a live HeadService (in-process mode) or
standalone against a node/head address via an observer connection
(``python -m ray_tpu.autoscaler.monitor`` analogue:
reference _private/monitor.py).
"""

from __future__ import annotations

import threading
import time
import traceback
from dataclasses import dataclass, field
from typing import Optional

from ray_tpu.autoscaler.node_provider import NodeProvider


@dataclass
class AutoscalerConfig:
    min_workers: int = 0
    max_workers: int = 4
    idle_timeout_s: float = 30.0
    # graceful scale-down: how long a draining node gets to finish its
    # running work + hand off owned objects before the provider
    # instance is terminated regardless
    drain_deadline_s: float = 30.0
    # how long queued demand must persist before launching (debounce —
    # a burst the current nodes will drain in one tick shouldn't scale)
    upscale_delay_s: float = 1.0
    tick_s: float = 1.0
    node_config: dict = field(default_factory=dict)


class Autoscaler:
    def __init__(self, head, provider: NodeProvider,
                 config: Optional[AutoscalerConfig] = None,
                 head_address: Optional[str] = None):
        """head: a live HeadService (in-process) — its .address is the
        join target unless head_address overrides it."""
        self.head = head
        self.provider = provider
        self.config = config or AutoscalerConfig()
        self.head_address = head_address or head.address
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._demand_since: Optional[float] = None
        self._idle_since: dict[str, float] = {}   # node_hex -> ts
        # provider ids launched but not yet seen in the membership view
        # (nodes self-identify via the provider_node_id label, so the
        # mapping is exact, never join-order guesswork)
        self._launched: set[str] = set()
        # provider id -> {"hex", "deadline"}: nodes mid-drain; the
        # instance terminates once the node leaves the membership (it
        # exited via drain_done) or at the deadline backstop
        self._draining: dict[str, dict] = {}
        self.num_launches = 0
        self.num_terminations = 0
        self.num_drains = 0

    # -- cluster view -------------------------------------------------------

    def _nodes(self) -> list[dict]:
        return self.head.nodes_snapshot()

    # -- control loop -------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="raytpu-autoscaler")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)

    def _loop(self) -> None:
        while not self._stop.wait(self.config.tick_s):
            try:
                self.tick()
            except Exception:
                traceback.print_exc()

    def tick(self) -> None:
        cfg = self.config
        nodes = [n for n in self._nodes() if n["alive"]]
        # exact attribution: managed nodes carry their provider id as a
        # label (providers start them with --label provider_node_id=...)
        managed_nodes = {n["labels"]["provider_node_id"]: n
                         for n in nodes
                         if "provider_node_id" in n.get("labels", {})}
        self._launched -= set(managed_nodes)   # joined
        # reconcile against the provider: launches that died before
        # joining must not count as capacity forever
        provider_ids = {p.node_id
                        for p in self.provider.non_terminated_nodes()}
        self._launched &= provider_ids

        managed = len(self._launched) + len(managed_nodes)
        queued = sum(sum(n["queued"].values()) for n in nodes)

        # ---- scale up: queued demand that persists past the debounce
        now = time.monotonic()
        if queued > 0:
            if self._demand_since is None:
                self._demand_since = now
            if (now - self._demand_since >= cfg.upscale_delay_s
                    and managed < cfg.max_workers):
                self._launch()
                self._demand_since = None   # re-debounce per launch
        else:
            self._demand_since = None

        # floor
        while managed < cfg.min_workers:
            self._launch()
            managed += 1

        # ---- finish in-flight drains: terminate the provider instance
        # once the node has LEFT the membership (it exited cleanly via
        # drain_done) or its deadline backstop passed
        for pid, d in list(self._draining.items()):
            n = managed_nodes.get(pid)
            gone = n is None or not n.get("alive", True)
            if gone or now >= d["deadline"]:
                del self._draining[pid]
                self.num_terminations += 1
                try:
                    self.provider.terminate_node(pid)
                except Exception:
                    traceback.print_exc()

        # ---- scale down: managed nodes idle past the timeout DRAIN
        # first (graceful decommission through the head), terminate
        # only after the node exits — planned removal, not a kill that
        # peers mistake for a crash
        remaining = len(managed_nodes) - len(
            set(managed_nodes) & set(self._draining))
        for pid, n in managed_nodes.items():
            if pid in self._draining:
                continue
            h = n["node_id"]
            busy = (sum(n["queued"].values()) > 0
                    or any(n["available"].get(k, 0.0) + 1e-9
                           < n["resources"].get(k, 0.0)
                           for k in n["resources"]))
            if busy:
                self._idle_since.pop(h, None)
                continue
            first = self._idle_since.setdefault(h, now)
            if (now - first >= cfg.idle_timeout_s
                    and remaining > cfg.min_workers):
                self._idle_since.pop(h, None)
                remaining -= 1
                self.num_drains += 1
                self._draining[pid] = {
                    "hex": h,
                    # node deadline + margin for the handoff/exit ticks
                    "deadline": now + cfg.drain_deadline_s + 15.0,
                }
                try:
                    self.head.request_drain(h, cfg.drain_deadline_s)
                except Exception:
                    traceback.print_exc()

    def _launch(self) -> None:
        pid = self.provider.create_node(self.head_address,
                                        dict(self.config.node_config))
        self._launched.add(pid)
        self.num_launches += 1
