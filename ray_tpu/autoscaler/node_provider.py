"""Node providers: how the autoscaler creates and destroys nodes.

Reference capability: the NodeProvider interface
(reference: python/ray/autoscaler/node_provider.py:13,121 —
create_node / terminate_node / non_terminated_nodes / node lifecycle
tags).  A node here is a whole worker HOST running one NodeService
joined to the head (on TPU pods: one host of a slice).
"""

from __future__ import annotations

import os
import subprocess
import sys
import uuid
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class NodeStatus:
    node_id: str
    status: str          # pending | running | terminated
    metadata: dict = field(default_factory=dict)


class NodeProvider:
    """Provider contract (reference: node_provider.py NodeProvider)."""

    def create_node(self, head_address: str, node_config: dict) -> str:
        """Launch one node joined to `head_address`; returns provider
        node id (the node registers itself with the head
        asynchronously)."""
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> list[NodeStatus]:
        raise NotImplementedError

    def shutdown(self) -> None:
        for n in self.non_terminated_nodes():
            self.terminate_node(n.node_id)


class LocalNodeProvider(NodeProvider):
    """Nodes as local NodeService subprocesses — the test/dev provider
    (reference analogue: autoscaler/_private/fake_multi_node/
    node_provider.py, the multi-node-on-one-machine provider)."""

    def __init__(self, base_dir: Optional[str] = None):
        self._procs: dict[str, subprocess.Popen] = {}
        self._base = base_dir or os.path.join(
            "/tmp/ray_tpu", f"autoscale_{uuid.uuid4().hex[:8]}")
        os.makedirs(self._base, exist_ok=True)
        # pid files make nodes findable across provider INSTANCES — the
        # launcher's `down` runs in a fresh process and must still reap
        # what `up` started
        self._n = self._next_index()

    def _next_index(self) -> int:
        import glob
        mx = 0
        for p in glob.glob(os.path.join(self._base, "*.pid")):
            tail = os.path.basename(p).rsplit("-", 1)[-1][:-4]
            if tail.isdigit():
                mx = max(mx, int(tail))
        return mx

    def _write_pid(self, node_id: str, pid: int) -> None:
        with open(os.path.join(self._base, f"{node_id}.pid"), "w") as f:
            f.write(str(pid))

    def _read_pid(self, node_id: str) -> Optional[int]:
        try:
            with open(os.path.join(self._base, f"{node_id}.pid")) as f:
                return int(f.read().strip())
        except (FileNotFoundError, ValueError):
            return None

    def _drop_pid(self, node_id: str) -> None:
        try:
            os.unlink(os.path.join(self._base, f"{node_id}.pid"))
        except FileNotFoundError:
            pass

    def create_node(self, head_address: str, node_config: dict) -> str:
        self._n += 1
        node_id = f"local-{self._n:03d}"
        # distinct session prefix => distinct shm arena (arena name is
        # derived from session[:8])
        session = f"a{self._n:03d}{uuid.uuid4().hex[:8]}"
        args = [sys.executable, "-m", "ray_tpu.core.node",
                "--head-address", head_address,
                "--session", session,
                "--session-dir", os.path.join(self._base, node_id),
                "--label", f"provider_node_id={node_id}"]
        if node_config.get("num_cpus") is not None:
            args += ["--num-cpus", str(node_config["num_cpus"])]
        if node_config.get("num_tpus"):
            args += ["--num-tpus", str(node_config["num_tpus"])]
        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join(
            [p for p in sys.path if p]
            + [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p])
        log = open(os.path.join(self._base, f"{node_id}.log"), "ab")
        self._procs[node_id] = subprocess.Popen(
            args, env=env, stdout=log, stderr=log, start_new_session=True)
        self._write_pid(node_id, self._procs[node_id].pid)
        return node_id

    def create_head(self, node_config: dict, port: int = 0
                    ) -> tuple[str, str]:
        """Local head process for the launcher's `local` provider type:
        spawn a head service, read its address from the ready file."""
        self._n += 1
        node_id = f"local-head-{self._n:03d}"
        addr_file = os.path.join(self._base, f"{node_id}.addr")
        args = [sys.executable, "-m", "ray_tpu.core.head",
                "--port", str(port), "--address-file", addr_file]
        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join(
            [p for p in sys.path if p]
            + [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p])
        log = open(os.path.join(self._base, f"{node_id}.log"), "ab")
        self._procs[node_id] = subprocess.Popen(
            args, env=env, stdout=log, stderr=log, start_new_session=True)
        self._write_pid(node_id, self._procs[node_id].pid)
        import time as _t
        deadline = _t.monotonic() + 30
        while _t.monotonic() < deadline:
            try:
                with open(addr_file) as f:
                    addr = f.read().strip()
                if addr:
                    return node_id, addr
            except FileNotFoundError:
                pass
            _t.sleep(0.1)
        raise RuntimeError("local head did not publish its address")

    def exec_on(self, node_id: str, command: str,
                all_workers: bool = False) -> str:
        proc = subprocess.run(["sh", "-c", command], capture_output=True,
                              text=True, timeout=600)
        if proc.returncode != 0:
            raise RuntimeError(f"exec failed ({proc.returncode}): "
                               f"{proc.stderr[-1000:]}")
        return proc.stdout

    def ssh_command(self, node_id: str) -> list[str]:
        return ["sh"]   # "attach" to a local cluster is just a shell

    def terminate_node(self, node_id: str) -> None:
        import signal as _signal
        import time as _t
        p = self._procs.pop(node_id, None)
        if p is not None:
            p.terminate()
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
            self._drop_pid(node_id)
            return
        pid = self._read_pid(node_id)     # started by another process
        if pid is None:
            return
        for sig in (_signal.SIGTERM, _signal.SIGKILL):
            try:
                os.kill(pid, sig)
            except ProcessLookupError:
                break
            deadline = _t.monotonic() + 8
            while _t.monotonic() < deadline:
                try:
                    os.kill(pid, 0)
                except ProcessLookupError:
                    break
                _t.sleep(0.1)
            else:
                continue
            break
        self._drop_pid(node_id)

    def non_terminated_nodes(self) -> list[NodeStatus]:
        import glob
        out = []
        seen = set()
        for nid, p in list(self._procs.items()):
            seen.add(nid)
            if p.poll() is None:
                out.append(NodeStatus(nid, "running", {"pid": p.pid}))
            else:
                self._procs.pop(nid, None)
                self._drop_pid(nid)
        for path in glob.glob(os.path.join(self._base, "*.pid")):
            nid = os.path.basename(path)[:-4]
            if nid in seen:
                continue
            pid = self._read_pid(nid)
            alive = False
            if pid is not None:
                try:
                    os.kill(pid, 0)
                    alive = True
                except (ProcessLookupError, PermissionError):
                    pass
            if alive:
                out.append(NodeStatus(nid, "running", {"pid": pid}))
            else:
                self._drop_pid(nid)
        return out
