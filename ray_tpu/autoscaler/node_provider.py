"""Node providers: how the autoscaler creates and destroys nodes.

Reference capability: the NodeProvider interface
(reference: python/ray/autoscaler/node_provider.py:13,121 —
create_node / terminate_node / non_terminated_nodes / node lifecycle
tags).  A node here is a whole worker HOST running one NodeService
joined to the head (on TPU pods: one host of a slice).
"""

from __future__ import annotations

import os
import subprocess
import sys
import uuid
from dataclasses import dataclass, field
from typing import Optional


@dataclass
class NodeStatus:
    node_id: str
    status: str          # pending | running | terminated
    metadata: dict = field(default_factory=dict)


class NodeProvider:
    """Provider contract (reference: node_provider.py NodeProvider)."""

    def create_node(self, head_address: str, node_config: dict) -> str:
        """Launch one node joined to `head_address`; returns provider
        node id (the node registers itself with the head
        asynchronously)."""
        raise NotImplementedError

    def terminate_node(self, node_id: str) -> None:
        raise NotImplementedError

    def non_terminated_nodes(self) -> list[NodeStatus]:
        raise NotImplementedError

    def shutdown(self) -> None:
        for n in self.non_terminated_nodes():
            self.terminate_node(n.node_id)


class LocalNodeProvider(NodeProvider):
    """Nodes as local NodeService subprocesses — the test/dev provider
    (reference analogue: autoscaler/_private/fake_multi_node/
    node_provider.py, the multi-node-on-one-machine provider)."""

    def __init__(self, base_dir: Optional[str] = None):
        self._procs: dict[str, subprocess.Popen] = {}
        self._base = base_dir or os.path.join(
            "/tmp/ray_tpu", f"autoscale_{uuid.uuid4().hex[:8]}")
        os.makedirs(self._base, exist_ok=True)
        self._n = 0

    def create_node(self, head_address: str, node_config: dict) -> str:
        self._n += 1
        node_id = f"local-{self._n:03d}"
        # distinct session prefix => distinct shm arena (arena name is
        # derived from session[:8])
        session = f"a{self._n:03d}{uuid.uuid4().hex[:8]}"
        args = [sys.executable, "-m", "ray_tpu.core.node",
                "--head-address", head_address,
                "--session", session,
                "--session-dir", os.path.join(self._base, node_id),
                "--label", f"provider_node_id={node_id}"]
        if node_config.get("num_cpus") is not None:
            args += ["--num-cpus", str(node_config["num_cpus"])]
        if node_config.get("num_tpus"):
            args += ["--num-tpus", str(node_config["num_tpus"])]
        env = dict(os.environ)
        env["PYTHONPATH"] = os.pathsep.join(
            [p for p in sys.path if p]
            + [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p])
        log = open(os.path.join(self._base, f"{node_id}.log"), "ab")
        self._procs[node_id] = subprocess.Popen(
            args, env=env, stdout=log, stderr=log, start_new_session=True)
        return node_id

    def terminate_node(self, node_id: str) -> None:
        p = self._procs.pop(node_id, None)
        if p is None:
            return
        p.terminate()
        try:
            p.wait(timeout=10)
        except subprocess.TimeoutExpired:
            p.kill()

    def non_terminated_nodes(self) -> list[NodeStatus]:
        out = []
        for nid, p in list(self._procs.items()):
            if p.poll() is None:
                out.append(NodeStatus(nid, "running", {"pid": p.pid}))
            else:
                self._procs.pop(nid, None)
        return out
