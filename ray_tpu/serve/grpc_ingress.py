"""gRPC ingress for Serve.

Reference capability: the serve gRPC proxy
(python/ray/serve/_private/grpc_util.py + proxy gRPC mode,
src/ray/protobuf/serve.proto): a second ingress protocol next to HTTP,
for clients that want typed, multiplexed, low-overhead calls.

Implementation note: this image has protoc (message codegen) but not
the grpc_tools stub generator, so the service is wired with
grpc.method_handlers_generic_handler over the protoc-generated message
classes — functionally identical to generated stubs.
"""

from __future__ import annotations

import json
from typing import Optional

from ray_tpu.serve.http_proxy import _jsonable

_SERVICE = "ray_tpu.serve.RayTpuServe"


def _pb():
    # core.schema already puts ray_tpu/core/generated on sys.path
    import ray_tpu.core.schema  # noqa: F401 - path bootstrap
    import serve_pb2
    return serve_pb2


class GrpcIngress:
    """Serves Predict/Healthz/Routes for a controller's deployments."""

    def __init__(self, controller, host: str = "127.0.0.1",
                 port: int = 0, max_workers: int = 16):
        try:
            import grpc
        except ImportError as e:
            raise ImportError("gRPC ingress requires grpcio") from e
        from concurrent import futures
        pb = _pb()
        self.controller = controller
        ingress = self

        def predict(request, context):
            from ray_tpu.serve.handle import DeploymentHandle
            reply = pb.ServeReply()
            try:
                state = ingress.controller.get(request.deployment)
                handle = DeploymentHandle(state,
                                          request.method or "__call__")
                arg = (json.loads(request.payload)
                       if request.payload else None)
                # honor the CLIENT's deadline: holding a worker thread
                # past it just pins the pool for a caller that's gone
                remaining = context.time_remaining()
                timeout = (min(remaining, 300.0)
                           if remaining is not None else 300.0)
                result = handle.remote(arg).result(timeout=timeout)
                reply.payload = json.dumps(_jsonable(result)).encode()
            except Exception as e:  # noqa: BLE001 - wire to client
                reply.error = f"{type(e).__name__}: {e}"
            return reply

        def healthz(request, context):
            return pb.HealthzReply(status="ok")

        def routes(request, context):
            return pb.RoutesReply(
                deployments=sorted(ingress.controller.deployments))

        rpcs = {
            "Predict": grpc.unary_unary_rpc_method_handler(
                predict,
                request_deserializer=pb.ServeRequest.FromString,
                response_serializer=pb.ServeReply.SerializeToString),
            "Healthz": grpc.unary_unary_rpc_method_handler(
                healthz,
                request_deserializer=pb.HealthzRequest.FromString,
                response_serializer=pb.HealthzReply.SerializeToString),
            "Routes": grpc.unary_unary_rpc_method_handler(
                routes,
                request_deserializer=pb.RoutesRequest.FromString,
                response_serializer=pb.RoutesReply.SerializeToString),
        }
        self._server = grpc.server(
            futures.ThreadPoolExecutor(max_workers=max_workers))
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(_SERVICE, rpcs),))
        self.port = self._server.add_insecure_port(f"{host}:{port}")
        self.host = host
        self.address = f"{host}:{self.port}"
        self._server.start()

    def stop(self, grace: Optional[float] = 1.0):
        self._server.stop(grace)


class GrpcServeClient:
    """Typed client (the stub the reference generates; hand-wired here
    for the same reason as the server)."""

    def __init__(self, address: str):
        import grpc
        pb = _pb()
        self._pb = pb
        self._channel = grpc.insecure_channel(address)
        base = f"/{_SERVICE}/"
        self._predict = self._channel.unary_unary(
            base + "Predict",
            request_serializer=pb.ServeRequest.SerializeToString,
            response_deserializer=pb.ServeReply.FromString)
        self._healthz = self._channel.unary_unary(
            base + "Healthz",
            request_serializer=pb.HealthzRequest.SerializeToString,
            response_deserializer=pb.HealthzReply.FromString)
        self._routes = self._channel.unary_unary(
            base + "Routes",
            request_serializer=pb.RoutesRequest.SerializeToString,
            response_deserializer=pb.RoutesReply.FromString)

    def predict(self, deployment: str, data=None, method: str = "",
                timeout: float = 300.0):
        req = self._pb.ServeRequest(
            deployment=deployment, method=method,
            payload=json.dumps(data).encode() if data is not None
            else b"")
        reply = self._predict(req, timeout=timeout)
        if reply.error:
            raise RuntimeError(reply.error)
        return json.loads(reply.payload) if reply.payload else None

    def healthz(self, timeout: float = 10.0) -> str:
        return self._healthz(self._pb.HealthzRequest(),
                             timeout=timeout).status

    def routes(self, timeout: float = 10.0) -> list:
        return list(self._routes(self._pb.RoutesRequest(),
                                 timeout=timeout).deployments)

    def close(self):
        self._channel.close()
