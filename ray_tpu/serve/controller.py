"""Serve control plane: replica lifecycle + routing.

Reference capability: the controller/proxy/replica triangle —
ServeController reconciliation (python/ray/serve/controller.py:60 +
_private/deployment_state.py:962,1812), Router/ReplicaSet round-robin
with max-concurrent backpressure (_private/router.py:261,62,221), replica
autoscaling from ongoing-request load (_private/autoscaling_policy.py:10).

Single-host shape: the controller is a driver-side object; replicas are
core-runtime actors when the runtime is up (process isolation, parallel
queries) or in-process objects otherwise.  Reconciliation runs inline on
deploy/delete and on the autoscaler tick — the reference's control loop
collapsed to its fixed points, same observable behavior.
"""

from __future__ import annotations

import itertools
import threading
import time
import traceback
from dataclasses import dataclass, field
from typing import Any, Optional

from ray_tpu.core import fault_injection as _fi
from ray_tpu.serve.deployment import Deployment

# replica lifecycle (the drain state machine): every PLANNED removal
# goes ACTIVE -> DRAINING -> STOPPED instead of ACTIVE -> killed.  A
# DRAINING replica is out of the routable membership (router, admission
# and the published snapshot all stop seeing it) but keeps finishing
# its in-flight work until the controller's drain tick observes it idle
# — or its deadline expires, at which point the EXPLICIT fallback is
# the kill+resume path, counted as drain_timeout, never masked.
LIFECYCLE_ACTIVE = "active"
LIFECYCLE_DRAINING = "draining"
LIFECYCLE_STOPPED = "stopped"


@dataclass
class ReplicaContext:
    """Identity of the replica currently being constructed/run
    (reference: serve.get_replica_context()).  The inference layer uses
    it to give each replica's engine a distinct name + metric labels."""
    deployment: str
    replica_tag: str


_replica_ctx = threading.local()


def get_replica_context() -> Optional[ReplicaContext]:
    """The ReplicaContext while a replica body is being constructed on
    this thread (None outside replica construction)."""
    return getattr(_replica_ctx, "ctx", None)


class _InProcReplica:
    def __init__(self, deployment: Deployment, tag: str = ""):
        _replica_ctx.ctx = ReplicaContext(deployment.name, tag)
        try:
            self._user = deployment.build_replica()
        finally:
            _replica_ctx.ctx = None

    def handle_request(self, method: str, args, kwargs):
        target = (self._user if method == "__call__"
                  else getattr(self._user, method))
        if method == "__call__" and not callable(target):
            target = self._user.__call__
        return target(*args, **kwargs)

    def health(self):
        # a user body that defines health() (e.g. an inference replica
        # whose engine can die) overrides the optimistic default — the
        # fleet self-heal path depends on a dead engine reading False
        probe = getattr(self._user, "health", None)
        if callable(probe):
            try:
                return bool(probe())
            except Exception:
                return False
        return True

    def close(self):
        """Replica teardown: in-process replicas share the driver, so a
        scale-down must give the user object a chance to release its
        resources (e.g. an inference engine's KV pool + loop thread) —
        actor replicas get process exit instead."""
        teardown = getattr(self._user, "teardown", None)
        if callable(teardown):
            teardown()


class _ActorReplicaShim:
    """The actor-side wrapper (reference: RayServeReplica
    _private/replica.py:260)."""

    def __init__(self, deployment_bytes: bytes, tag: str = ""):
        import cloudpickle
        self._dep: Deployment = cloudpickle.loads(deployment_bytes)
        _replica_ctx.ctx = ReplicaContext(self._dep.name, tag)
        try:
            self._user = self._dep.build_replica()
        finally:
            _replica_ctx.ctx = None

    def handle_request(self, method: str, args, kwargs):
        target = (self._user if method == "__call__"
                  else getattr(self._user, method))
        if method == "__call__" and not callable(target):
            target = self._user.__call__
        return target(*args, **kwargs)

    def health(self):
        # same contract as the in-proc replica: a body that can die
        # in place (engine stopped, actor process still up) must read
        # unhealthy so restart_dead replaces it
        probe = getattr(self._user, "health", None)
        if callable(probe):
            try:
                return bool(probe())
            except Exception:
                return False
        return True


@dataclass
class ReplicaHandle:
    impl: Any                      # _InProcReplica or actor handle
    is_actor: bool
    tag: str = ""                  # stable identity ("<deployment>#<n>")
    ongoing: int = 0               # in-flight queries (router-side count)
    lifecycle: str = LIFECYCLE_ACTIVE
    drain_deadline: float = 0.0    # monotonic; set when DRAINING


class DeploymentState:
    """Tracks one deployment's replicas (reference:
    deployment_state.py DeploymentState; states collapsed to
    RUNNING/dead)."""

    def __init__(self, deployment: Deployment, use_actors: bool,
                 on_membership_change=None):
        self.deployment = deployment
        self.use_actors = use_actors
        self.replicas: list[ReplicaHandle] = []
        # replicas mid-drain: OUT of the routable membership (router /
        # assign_replica / the published snapshot only see
        # self.replicas) but not yet torn down — drain_tick() settles
        # them to STOPPED, and restart_dead never sees them, so
        # self-heal cannot resurrect a deliberate drain
        self.draining: list[ReplicaHandle] = []
        self._rr = itertools.count()
        self._replica_seq = itertools.count()
        self._lock = threading.Lock()
        self._on_membership_change = on_membership_change
        # serve.fleet.enable() installs the fleet layer here: routing
        # moves to the occupancy router and autoscale_tick switches from
        # router-side ongoing counts to the fleet's engine-load signal
        self.fleet = None
        # request counters for /metrics + status (reference: serve's
        # per-deployment autoscaling/QPS metrics, autoscaling_metrics.py)
        self.request_metrics = {"requests": 0, "errors": 0,
                                "latency_sum_s": 0.0}
        self.scale_to(deployment.options.num_replicas)

    def record_request(self, latency_s: float, error: bool) -> None:
        with self._lock:
            self.request_metrics["requests"] += 1
            if error:
                self.request_metrics["errors"] += 1
            self.request_metrics["latency_sum_s"] += latency_s

    def _membership_changed(self) -> None:
        if self._on_membership_change is not None:
            try:
                self._on_membership_change(self)
            except Exception:
                traceback.print_exc()

    # -- replica lifecycle -------------------------------------------------

    def _start_replica(self) -> ReplicaHandle:
        tag = f"{self.deployment.name}#{next(self._replica_seq)}"
        if self.use_actors:
            import cloudpickle
            import ray_tpu
            Actor = ray_tpu.remote(_ActorReplicaShim)
            h = Actor.remote(cloudpickle.dumps(self.deployment), tag)
            return ReplicaHandle(h, True, tag)
        return ReplicaHandle(_InProcReplica(self.deployment, tag),
                             False, tag)

    def scale_to(self, n: int) -> None:
        """Immediate (non-draining) reconciliation to ``n`` replicas.
        Excess replicas are KILLED in place — the kill+resume path.  The
        autoscaler's shrink uses drain_replicas instead; this path
        remains for deploy/delete/explicit scaling, and marks its
        victims STOPPED first so any in-flight request that dies with
        them is classified as a scale-down resume, never a failure."""
        n = max(0, n)
        changed = False
        removed: list[ReplicaHandle] = []
        with self._lock:
            while len(self.replicas) > n:
                removed.append(self.replicas.pop())
                changed = True
            if n == 0 and self.draining:
                # scaling to zero (delete/redeploy) pre-empts any drain
                # in progress: tear the draining replicas down too
                removed.extend(self.draining)
                self.draining.clear()
                changed = True
            missing = n - len(self.replicas)
        # replica construction runs OUTSIDE the lock: building can be
        # expensive (model load, engine warmup) and must not block
        # routing (assign_replica) on the deployment lock meanwhile
        for _ in range(max(0, missing)):
            r = self._start_replica()
            with self._lock:
                if len(self.replicas) < n:
                    self.replicas.append(r)
                    changed = True
                else:           # concurrent scale-down won the race
                    removed.append(r)
        # teardown outside the lock: a slow user teardown must not block
        # routing (assign_replica) on the deployment lock
        for r in removed:
            self._teardown_replica(r)
        if changed:
            self._membership_changed()

    def _teardown_replica(self, r: ReplicaHandle) -> None:
        """Kill a replica's body.  Marking it STOPPED first lets the
        fleet's resume path classify the death of anything still in
        flight as ``resumed_scale_down`` (a deliberate removal), not
        ``resumed_failure`` — the r13 masking bug inverted."""
        r.lifecycle = LIFECYCLE_STOPPED
        try:
            if r.is_actor:
                import ray_tpu
                ray_tpu.kill(r.impl)
            else:
                r.impl.close()
        except Exception:
            traceback.print_exc()

    # -- graceful drain (planned scale-down) -------------------------------

    def drain_replicas(self, n: int, deadline_s: float = 30.0, *,
                       reason: str = "scale_down",
                       replicas: Optional[list] = None
                       ) -> list[ReplicaHandle]:
        """Move ``n`` replicas ACTIVE -> DRAINING: out of the routable
        membership immediately, bodies told to stop admitting
        (``drain()`` hook), teardown deferred to drain_tick() — which
        waits for in-flight work to finish or the deadline to pass.
        ``replicas`` targets specific handles (tests / operator
        maintenance); default picks from the tail."""
        deadline = time.monotonic() + max(0.0, float(deadline_s))
        moved: list[ReplicaHandle] = []
        with self._lock:
            pool = (list(replicas) if replicas is not None
                    else list(reversed(self.replicas)))
            for r in pool:
                if len(moved) >= n or r not in self.replicas:
                    continue
                self.replicas.remove(r)
                r.lifecycle = LIFECYCLE_DRAINING
                r.drain_deadline = deadline
                self.draining.append(r)
                moved.append(r)
        for r in moved:
            self._begin_body_drain(r)
            if self.fleet is not None:
                self.fleet.note("drain_begin", replica=r.tag,
                                reason=reason,
                                deadline_s=round(float(deadline_s), 3))
                if getattr(self.fleet, "prefix", None) is not None:
                    # cluster prefix plane: a DRAINING holder serves no
                    # fetches — drop its directory entries NOW (not at
                    # teardown), so adoptions stop targeting it the
                    # moment the drain begins
                    self.fleet.prefix.invalidate_holder(r.tag)
            self._drain_chaos("replica_drain", replica=r)
        if moved:
            self._membership_changed()
        return moved

    def _drain_chaos(self, point: str, **ctx) -> None:
        """Fault-plane hook on the drain path (points: replica_drain /
        replica_drain_timeout): zero-overhead gate when disarmed."""
        fi = _fi._active
        if fi is None:
            return
        ctx["state"] = self
        fi.on_drain(point, ctx)

    def _begin_body_drain(self, r: ReplicaHandle) -> None:
        """Tell the replica body to stop admitting (best-effort: bodies
        without a drain() hook simply finish their in-flight calls —
        r.ongoing is the signal drain_tick waits on for those)."""
        try:
            if r.is_actor:
                r.impl.handle_request.remote("drain", (), {})
            else:
                drain = getattr(getattr(r.impl, "_user", None), "drain",
                                None)
                if callable(drain):
                    drain()
        except Exception:
            traceback.print_exc()

    def _replica_drained(self, r: ReplicaHandle) -> bool:
        """True once nothing is left in flight on a draining replica:
        router-held calls released AND (when the body exposes engine
        gauges) no active slots or queued engine work."""
        if r.ongoing > 0:
            return False
        try:
            if r.is_actor:
                import ray_tpu
                st = ray_tpu.get(
                    r.impl.handle_request.remote("fleet_stats", (), {}),
                    timeout=5)
            else:
                user = getattr(r.impl, "_user", None)
                probe = getattr(user, "fleet_stats", None)
                st = probe() if callable(probe) else None
        except Exception:
            return True     # body already dead: nothing left to wait for
        if not st or st.get("stopped"):
            return True
        return (int(st.get("active_slots", 0)) == 0
                and int(st.get("waiting_requests", 0)) == 0)

    def drain_tick(self) -> None:
        """Settle DRAINING replicas: drained -> teardown (counted
        ``drained``); deadline passed -> EXPLICIT fallback to the
        kill+resume path (counted ``drain_timeout`` — in-flight streams
        die with the typed replica-death error and resume elsewhere,
        classified as scale-down resumes, never masked)."""
        with self._lock:
            snapshot = list(self.draining)
        if not snapshot:
            return
        now = time.monotonic()
        for r in snapshot:
            done = self._replica_drained(r)
            timed_out = not done and now >= r.drain_deadline
            if not done and not timed_out:
                continue
            with self._lock:
                if r not in self.draining:
                    continue    # a concurrent settle won the race
                self.draining.remove(r)
            fleet = self.fleet
            if timed_out:
                if fleet is not None:
                    fleet._count("drain_timeout")
                    fleet.note("drain_timeout", replica=r.tag,
                               in_flight=r.ongoing)
                self._drain_chaos("replica_drain_timeout", replica=r)
            elif fleet is not None:
                fleet._count("drained")
                fleet.note("drain_complete", replica=r.tag)
            self._teardown_replica(r)

    def restart_dead(self) -> int:
        """Health-check replicas; replace dead ones (reference:
        deployment_state reconciliation of FAILED replicas).  In-proc
        replicas are probed too: an inference replica whose engine was
        killed reads unhealthy and gets replaced — the fleet's
        self-heal path after a chaos kill."""
        dead: list[int] = []
        with self._lock:
            snapshot = list(enumerate(self.replicas))
        for i, r in snapshot:
            if r.lifecycle != LIFECYCLE_ACTIVE:
                # lifecycle, not just probe health: a DRAINING replica
                # reads "unhealthy-ish" the moment its engines wind down
                # — self-heal must never resurrect a deliberate drain
                continue
            ok = True
            if r.is_actor:
                import ray_tpu
                try:
                    ok = ray_tpu.get(r.impl.health.remote(), timeout=30)
                except Exception:
                    ok = False
            else:
                ok = r.impl.health()
            if not ok:
                dead.append(i)
        replaced = 0
        for i in dead:
            fresh = self._start_replica()   # outside the lock (slow)
            installed = False
            with self._lock:
                if i < len(self.replicas) \
                        and self.replicas[i] is snapshot[i][1]:
                    self.replicas[i] = fresh
                    installed = True
                    replaced += 1
            if not installed:   # membership moved under us; release it
                try:
                    if fresh.is_actor:
                        import ray_tpu
                        ray_tpu.kill(fresh.impl)
                    else:
                        fresh.impl.close()
                except Exception:
                    traceback.print_exc()
        if replaced:
            self._membership_changed()
        return replaced

    # -- routing -----------------------------------------------------------

    def assign_replica(self, timeout: float = 60.0) -> ReplicaHandle:
        """Round-robin among replicas with free slots; block if all are
        at max_concurrent_queries (reference: router.py:221
        assign_replica backpressure).  A deployment stuck at zero
        replicas past the timeout raises instead of spinning forever."""
        maxq = self.deployment.options.max_concurrent_queries
        deadline = time.monotonic() + timeout
        while True:
            with self._lock:
                if self.replicas:
                    for _ in range(len(self.replicas)):
                        i = next(self._rr) % len(self.replicas)
                        r = self.replicas[i]
                        if r.ongoing < maxq:
                            r.ongoing += 1
                            return r
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"deployment {self.deployment.name!r}: no replica "
                    f"available within {timeout}s "
                    f"({len(self.replicas)} replicas, all saturated)"
                    if self.replicas else
                    f"deployment {self.deployment.name!r} has no "
                    "replicas (deleted or scaled to zero?)")
            time.sleep(0.001)

    def release(self, r: ReplicaHandle):
        with self._lock:
            r.ongoing = max(0, r.ongoing - 1)

    def ongoing_per_replica(self) -> float:
        with self._lock:
            if not self.replicas:
                return 0.0
            return sum(r.ongoing for r in self.replicas) / len(self.replicas)

    def autoscale_tick(self) -> None:
        auto = self.deployment.options.autoscaling
        if auto is None:
            return
        cur = len(self.replicas)
        fleet = self.fleet
        if fleet is not None:
            # occupancy-driven scaling: the fleet's load signal is the
            # REAL per-deployment demand — engine-held slots + engine
            # queue depth + requests parked at the ingress — instead of
            # the router-side ongoing count (which undercounts streams
            # and queued work).  Proportional step (reference:
            # calculate_desired_num_replicas), capped at doubling per
            # tick, with shrink hysteresis at half the target.
            total = fleet.total_load()
            import math
            desired = max(1, math.ceil(
                total / max(auto.target_ongoing_requests, 1e-9)))
            if desired > cur:
                desired = min(desired, max(cur + 1, cur * 2))
            elif desired < cur:
                per = total / cur if cur else 0.0
                if per >= auto.target_ongoing_requests / 2:
                    desired = cur          # not idle enough to shrink
                else:
                    desired = cur - 1      # shrink gently
        else:
            load = self.ongoing_per_replica()
            desired = cur
            if load > auto.target_ongoing_requests:
                desired += 1
            elif load < auto.target_ongoing_requests / 2:
                desired -= 1
        desired = min(max(desired, auto.min_replicas), auto.max_replicas)
        if desired != cur:
            if fleet is not None:
                fleet.note("scale", replicas_from=cur, replicas_to=desired)
                if desired < cur:
                    # planned scale-down DRAINS (ACTIVE -> DRAINING ->
                    # teardown once idle / at the deadline) instead of
                    # killing replicas with requests in flight — the
                    # r13 trace showed the kill path masking 27 resumes
                    self.drain_replicas(
                        cur - desired,
                        getattr(fleet.cfg, "drain_deadline_s", 30.0))
                    return
            self.scale_to(desired)


class ServeController:
    """(reference: serve/controller.py ServeController — deployment map +
    reconciliation; here driver-side, exposed via ray_tpu.serve.api)"""

    def __init__(self):
        from ray_tpu.serve.long_poll import LongPollHost
        self.deployments: dict[str, DeploymentState] = {}
        self.long_poll = LongPollHost()
        self._autoscale_thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self._publish_lock = threading.Lock()

    def _publish_membership(self, st: DeploymentState) -> None:
        """Config-push choke point: version the replica membership for
        in-process long-pollers AND mirror it into the core KV store so
        cross-process handles can refresh without a controller hop
        (reference: long_poll.py LongPollNamespace.REPLICA_HANDLES).

        Snapshot + publish run under one lock so concurrent scale
        operations (autoscaler vs driver) can neither tear the replica
        list nor publish out of order; an empty membership DELETES the
        KV mirror so remote handles fail fast instead of routing to
        killed actors."""
        name = st.deployment.name
        import ray_tpu
        with self._publish_lock:
            with st._lock:
                snapshot = {
                    "replicas": [r.impl for r in st.replicas if r.is_actor],
                    "max_concurrent_queries":
                        st.deployment.options.max_concurrent_queries,
                }
            self.long_poll.notify(f"replicas:{name}", snapshot)
            self.long_poll.notify("routes",
                                  sorted(self.deployments.keys()))
            if ray_tpu.is_initialized():
                import cloudpickle
                key = f"serve:replicas:{name}".encode()
                try:
                    if snapshot["replicas"]:
                        ray_tpu.get_runtime().client.kv_put(
                            key, cloudpickle.dumps(snapshot))
                    else:
                        ray_tpu.get_runtime().client.kv_del(key)
                except Exception:
                    traceback.print_exc()

    def deploy(self, deployment: Deployment,
               use_actors: Optional[bool] = None) -> DeploymentState:
        if use_actors is None:
            use_actors = deployment.options.use_actors
        if use_actors is None:
            import ray_tpu
            use_actors = ray_tpu.is_initialized()
        existing = self.deployments.get(deployment.name)
        if existing is not None:
            existing.scale_to(0)
        st = DeploymentState(deployment, use_actors,
                             on_membership_change=self._publish_membership)
        self.deployments[deployment.name] = st
        self._publish_membership(st)
        self._ensure_autoscaler()
        return st

    def delete(self, name: str) -> None:
        st = self.deployments.pop(name, None)
        if st is not None:
            st.scale_to(0)   # publishes empty membership -> kv_del
            self.long_poll.drop(f"replicas:{name}")
            self.long_poll.notify("routes",
                                  sorted(self.deployments.keys()))

    def get(self, name: str) -> DeploymentState:
        if name not in self.deployments:
            raise KeyError(f"no deployment named {name!r}")
        return self.deployments[name]

    def _ensure_autoscaler(self):
        if self._autoscale_thread is not None:
            return

        def heal(st: DeploymentState) -> None:
            try:
                st.restart_dead()
            except Exception:
                traceback.print_exc()
            finally:
                st._healing = False

        def tick():
            while not self._stop.wait(0.25):
                for st in list(self.deployments.values()):
                    try:
                        st.autoscale_tick()
                        st.drain_tick()
                        # fleet deployments self-heal: a replica whose
                        # engine died (chaos kill, crash) is replaced
                        # so routing capacity recovers without operator
                        # action.  Gated on fleet (plain actor
                        # deployments don't pay a health RPC per
                        # replica per tick) and run OFF the tick thread
                        # — a wedged actor's 30 s health timeout must
                        # not freeze autoscaling for every deployment —
                        # with at most one heal pass in flight per
                        # deployment.
                        if st.fleet is not None \
                                and not getattr(st, "_healing", False):
                            st._healing = True
                            threading.Thread(
                                target=heal, args=(st,), daemon=True,
                                name="raytpu-serve-heal").start()
                    except Exception:
                        traceback.print_exc()

        self._autoscale_thread = threading.Thread(target=tick, daemon=True)
        self._autoscale_thread.start()

    def shutdown(self):
        self._stop.set()
        for name in list(self.deployments):
            self.delete(name)
        self._autoscale_thread = None
        self._stop = threading.Event()
