"""Ingress admission control: token buckets, a bounded priority wait
queue with deadlines, and explicit load shedding.

Reference capability: graceful overload for a serving fleet — instead
of unbounded queueing (every request eventually times out, the slowest
way to say no), the ingress admits what the fleet can absorb, parks a
BOUNDED amount of burst in a priority queue, and sheds the rest with
``429 Too Many Requests`` + ``Retry-After`` so clients back off instead
of piling on.

Mechanics:

  * ``TokenBucket`` — classic leaky-bucket rate limit: ``rate``
    tokens/s refill up to ``burst``.  Lazy refill on ``take()`` (no
    refill thread).
  * ``AdmissionController.acquire(priority)`` — take a token or park in
    the wait queue.  The queue is priority-ordered (interactive ahead
    of batch regardless of arrival order) and doubly bounded: by depth
    (``max_queue_depth`` — full queue sheds immediately) and by wait
    deadline per class (a parked request sheds when its deadline
    passes, so the queue can never hide unbounded latency).
  * ``ShedError`` carries ``retry_after_s`` — the ingress maps it to a
    429 with a ``Retry-After`` header.

All waits are bounded condition waits (the control-plane lint's
blocking rules are the house style even off the node event loop).
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from dataclasses import dataclass, field

from ray_tpu.serve.qos import (PRIORITY_BATCH, PRIORITY_INTERACTIVE,
                               parse_priority)


class ShedError(RuntimeError):
    """The ingress refused this request (bucket dry + queue full, or
    the queue deadline passed).  ``retry_after_s`` is the ingress's
    estimate of when capacity frees up — the HTTP layer renders it as
    ``429`` + ``Retry-After``."""

    def __init__(self, reason: str, retry_after_s: float):
        super().__init__(
            f"request shed ({reason}); retry after "
            f"{retry_after_s:.1f}s")
        self.reason = reason
        self.retry_after_s = max(0.0, float(retry_after_s))


class TokenBucket:
    """Lazy-refill token bucket.  Not thread-safe on its own — the
    AdmissionController serializes access under its condition lock."""

    def __init__(self, rate: float, burst: float):
        if rate <= 0:
            raise ValueError(f"rate must be > 0, got {rate}")
        self.rate = float(rate)
        self.burst = max(1.0, float(burst))
        self._tokens = self.burst
        self._stamp = time.monotonic()

    def _refill(self, now: float) -> None:
        elapsed = max(0.0, now - self._stamp)   # never drain on a
        self._tokens = min(self.burst,          # backwards clock
                           self._tokens + elapsed * self.rate)
        self._stamp = now

    def take(self, now: float) -> bool:
        self._refill(now)
        if self._tokens >= 1.0 - 1e-9:      # float-robust boundary
            self._tokens = max(0.0, self._tokens - 1.0)
            return True
        return False

    def time_to_token(self, now: float, n: float = 1.0) -> float:
        """Seconds until ``n`` tokens will be available."""
        self._refill(now)
        deficit = n - self._tokens
        return max(0.0, deficit / self.rate)


@dataclass
class AdmissionStats:
    admitted: int = 0
    admitted_queued: int = 0          # admitted after waiting in queue
    shed_queue_full: int = 0
    shed_deadline: int = 0
    queue_wait_sum_s: float = 0.0
    by_class: dict = field(default_factory=dict)   # priority -> admitted


class AdmissionController:
    """Token bucket + bounded priority wait queue, one per deployment.

    ``acquire`` returns the seconds spent queued (0.0 on the fast
    path); raises ShedError on refusal.  Queue order is (priority,
    arrival) — an interactive request entering a full-but-not-shedding
    queue is served before batch requests that arrived earlier.
    """

    def __init__(self, *, rate: float, burst: float,
                 max_queue_depth: int = 64,
                 max_queue_wait_s: dict | float = 5.0):
        self._cond = threading.Condition()
        self._bucket = TokenBucket(rate, burst)
        self._depth = int(max_queue_depth)
        if not isinstance(max_queue_wait_s, dict):
            max_queue_wait_s = {PRIORITY_INTERACTIVE: max_queue_wait_s,
                                PRIORITY_BATCH: max_queue_wait_s}
        self._max_wait = dict(max_queue_wait_s)
        self._heap: list[tuple[int, int]] = []   # (priority, seq)
        self._parked: set[int] = set()            # live seqs in heap
        self._seq = itertools.count()
        self.stats = AdmissionStats()

    # ------------------------------------------------------------ internals

    def _head(self) -> int | None:
        """Seq of the live queue head (pops stale heap entries)."""
        while self._heap and self._heap[0][1] not in self._parked:
            heapq.heappop(self._heap)
        return self._heap[0][1] if self._heap else None

    def _retry_after(self, now: float) -> float:
        """Back-off estimate for a shed request: time for the bucket to
        clear everything already parked plus one."""
        return self._bucket.time_to_token(now, n=len(self._parked) + 1)

    def _admitted(self, priority: int, waited: float) -> None:
        st = self.stats
        st.admitted += 1
        if waited > 0:
            st.admitted_queued += 1
            st.queue_wait_sum_s += waited
        st.by_class[priority] = st.by_class.get(priority, 0) + 1

    # -------------------------------------------------------------- public

    def queue_depth(self) -> int:
        with self._cond:
            return len(self._parked)

    def acquire(self, priority: int = PRIORITY_BATCH, *,
                deadline_s: float | None = None) -> float:
        """Admit or shed.  Returns seconds spent queued; raises
        ShedError when refused."""
        t0 = time.monotonic()
        limit = (deadline_s if deadline_s is not None
                 else self._max_wait.get(priority, 5.0))
        deadline = t0 + max(0.0, float(limit))
        with self._cond:
            # fast path: nobody parked ahead and a token is ready
            if not self._parked and self._bucket.take(t0):
                self._admitted(priority, 0.0)
                return 0.0
            if len(self._parked) >= self._depth:
                self.stats.shed_queue_full += 1
                raise ShedError("queue full", self._retry_after(t0))
            seq = next(self._seq)
            heapq.heappush(self._heap, (priority, seq))
            self._parked.add(seq)
            try:
                while True:
                    now = time.monotonic()
                    if self._head() == seq and self._bucket.take(now):
                        self._parked.discard(seq)
                        self._cond.notify_all()
                        waited = now - t0
                        self._admitted(priority, waited)
                        return waited
                    if now >= deadline:
                        self.stats.shed_deadline += 1
                        raise ShedError("queue deadline",
                                        self._retry_after(now))
                    # bounded park: head waits for its token, others
                    # wait for a notify (with a poll floor so a missed
                    # notify can't strand anyone)
                    wait = min(0.05, deadline - now)
                    if self._head() == seq:
                        wait = min(max(self._bucket.time_to_token(now),
                                       0.001), wait)
                    self._cond.wait(wait)
            finally:
                self._parked.discard(seq)
                self._cond.notify_all()
