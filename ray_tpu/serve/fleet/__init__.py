"""ray_tpu.serve.fleet: the production ingress-and-fleet layer on top
of Serve + the continuous-batching inference engine.

Three pieces, composable per deployment (ROADMAP items 1d and 5):

  * admission.py — token-bucket admission, bounded priority wait queue
    with deadlines, explicit load shedding (429 + Retry-After).
  * router.py    — occupancy-aware replica routing: power-of-two-
    choices on the per-engine gauges (active slots + queue depth), the
    real signal the round-robin router can't see.
  * multiplex.py — N model variants behind one deployment, LRU-loaded
    per replica; routing prefers replicas already holding the variant.
  * ingress.py   — the ``Fleet`` composition: admit → route → call,
    resume-on-replica-death for streams, ingress event trail for the
    merged timeline, occupancy signal for the autoscaler.

Quick start::

    from ray_tpu import serve
    from ray_tpu.serve import fleet
    from ray_tpu.inference import build_gpt_deployment

    dep = build_gpt_deployment(
        num_replicas=2,
        autoscaling=serve.AutoscalingConfig(min_replicas=1,
                                            max_replicas=4,
                                            target_ongoing_requests=12))
    serve.run(dep, use_actors=False, http=True)
    fleet.enable("v1", fleet.FleetConfig(rate=200, burst=64))
    # POST /v1/generate now goes admission -> occupancy router -> engine
"""

from __future__ import annotations

from typing import Optional, Union

from ray_tpu.serve.fleet.admission import (AdmissionController, ShedError,
                                           TokenBucket, parse_priority)
from ray_tpu.serve.fleet.ingress import Fleet, FleetConfig
from ray_tpu.serve.fleet.multiplex import ModelMultiplexer, UnknownModelError
from ray_tpu.serve.fleet.router import NoReplicaError, OccupancyRouter


def enable(deployment: Union[str, object],
           config: Optional[FleetConfig] = None) -> Fleet:
    """Install the fleet layer on a deployment (by name or
    DeploymentState).  Handle + HTTP traffic immediately starts flowing
    through admission + the occupancy router, and ``autoscale_tick``
    switches to the fleet's engine-load signal."""
    state = deployment
    if isinstance(deployment, str):
        from ray_tpu import serve as _serve
        state = _serve._get_controller().get(deployment)
    f = Fleet(state, config)
    state.fleet = f
    return f


def disable(deployment: Union[str, object]) -> None:
    """Remove the fleet layer (traffic reverts to round-robin)."""
    state = deployment
    if isinstance(deployment, str):
        from ray_tpu import serve as _serve
        state = _serve._get_controller().get(deployment)
    state.fleet = None


def get(deployment_name: str) -> Optional[Fleet]:
    from ray_tpu import serve as _serve
    return getattr(_serve._get_controller().get(deployment_name),
                   "fleet", None)


def join_worker_threads(cancel_pending: bool = True) -> None:
    """Deterministically retire the fleet ingress worker pool: swap the
    shared ThreadPoolExecutor out under its lock, then JOIN every
    worker thread.

    A parked worker keeps its last request's frame (replica + engine
    locals) alive until the interpreter recycles the thread, so
    GC-window assertions — block-leak audits, weakref liveness checks —
    race it roughly 1 run in 4; no sleep length fixes that, only a
    join does.  Safe to call any time: in-flight requests finish first
    (``wait=True``), queued-but-unstarted ones are cancelled when
    ``cancel_pending``, and the pool is re-created lazily by the next
    request.  ``serve.shutdown()`` calls this automatically."""
    from ray_tpu.serve.fleet.ingress import _FleetResponse
    with _FleetResponse._pool_lock:
        pool, _FleetResponse._pool = _FleetResponse._pool, None
    if pool is not None:
        pool.shutdown(wait=True, cancel_futures=cancel_pending)


def metrics_snapshot() -> list:
    """Fleet ingress gauges/counters in the exporter's tuple format,
    one labeled series per fleet-enabled deployment."""
    from ray_tpu import serve as _serve
    ctrl = _serve._controller
    if ctrl is None:
        return []
    admitted, shed, queued, replicas, slots = {}, {}, {}, {}, {}
    resumed_fail, resumed_scale, drained, drain_to = {}, {}, {}, {}
    blocks, butil, phit, saccept = {}, {}, {}, {}
    meshdev, tpsh = {}, {}
    prem_hit, prem_fail, prem_fallback = {}, {}, {}
    for name, st in list(ctrl.deployments.items()):
        f = getattr(st, "fleet", None)
        if f is None:
            continue
        key = (("deployment", name),)
        snap = f.fleet_snapshot()
        admitted[key] = float(snap["admitted"])
        shed[key] = float(snap["shed"])
        resumed_fail[key] = float(snap["resumed_failure"])
        resumed_scale[key] = float(snap["resumed_scale_down"])
        drained[key] = float(snap["drained"])
        drain_to[key] = float(snap["drain_timeout"])
        queued[key] = float(snap["ingress_queued"])
        replicas[key] = float(snap["replicas"])
        slots[key] = float(snap["total_slots"])
        blocks[key] = float(snap.get("total_blocks", 0))
        butil[key] = float(snap.get("block_utilization", 0.0))
        phit[key] = float(snap.get("prefix_hit_rate", 0.0))
        saccept[key] = float(snap.get("spec_accept_rate", 0.0))
        meshdev[key] = float(snap.get("mesh_devices", 1))
        tpsh[key] = float(snap.get("tp_shards", 1))
        # cluster prefix plane counters: keys exist only when the
        # deployment's FleetConfig enabled cluster_prefix (OFF keeps
        # the snapshot — and therefore this exporter — byte-identical)
        if "prefix_remote_hits" in snap:
            prem_hit[key] = float(snap["prefix_remote_hits"])
            prem_fail[key] = float(snap["prefix_remote_fetch_failures"])
            prem_fallback[key] = float(snap["prefix_fallback_recomputes"])
    if not admitted:
        return []
    return [
        ("serve_fleet_admitted_total", "counter",
         "Requests admitted through the fleet ingress", admitted),
        ("serve_fleet_shed_total", "counter",
         "Requests shed (429) at the fleet ingress", shed),
        ("serve_fleet_resumed_failure_total", "counter",
         "Requests re-routed after a replica CRASH", resumed_fail),
        ("serve_fleet_resumed_scale_down_total", "counter",
         "Requests re-routed off a planned replica removal",
         resumed_scale),
        ("serve_fleet_drained_total", "counter",
         "Replicas retired empty via graceful drain", drained),
        ("serve_fleet_drain_timeout_total", "counter",
         "Drains that hit the deadline and fell back to kill+resume",
         drain_to),
        ("serve_fleet_ingress_queue_depth", "gauge",
         "Requests parked in the admission queue", queued),
        ("serve_fleet_replicas", "gauge",
         "Live replicas behind the fleet router", replicas),
        ("serve_fleet_total_slots", "gauge",
         "Total decode slots across live replicas", slots),
        ("serve_fleet_total_blocks", "gauge",
         "Total paged-KV blocks across live replicas (0 = slot pools); "
         "global admission budgets, never per-tp-shard counts — block "
         "counts replicate across shards, heads are what's split",
         blocks),
        ("serve_fleet_block_utilization", "gauge",
         "Fleet-wide paged-KV blocks in use / usable", butil),
        ("serve_fleet_prefix_hit_rate", "gauge",
         "Fleet-wide prompt tokens served from the radix prefix cache",
         phit),
        ("serve_fleet_spec_accept_rate", "gauge",
         "Fleet-wide speculative-draft acceptance (0 = not speculating)",
         saccept),
        ("serve_fleet_mesh_devices", "gauge",
         "Widest engine mesh across live replicas (1 = unmeshed)",
         meshdev),
        ("serve_fleet_tp_shards", "gauge",
         "Widest tensor-parallel shard count across live replicas",
         tpsh),
    ] + ([
        ("serve_fleet_prefix_remote_hits_total", "counter",
         "Prefixes adopted from a remote holder via the cluster "
         "prefix directory", prem_hit),
        ("serve_fleet_prefix_remote_fetch_failures_total", "counter",
         "Remote prefix fetches that failed (holder died/drained, "
         "stale generation, install pressure)", prem_fail),
        ("serve_fleet_prefix_fallback_recomputes_total", "counter",
         "Requests that fell back to local chunked-prefill recompute "
         "after a failed adoption", prem_fallback),
    ] if prem_hit else [])


__all__ = [
    "AdmissionController", "Fleet", "FleetConfig", "ModelMultiplexer",
    "NoReplicaError", "OccupancyRouter", "ShedError", "TokenBucket",
    "UnknownModelError", "enable", "disable", "get",
    "join_worker_threads", "metrics_snapshot", "parse_priority",
]
