"""Occupancy-aware replica routing: power-of-two-choices on the
per-engine gauges.

Reference capability: replica-aware routing for model serving — the
round-robin router (serve/controller.py ``assign_replica``) spreads
REQUEST COUNTS evenly, but continuous-batching replicas are not equal:
one may have a deep admission queue while another sits half-empty, and
streaming responses release the router-side ``ongoing`` count long
before the engine slot frees.  This router scores replicas by what the
engine actually reports — ``active_slots + waiting_requests`` over
``max_slots`` (the same gauges PR 5 exports at /metrics) — and picks
the less-loaded of two random choices (power-of-two-choices: near-
optimal balance at O(1) probes, no global scan race).

Model multiplexing hooks in at candidate selection: when the request
names a model variant, replicas already holding it are preferred (no
load penalty), falling back to the full live set (the chosen replica
then LRU-loads the variant).

Probes are method calls for in-process replicas and RPCs (TTL-cached)
for actor replicas; a probe that fails or reports ``stopped`` marks
the replica dead — it is skipped until the controller's self-heal tick
replaces it.
"""

from __future__ import annotations

import random
import threading
import time
from typing import Any, Optional

from ray_tpu.serve.controller import DeploymentState, ReplicaHandle


class NoReplicaError(RuntimeError):
    """No live replica could take the request within the timeout."""


def _probe_inproc(replica: ReplicaHandle) -> Optional[dict]:
    """Stats from an in-process replica body; None when the body has no
    fleet surface (plain deployments fall back to ongoing counts)."""
    user = getattr(replica.impl, "_user", None)
    probe = getattr(user, "fleet_stats", None)
    if not callable(probe):
        return None
    return probe()


class OccupancyRouter:
    """Power-of-two-choices router over one deployment's replicas."""

    PROBE_TTL_S = 0.25       # actor-replica stats cache (in-proc: fresh)
    # dead-marks EXPIRE: a mark is a short-circuit around probing a
    # corpse, not a tombstone.  A replica that died for one request
    # (e.g. a multiplex LRU eviction failed its in-flight stream) but
    # is otherwise healthy must come back; a genuinely dead one
    # re-marks itself on the next probe.  The controller's self-heal
    # tick replaces real corpses well within one TTL.
    DEAD_TTL_S = 5.0

    # in-proc probes are near-free but not free (every engine's stat
    # locks); a short cache bounds probe traffic per replica regardless
    # of QPS — p2c tolerates 50 ms-stale scores
    INPROC_TTL_S = 0.05

    def __init__(self, state: DeploymentState, *, seed: int = 0):
        self._state = state
        self._rng = random.Random(seed)
        # guards _dead + _probe_cache: both are written from every
        # fleet-pool thread (mark_dead on call failure, cache fills),
        # and the pruning pass rebuilds them wholesale — an unlocked
        # rebuild could silently drop a concurrent dead-mark
        self._mlock = threading.Lock()
        self._probe_cache: dict[str, tuple[float, Optional[dict]]] = {}
        self._dead: dict[str, float] = {}     # tag -> mark time

    # ------------------------------------------------------------- probing

    def probe(self, replica: ReplicaHandle) -> Optional[dict]:
        """Engine-load stats for one replica (None = no fleet surface).
        Raises on a dead replica probe (actor gone)."""
        now = time.monotonic()
        ttl = (self.INPROC_TTL_S if not replica.is_actor
               else self.PROBE_TTL_S)
        with self._mlock:
            hit = self._probe_cache.get(replica.tag)
        if hit is not None and now - hit[0] < ttl:
            return hit[1]
        if not replica.is_actor:
            st = _probe_inproc(replica)
        else:
            import ray_tpu
            try:
                st = ray_tpu.get(
                    replica.impl.handle_request.remote("fleet_stats",
                                                       (), {}),
                    timeout=5)
            except Exception:
                st = {"stopped": True}
        with self._mlock:
            self._probe_cache[replica.tag] = (now, st)
        return st

    def _score(self, replica: ReplicaHandle,
               maxq: int) -> Optional[tuple]:
        """(load, waiting, jitter) — lower routes first; None = not a
        candidate (dead or saturated)."""
        if replica.lifecycle != "active":
            # lifecycle outranks probe health: a DRAINING replica is
            # alive (it still finishes in-flight streams) but must not
            # take new work — and it is NOT dead-marked, because a
            # dead-mark expires (DEAD_TTL_S) and expiry must never
            # resurrect a deliberate drain
            return None
        try:
            st = self.probe(replica)
        except Exception:
            st = {"stopped": True}
        if st is not None and st.get("stopped"):
            with self._mlock:
                self._dead[replica.tag] = time.monotonic()
            return None
        if st is not None and st.get("draining"):
            # the body began draining before the controller's membership
            # move landed: skip as a candidate without dead-marking
            return None
        if replica.ongoing >= maxq:
            return None
        if st is None:   # plain deployment: router-side count is all we have
            return (replica.ongoing / max(1, maxq), 0,
                    self._rng.random())
        slots = max(1, int(st.get("max_slots", 1)))
        load = (float(st.get("active_slots", 0))
                + float(st.get("waiting_requests", 0))) / slots
        # paged engines also report BLOCK pressure: a replica with free
        # decode rows but a nearly-full pool will queue/preempt, so the
        # binding constraint (rows or blocks) is the real load signal
        blocks = float(st.get("blocks_total", 0))
        if blocks:
            load = max(load,
                       (blocks - float(st.get("blocks_free", 0))) / blocks)
        return (load, int(st.get("waiting_requests", 0)),
                self._rng.random())

    # ------------------------------------------------------------- routing

    def live_replicas(self) -> list[ReplicaHandle]:
        with self._state._lock:
            # DRAINING replicas live in state.draining, not here — but
            # filter on lifecycle anyway so any transitional window
            # (drain marked, membership move racing) stays unroutable
            reps = [r for r in self._state.replicas
                    if r.lifecycle == "active"]
        # dead-marks and probe-cache entries for replicas no longer in
        # the membership are stale (controller replaced them — tags are
        # never reused), and surviving marks expire after DEAD_TTL_S —
        # prune both so they stay bounded over weeks of churn
        tags = {r.tag for r in reps}
        now = time.monotonic()
        with self._mlock:
            for t in [t for t, s in self._dead.items()
                      if t not in tags or now - s >= self.DEAD_TTL_S]:
                del self._dead[t]
            for t in [t for t in self._probe_cache if t not in tags]:
                del self._probe_cache[t]
            dead = set(self._dead)
        live = [r for r in reps if r.tag not in dead]
        if not live and reps:
            # every known replica was marked dead — retry them rather
            # than refusing forever (a stale dead-mark must not wedge
            # routing when the body healed in place)
            with self._mlock:
                self._dead.clear()
            live = reps
        return live

    def holders(self, replicas: list[ReplicaHandle],
                model: str) -> list[ReplicaHandle]:
        """Replicas whose body already has ``model`` loaded."""
        out = []
        for r in replicas:
            try:
                st = self.probe(r)
            except Exception:
                continue
            if st is not None and model in (st.get("models") or ()):
                out.append(r)
        return out

    def assign(self, model: Optional[str] = None, *,
               timeout: float = 30.0,
               exclude: tuple = (),
               prefer: Optional[str] = None) -> ReplicaHandle:
        """Pick a replica (p2c on occupancy), increment its ongoing
        count.  ``exclude`` skips tags (retry-after-failure path).

        ``prefer`` is the prefix-affinity hint (a directory-confirmed
        prefix HOLDER's tag): when that replica is live, active and
        unsaturated it wins outright — serving there reuses cached KV
        with no transfer at all.  The preference is judged by
        ``_score``, so a DRAINING holder is skipped IMMEDIATELY via its
        lifecycle/probe (never dead-marked — a mark's DEAD_TTL_S expiry
        must not resurrect a deliberate drain), and a saturated or dead
        holder falls through to the normal occupancy pick."""
        maxq = self._state.deployment.options.max_concurrent_queries
        deadline = time.monotonic() + timeout
        first_pass = True
        while True:
            live = [r for r in self.live_replicas()
                    if r.tag not in exclude]
            if prefer is not None and first_pass:
                # honored once: if the holder cannot take the request
                # NOW, balance beats affinity (the adoption path will
                # warm whoever the p2c pick lands on)
                first_pass = False
                held = [r for r in live if r.tag == prefer]
                if held and self._score(held[0], maxq) is not None:
                    with self._state._lock:
                        held[0].ongoing += 1
                    return held[0]
            cands = live
            if model is not None and live:
                held = self.holders(live, model)
                if held:
                    cands = held
            pick = self._pick(cands, maxq)
            if pick is not None:
                with self._state._lock:
                    pick.ongoing += 1
                return pick
            if time.monotonic() > deadline:
                raise NoReplicaError(
                    f"deployment {self._state.deployment.name!r}: no "
                    f"live replica available within {timeout}s "
                    f"({len(live)} live, exclude={list(exclude)})")
            # saturated: park briefly rather than hammering the engine
            # stat locks 200x/s per waiting thread
            time.sleep(0.02)

    def _pick(self, cands: list[ReplicaHandle],
              maxq: int) -> Optional[ReplicaHandle]:
        """Sample TWO candidates, then probe only those (the p2c
        contract: O(1) probes per pick); fall back to a full scan only
        when both sampled replicas are dead or saturated."""
        if len(cands) > 2:
            pick = self._pick_scored(self._rng.sample(cands, 2), maxq)
            if pick is not None:
                return pick
        return self._pick_scored(cands, maxq)

    def _pick_scored(self, cands: list[ReplicaHandle],
                     maxq: int) -> Optional[ReplicaHandle]:
        scored = [(s, r) for r in cands
                  if (s := self._score(r, maxq)) is not None]
        if not scored:
            return None
        return min(scored, key=lambda t: t[0])[1]

    def release(self, replica: ReplicaHandle) -> None:
        with self._state._lock:
            replica.ongoing = max(0, replica.ongoing - 1)

    def mark_dead(self, replica: ReplicaHandle) -> None:
        """Route-time death report (call failed with a dead-replica
        error): skip this replica until the controller replaces it or
        the mark expires (DEAD_TTL_S — one failed request must not
        permanently exclude an otherwise-healthy replica)."""
        with self._mlock:
            self._dead[replica.tag] = time.monotonic()
