"""Model multiplexing: N model variants behind one deployment,
LRU-loaded per replica.

Reference capability: multiplexed model serving (the PAPER.md L7 Serve
survey) — a deployment fronts a CATALOG of model variants, each replica
holds at most ``capacity`` of them resident (an inference engine +
KV pool each), and a request names its variant.  The fleet router
prefers replicas that already hold the variant (no load latency, warm
cache); a miss LRU-loads on the routed replica, evicting the
least-recently-used variant when at capacity (its engine shuts down,
releasing the pool).

The multiplexer is generic over a ``loader(model_id) -> body`` /
``unloader(body)`` pair so non-LLM deployments can multiplex too; the
inference layer wires it to per-variant InferenceEngines
(``serving.GPTServer`` with ``variants=...``).
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Callable, Optional

from ray_tpu.serve.qos import ReplicaDeadError


class UnknownModelError(ValueError):
    """Request named a variant that is not in the deployment catalog."""


class ModelMultiplexer:
    """Per-replica LRU of loaded model variants.

    ``get(model_id)`` returns the loaded body, loading/evicting as
    needed.  The LOAD itself runs OUTSIDE the lock behind a per-model
    future: concurrent misses for the same variant share one load (two
    engines for one variant would double the pool), while hits,
    ``loaded_models()``/``loaded_bodies()`` (the router's probe
    surface) and health checks never block behind a multi-second model
    load — a load stalls only requests that need the loading variant.
    """

    # bound on a follower waiting for another request's in-flight load
    # (params init + compile is seconds; a wedged loader must fail
    # followers cleanly, not strand pool threads)
    LOAD_TIMEOUT_S = 120.0

    def __init__(self, catalog: dict,
                 loader: Callable[[str, Any], Any],
                 unloader: Optional[Callable[[Any], None]] = None,
                 capacity: int = 2):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if not catalog:
            raise ValueError("empty model catalog")
        self.catalog = dict(catalog)       # model_id -> loader spec
        self.capacity = int(capacity)
        self._loader = loader
        self._unloader = unloader
        self._lock = threading.Lock()
        self._loaded: "OrderedDict[str, Any]" = OrderedDict()
        self._loading: dict = {}           # model_id -> Future
        self._down = False
        self.loads = 0
        self.evictions = 0

    def default_model(self) -> str:
        return next(iter(self.catalog))

    def loaded_models(self) -> list[str]:
        with self._lock:
            return list(self._loaded)

    def loaded_bodies(self) -> list:
        with self._lock:
            return list(self._loaded.values())

    def get(self, model_id: Optional[str]) -> Any:
        """Resident body for ``model_id`` (None = catalog default),
        loading/evicting as needed."""
        from concurrent.futures import Future
        if model_id is None:
            model_id = self.default_model()
        if model_id not in self.catalog:
            raise UnknownModelError(
                f"unknown model {model_id!r} (catalog: "
                f"{sorted(self.catalog)})")
        with self._lock:
            if self._down:
                raise ReplicaDeadError("multiplexer is shut down")
            body = self._loaded.get(model_id)
            if body is not None:
                self._loaded.move_to_end(model_id)
                return body
            fut = self._loading.get(model_id)
            if fut is not None:
                leader = False
            else:
                fut = self._loading[model_id] = Future()
                leader = True
        if not leader:
            # share the in-flight load — BOUNDED (house style: no
            # unbounded waits): a wedged loader fails followers with a
            # clean timeout instead of leaking pool threads forever
            return fut.result(timeout=self.LOAD_TIMEOUT_S)
        try:
            body = self._loader(model_id, self.catalog[model_id])
        except BaseException as e:
            with self._lock:
                self._loading.pop(model_id, None)
            fut.set_exception(e)
            raise
        evicted = None
        unload_now = False
        with self._lock:
            self._loading.pop(model_id, None)
            if self._down:             # lost the race with unload_all
                unload_now = True
            else:
                if len(self._loaded) >= self.capacity:
                    _, evicted = self._loaded.popitem(last=False)
                    self.evictions += 1
                self._loaded[model_id] = body
                self.loads += 1
        if unload_now:
            if self._unloader is not None:
                self._unloader(body)
            err = ReplicaDeadError("multiplexer is shut down")
            fut.set_exception(err)
            raise err
        fut.set_result(body)
        if evicted is not None and self._unloader is not None:
            self._unloader(evicted)    # outside the lock: may be slow
        return body

    def unload_all(self) -> None:
        with self._lock:
            self._down = True
            bodies = list(self._loaded.values())
            self._loaded.clear()
        if self._unloader is not None:
            for b in bodies:
                self._unloader(b)

    def stats(self) -> dict:
        with self._lock:
            return {
                "catalog": sorted(self.catalog),
                "loaded": list(self._loaded),
                "loading": list(self._loading),
                "capacity": self.capacity,
                "loads": self.loads,
                "evictions": self.evictions,
            }
