"""The fleet layer: admission → occupancy routing → replica call, with
resume-on-replica-death for streams and a full ingress event trail.

``Fleet`` is installed on a DeploymentState by ``serve.fleet.enable``;
``DeploymentHandle.remote`` detects it and routes ``__call__`` traffic
through here instead of the round-robin ``assign_replica`` path.  One
request's life:

  1. **admit** — ``AdmissionController.acquire`` (token bucket +
     bounded priority queue).  Refusal raises ``ShedError``; the HTTP
     ingress maps it to ``429`` + ``Retry-After``.  Every admitted or
     shed request is counted — nothing exits this layer unaccounted.
  2. **route** — ``OccupancyRouter.assign``: power-of-two-choices on
     the engine gauges, preferring replicas that already hold the
     requested model variant.
  3. **call** — in-process bodies run on the calling thread (the
     proxy's executor); actor replicas go through the core runtime.
  4. **resume** — a replica that dies mid-request (typed
     ``EngineStoppedError``) is marked dead and the request is retried
     on another replica.  Streams resume EXACTLY: generation is
     deterministic from the request (same params/seed on every
     replica), so the retry replays and the wrapper skips the
     already-delivered prefix by token index.  A request that cannot be
     placed fails promptly with a clean error — never a silent hang.

Chaos/observability hooks follow the house gate discipline: when the
fault plane / flight recorder is disarmed each hook site costs one
module-global load + ``is None`` branch (enforced by ``ray_tpu lint``
via analysis/hotpath_registry.py).
"""

from __future__ import annotations

import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Iterator, Optional

from ray_tpu.core import fault_injection as _fi
from ray_tpu.core import flight_recorder as _fr
from ray_tpu.serve.fleet.admission import (AdmissionController, ShedError,
                                           parse_priority)
from ray_tpu.serve.fleet.router import NoReplicaError, OccupancyRouter
from ray_tpu.serve.qos import (PRIORITY_BATCH, EngineDrainingError,
                               ReplicaDeadError)


def _is_replica_death(e: BaseException, replica) -> bool:
    """Classify an exception as this-replica-died (retriable: the
    request had no observable side effects).  In-process engines raise
    the typed ReplicaDeadError subclass; a killed ACTOR replica's
    pending calls fail with the core runtime's actor-death errors
    instead, which carry no shared base class."""
    if isinstance(e, ReplicaDeadError):
        return True
    if replica is not None and replica.is_actor:
        try:
            from ray_tpu.core.client import ActorDiedError
        except ImportError:                      # pragma: no cover
            ActorDiedError = ()
        if isinstance(e, ActorDiedError):
            return True
        return isinstance(e, RuntimeError) and "Actor died" in str(e)
    return False


def _resume_kind(e: BaseException, replica) -> str:
    """Classify a replica-death re-route: planned removal (drain race /
    drain-timeout kill / explicit scale_to kill — the replica's
    lifecycle already left "active", or the typed draining error) vs a
    genuine failure.  Splitting the counter is what makes the r13
    masking bug impossible to reintroduce silently: a scale-down that
    eats resumes now shows up under ``resumed_scale_down``, and
    ``resumed_failure`` staying 0 without chaos is an assertable
    invariant."""
    if isinstance(e, EngineDrainingError):
        return "resumed_scale_down"
    if replica is not None \
            and getattr(replica, "lifecycle", "active") != "active":
        return "resumed_scale_down"
    return "resumed_failure"


@dataclass
class FleetConfig:
    """Ingress knobs for one deployment's fleet layer."""
    rate: float = 200.0                  # admission tokens/s
    burst: float = 64.0                  # bucket depth (absorbed burst)
    max_queue_depth: int = 64            # parked requests before shedding
    max_queue_wait_s: Any = None         # float or {priority: seconds}
    interactive_wait_s: float = 2.0      # used when max_queue_wait_s is None
    batch_wait_s: float = 10.0
    retry_on_replica_failure: bool = True
    max_resume_attempts: int = 2         # re-routes after a replica death
    drain_deadline_s: float = 30.0       # DRAINING -> forced kill+resume
    seed: int = 0                        # router's p2c rng
    keep_events: int = 8192
    # cluster prefix plane (serve/fleet/prefix_directory.py): directory
    # + prefix-affinity routing + replica→replica block adoption.  OFF
    # by default — with it off, the fleet path is byte-identical to the
    # plane not existing (every hook gates on ``fleet.prefix is None``)
    cluster_prefix: bool = False
    prefix_directory_capacity: int = 4096
    prefix_fetch_timeout_s: float = 5.0  # extract/install per-hop cap


@dataclass
class FleetCounters:
    """Request accounting.  Identity (asserted in tests): every admitted
    request ends in exactly one of completed/errored/cancelled, and
    every replica-death re-route is classified — there is deliberately
    NO aggregate ``resumed`` field, so a new death path MUST pick a
    class (``fleet_snapshot`` derives the sum for compatibility)."""
    admitted: int = 0
    shed: int = 0
    rejected: int = 0                    # malformed envelope (client bug)
    completed: int = 0
    errored: int = 0
    cancelled: int = 0                   # consumer abandoned the stream
    resumed_failure: int = 0             # re-route after a CRASH
    resumed_scale_down: int = 0          # re-route off a planned removal
    drained: int = 0                     # replicas retired empty
    drain_timeout: int = 0               # drains that fell back to kill
    replayed_tokens: int = 0             # resume-replay cost (skipped)


class Fleet:
    """Per-deployment fleet layer (admission + router + event trail)."""

    def __init__(self, state, config: Optional[FleetConfig] = None):
        self.state = state
        self.cfg = config or FleetConfig()
        self.name = state.deployment.name
        waits = self.cfg.max_queue_wait_s
        if waits is None:
            from ray_tpu.inference.engine import PRIORITY_INTERACTIVE
            waits = {PRIORITY_INTERACTIVE: self.cfg.interactive_wait_s,
                     PRIORITY_BATCH: self.cfg.batch_wait_s}
        self.admission = AdmissionController(
            rate=self.cfg.rate, burst=self.cfg.burst,
            max_queue_depth=self.cfg.max_queue_depth,
            max_queue_wait_s=waits)
        self.router = OccupancyRouter(state, seed=self.cfg.seed)
        self.counters = FleetCounters()
        self._clock = threading.Lock()
        self._events: deque = deque(maxlen=self.cfg.keep_events)
        self.prefix = None
        if self.cfg.cluster_prefix:
            from ray_tpu.serve.fleet.prefix_directory import PrefixPlane
            self.prefix = PrefixPlane(
                self, capacity=self.cfg.prefix_directory_capacity,
                fetch_timeout_s=self.cfg.prefix_fetch_timeout_s)

    # ----------------------------------------------------------- event trail

    def note(self, kind: str, **fields) -> None:
        """Ingress event: local bounded ring always; a timestamped copy
        into the flight recorder when one is armed so `ray_tpu
        timeline` shows admission/shed/route next to task stages."""
        ev = {"t": time.time(), "kind": kind, "deployment": self.name}
        ev.update(fields)
        self._events.append(ev)
        rec = _fr._active
        if rec is None:
            return
        rec.note_ingress(ev)

    def _chaos(self, point: str, **ctx) -> None:
        """Fault-plane hook (serve_route / serve_stream): zero-overhead
        gate when no plan is installed."""
        fi = _fi._active
        if fi is None:
            return
        ctx["fleet"] = self
        fi.on_serve(point, ctx)

    def events(self) -> list[dict]:
        return list(self._events)

    def dump_events(self, path: str) -> str:
        import json
        with open(path, "w") as f:
            json.dump(self.events(), f)
        return path

    def _count(self, field_name: str, n: int = 1) -> None:
        with self._clock:
            setattr(self.counters, field_name,
                    getattr(self.counters, field_name) + n)

    # ------------------------------------------------------------- signals

    def total_load(self) -> float:
        """Deployment-wide demand for the autoscaler: engine-held slots
        + engine queues + requests parked at the ingress."""
        total = float(self.admission.queue_depth())
        for r in self.router.live_replicas():
            try:
                st = self.router.probe(r)
            except Exception:
                continue
            if st is None:
                total += r.ongoing
            elif not st.get("stopped"):
                total += (float(st.get("active_slots", 0))
                          + float(st.get("waiting_requests", 0)))
        return total

    def fleet_snapshot(self) -> dict:
        """Point-in-time fleet state (the trace-replay sampler's row)."""
        reps = self.router.live_replicas()
        slots = active = waiting = 0
        blocks_total = blocks_free = hit_toks = lookup_toks = 0
        drafted = accepted = 0
        mesh_devices = tp_shards = 1
        for r in reps:
            try:
                st = self.router.probe(r)
            except Exception:
                continue
            if st and not st.get("stopped"):
                slots += int(st.get("max_slots", 0))
                active += int(st.get("active_slots", 0))
                waiting += int(st.get("waiting_requests", 0))
                # engine blocks_total is the GLOBAL admission budget
                # (block counts replicate across tp shards; heads are
                # what's split) — summing replicas needs no per-shard
                # correction, and total_blocks never silently reports
                # per-shard numbers
                blocks_total += int(st.get("blocks_total", 0))
                blocks_free += int(st.get("blocks_free", 0))
                hit_toks += int(st.get("prefix_hit_tokens", 0))
                lookup_toks += int(st.get("prefix_lookup_tokens", 0))
                drafted += int(st.get("spec_drafted_tokens", 0))
                accepted += int(st.get("spec_accepted_tokens", 0))
                mesh_devices = max(mesh_devices,
                                   int(st.get("mesh_devices", 1)))
                tp_shards = max(tp_shards, int(st.get("tp_shards", 1)))
        with self._clock:
            counters = dict(self.counters.__dict__)
        # compatibility aggregate (the split fields are authoritative)
        counters["resumed"] = (counters["resumed_failure"]
                               + counters["resumed_scale_down"])
        if self.prefix is not None:
            # cluster prefix plane: remote hits / fetch failures /
            # fallback recomputes + live directory size (all zero-less
            # ABSENT when the plane is off, so plane-less snapshots
            # stay byte-identical to previous rounds)
            counters.update(self.prefix.counters())
        return {
            "replicas": len(reps),
            "total_slots": slots,
            "active_slots": active,
            "engine_waiting": waiting,
            "ingress_queued": self.admission.queue_depth(),
            "occupancy": (active / slots) if slots else 0.0,
            # paged-cache capacity across the fleet (0s when replicas
            # run the legacy slot pool): the REAL memory signal behind
            # the row counts, exported at /metrics for the autoscaler's
            # operators and dashboards
            "total_blocks": blocks_total,
            "block_utilization": ((blocks_total - blocks_free)
                                  / blocks_total if blocks_total else 0.0),
            # serving geometry (1/1 = unmeshed): max across replicas —
            # a mixed rollout shows its widest mesh, not a bogus sum
            "mesh_devices": mesh_devices,
            "tp_shards": tp_shards,
            "prefix_hit_rate": (hit_toks / lookup_toks
                                if lookup_toks else 0.0),
            # speculative decoding across the fleet (0.0 when no replica
            # speculates — plain arms report nothing, not a fake zero%)
            "spec_drafted_tokens": drafted,
            "spec_accepted_tokens": accepted,
            "spec_accept_rate": (accepted / drafted) if drafted else 0.0,
            **counters,
        }

    # ------------------------------------------------------------- serving

    def remote(self, args: tuple, kwargs: dict) -> "_FleetResponse":
        """Admission happens HERE (synchronously — backpressure is the
        point); routing/calling happen in ``result()``."""
        req = args[0] if args and isinstance(args[0], dict) else None
        priority = PRIORITY_BATCH
        model = None
        if req is not None:
            try:
                priority = parse_priority(req.get("priority"))
            except ValueError:
                # malformed envelope: a CLIENT error, accounted (the
                # complete-accounting invariant covers every request:
                # offered == admitted + shed + rejected)
                self._count("rejected")
                self.note("rejected", reason="bad priority",
                          value=repr(req.get("priority")))
                raise
            model = req.get("model")
        try:
            waited = self.admission.acquire(priority)
        except ShedError as e:
            self._count("shed")
            self.note("shed", reason=e.reason,
                      retry_after_s=round(e.retry_after_s, 3),
                      priority=priority)
            raise
        self._count("admitted")
        self.note("admit", queued_s=round(waited, 6), priority=priority,
                  model=model)
        return _FleetResponse(self, args, kwargs, model, priority)

    def _call(self, replica, args: tuple, kwargs: dict,
              timeout: Optional[float] = None):
        if self.prefix is not None:
            # cluster prefix adoption runs before EVERY replica call
            # (first route and resume re-routes alike): if the
            # directory knows a peer holding this prompt's prefix,
            # fetch + install it here so the engine's admission match
            # adopts it.  before_call NEVER raises — any failure is a
            # counted, silent downgrade to local recompute
            self.prefix.before_call(replica, args)
        if replica.is_actor:
            import ray_tpu
            ref = replica.impl.handle_request.remote("__call__", args,
                                                     kwargs)
            return ray_tpu.get(ref, timeout=timeout)
        return replica.impl.handle_request("__call__", args, kwargs)

    # --------------------------------------------------------------- chaos

    def kill_replica(self, replica) -> None:
        """Chaos helper: kill a replica's body in place (engines shut
        down, pending requests fail with EngineStoppedError) WITHOUT
        removing it from the membership — exactly what a crash looks
        like to the router.  The controller's self-heal tick replaces
        it."""
        self.note("chaos_kill", replica=replica.tag)
        if self.prefix is not None:
            self.prefix.invalidate_holder(replica.tag)
        try:
            if replica.is_actor:
                import ray_tpu
                ray_tpu.kill(replica.impl)
            else:
                replica.impl.close()
        except Exception:
            pass


class _FleetResponse:
    """Future-like over the fleet path (same ``result()`` surface as
    ServeResponse).  Routing + the replica call + the resume loop start
    EAGERLY on the fleet pool at construction — ``remote()`` fires the
    request like the plain handle path does; ``result()`` just waits —
    so submit-then-collect clients overlap and the engines see the real
    offered load."""

    _pool = None
    _pool_lock = threading.Lock()

    @classmethod
    def _ensure_pool(cls):
        from concurrent.futures import ThreadPoolExecutor
        with cls._pool_lock:
            if cls._pool is None:
                cls._pool = ThreadPoolExecutor(
                    max_workers=256, thread_name_prefix="raytpu-fleet")
        return cls._pool

    def __init__(self, fleet: Fleet, args, kwargs, model, priority):
        self._fleet = fleet
        self._args = args
        self._kwargs = kwargs
        self._model = model
        self._priority = priority
        self._fut = self._ensure_pool().submit(self._run)

    def result(self, timeout: Optional[float] = None):
        return self._fut.result(timeout)

    def _run(self):
        fleet = self._fleet
        state = fleet.state
        t0 = time.perf_counter()
        exclude: list = []
        attempts = fleet.cfg.max_resume_attempts \
            if fleet.cfg.retry_on_replica_failure else 0
        try:
            for attempt in range(attempts + 1):
                prefer = (fleet.prefix.route_hint(self._args)
                          if fleet.prefix is not None else None)
                replica = fleet.router.assign(self._model,
                                              exclude=tuple(exclude),
                                              prefer=prefer)
                fleet.note("route", replica=replica.tag,
                           model=self._model, attempt=attempt,
                           priority=self._priority)
                fleet._chaos("serve_route", replica=replica,
                             model=self._model, attempt=attempt)
                try:
                    out = fleet._call(replica, self._args, self._kwargs)
                except BaseException as e:
                    fleet.router.release(replica)
                    if not _is_replica_death(e, replica):
                        raise
                    # replica died before/while handling: mark, re-route
                    fleet.router.mark_dead(replica)
                    if fleet.prefix is not None:
                        fleet.prefix.invalidate_holder(replica.tag)
                    exclude.append(replica.tag)
                    if attempt >= attempts:
                        raise
                    kind = _resume_kind(e, replica)
                    fleet._count(kind)
                    fleet.note("resume", from_replica=replica.tag,
                               resume_kind=kind, attempt=attempt + 1)
                    continue
                if hasattr(out, "__next__"):
                    # stream: the wrapper owns release + resume +
                    # completion accounting from here on.  _FleetStream
                    # guards the closed-before-first-next() case — a
                    # closed UNSTARTED generator never runs its body,
                    # so the generator's own finally cannot be the only
                    # holder of the release
                    gen = fleet_stream(fleet, out, replica, self._args,
                                       self._kwargs, self._model,
                                       exclude, t0, state)

                    def never_started(fleet=fleet, out=out,
                                      replica=replica):
                        try:
                            out.close()   # cancel the engine request
                        except Exception:
                            pass
                        fleet.router.release(replica)
                        fleet._count("cancelled")
                    return _FleetStream(gen, never_started)
                fleet.router.release(replica)
                if fleet.prefix is not None:
                    # advertise what this replica's engines published to
                    # their local tries while serving (best-effort)
                    fleet.prefix.publish_from(replica)
                self._account(False, t0, state)
                return out
            raise ReplicaDeadError(      # pragma: no cover (loop exits)
                "no attempt succeeded")
        except BaseException:
            self._account(True, t0, state)
            raise

    def _account(self, error: bool, t0: float, state) -> None:
        self._fleet._count("errored" if error else "completed")
        if state is not None:
            try:
                state.record_request(time.perf_counter() - t0, error)
            except Exception:
                pass


class _FleetStream:
    """Iterator shim over the fleet_stream generator.  Its single job:
    a consumer that abandons the stream BEFORE the first ``next()``
    (client disconnect during response-start) closes an UNSTARTED
    generator — whose body, including the finally that releases the
    replica and cancels the engine request, never runs.  The shim
    tracks whether iteration started and runs that cleanup itself."""

    def __init__(self, gen, on_never_started):
        self._gen = gen
        self._on_never_started = on_never_started
        self._started = False
        self._closed = False

    def __iter__(self):
        return self

    def __next__(self):
        self._started = True
        return next(self._gen)

    def close(self):
        if self._closed:
            return
        self._closed = True
        started = self._started
        self._gen.close()
        if not started:
            self._on_never_started()

    def __del__(self):   # belt-and-braces: dropped without close()
        try:
            self.close()
        except Exception:
            pass


def fleet_stream(fleet: Fleet, gen: Iterator, replica, args, kwargs,
                 model, exclude: list, t0: float, state) -> Iterator:
    """Resume-capable stream wrapper.  Yields the inner chunks; when
    the serving replica dies mid-stream (EngineStoppedError out of the
    generator) the request is re-routed and REPLAYED — deterministic
    generation means the retry produces the same tokens, and chunks
    whose ``index`` precedes what was already delivered are skipped, so
    the consumer sees one seamless stream."""
    emitted = 0          # token chunks already delivered downstream
    attempts_left = (fleet.cfg.max_resume_attempts
                     if fleet.cfg.retry_on_replica_failure else 0)
    held = replica       # the replica whose ongoing count we hold
    finished = False
    try:
        while True:
            try:
                for chunk in gen:
                    if isinstance(chunk, dict):
                        idx = chunk.get("index")
                        if idx is not None and idx < emitted:
                            # resume replay: already sent — counted, so
                            # the replay COST of every resume path is a
                            # visible number, not free-looking work
                            fleet._count("replayed_tokens")
                            continue
                    fleet._chaos("serve_stream", replica=held,
                                 index=emitted)
                    yield chunk
                    if isinstance(chunk, dict) and "token" in chunk:
                        emitted += 1
                finished = True
                fleet._count("completed")
                if fleet.prefix is not None and held is not None:
                    fleet.prefix.publish_from(held)
                if state is not None:
                    state.record_request(time.perf_counter() - t0, False)
                return
            except BaseException as e:
                if held is None or not _is_replica_death(e, held):
                    raise
                dead_tag = held.tag
                kind = _resume_kind(e, held)
                fleet.router.mark_dead(held)
                if fleet.prefix is not None:
                    fleet.prefix.invalidate_holder(dead_tag)
                fleet.router.release(held)
                held = None
                exclude.append(dead_tag)
                while True:
                    if attempts_left <= 0:
                        raise
                    attempts_left -= 1
                    fleet._count(kind)
                    fleet.note("resume", from_replica=dead_tag,
                               resume_kind=kind, mid_stream=True,
                               emitted=emitted)
                    # re-route (NoReplicaError here fails the request
                    # promptly — a clean error, never a hang), replay
                    held = fleet.router.assign(model,
                                               exclude=tuple(exclude))
                    fleet.note("route", replica=held.tag, model=model,
                               resumed_at=emitted)
                    try:
                        out = fleet._call(held, args, kwargs)
                        break
                    except BaseException as e2:
                        # the REPLAY target may be dead too (cascading
                        # chaos): burn another attempt on the next
                        # replica instead of failing with spares left
                        if not _is_replica_death(e2, held):
                            raise
                        dead_tag = held.tag
                        kind = _resume_kind(e2, held)
                        fleet.router.mark_dead(held)
                        if fleet.prefix is not None:
                            fleet.prefix.invalidate_holder(dead_tag)
                        fleet.router.release(held)
                        held = None
                        exclude.append(dead_tag)
                if not hasattr(out, "__next__"):
                    raise ReplicaDeadError(
                        "resume produced a non-stream result")
                gen = out
    except BaseException as e:
        if not finished:
            if isinstance(e, GeneratorExit):
                # consumer abandonment (client disconnect), not a
                # server fault: account it as cancelled so error-rate
                # metrics don't rise on hung-up clients
                fleet._count("cancelled")
            else:
                fleet._count("errored")
                if state is not None:
                    try:
                        state.record_request(time.perf_counter() - t0,
                                             True)
                    except Exception:
                        pass
        raise
    finally:
        if held is not None:
            fleet.router.release(held)
        close = getattr(gen, "close", None)
        if close is not None:
            close()     # propagate consumer abandonment to the engine
